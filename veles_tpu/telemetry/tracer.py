"""Step-timeline tracing: where a step's wall-clock went, as spans.

The observability gap this closes: the PR-5 H2D-under-compute overlap
was *inferred* from counters (`loader_block_s` vs `device_sync_s`);
nothing showed WHERE inside one step the time sat. The `Tracer` records
host-side spans — feed pops, the async train dispatch, the in-flight
device window, the class-pass-boundary sync, Decision/snapshot
bookkeeping, the next batch's `device_put` — into a fixed-capacity ring
buffer and exports them as a Chrome-trace/Perfetto-loadable
``trace.json``, so the overlap becomes a picture: batch k+1's
``feed.device_put`` span visibly riding under step k's ``step`` span.

Design constraints (the hot-path contract):

- **Zero host-sync**: spans are host timestamps only
  (``time.perf_counter_ns``, one monotonic clock for the whole
  process); recording never touches a device value.
- **Pre-bound handle**: hot paths capture ``tracer.active()`` ONCE
  (None when tracing is off) and guard each record with a plain ``is
  not None`` check — the disabled path costs one attribute load. The
  velint ``hot-metric`` rule enforces the same discipline for metric
  records.
- **Bounded memory**: a ring buffer of `capacity` events; overflow
  overwrites the oldest and the export reports how many were dropped
  (``otherData.dropped``) instead of growing without bound on a long
  run.
- **Thread-safe**: one lock around the ring append; begin/end tokens
  carry their own timestamps so the lock is held for the append only.

Profile windows (`ProfileController`): ``--profile-window N:M``
brackets driver steps N..M (inclusive) with ``jax.profiler``
start/stop — the on-chip capture path — and ``POST /profile`` on the
web-status control plane arms a window on a LIVE run (the
tunnel-watcher's remote-capture hook). The driver calls
``controller.on_step(k)`` once per step; the disarmed path is a single
attribute check.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

#: default ring capacity (events); env-overridable for long captures
_DEFAULT_CAPACITY = int(os.environ.get("VELES_TRACE_CAPACITY",
                                       str(1 << 16)))


class Tracer:
    """Fixed-capacity span recorder with Chrome-trace export."""

    def __init__(self, capacity: int = 0) -> None:
        self.capacity = max(256, int(capacity or _DEFAULT_CAPACITY))
        #: ring slots: (name, cat, ts_us, dur_us, tid, ph)
        self._ring: List[Optional[Tuple]] = [None] * self.capacity
        self._n = 0                      # total events ever recorded
        self._lock = threading.Lock()
        #: perf_counter_ns at construction — every ts is relative to it
        self._epoch_ns = time.perf_counter_ns()
        #: wall-clock twin of the epoch, for correlating with logs
        self._epoch_unix = time.time()
        self._pid = os.getpid()

    # -- recording ------------------------------------------------------------

    def begin(self, name: str, cat: str = "host") -> Tuple:
        """Open a span; returns the token `end()` closes. No lock —
        the token carries its own start timestamp."""
        return (name, cat, time.perf_counter_ns(),
                threading.get_ident())

    def end(self, token: Tuple) -> None:
        """Close a span opened by `begin()` and append it."""
        name, cat, t0, tid = token
        t1 = time.perf_counter_ns()
        self._append((name, cat, (t0 - self._epoch_ns) / 1e3,
                      (t1 - t0) / 1e3, tid, "X"))

    def add_span(self, name: str, cat: str,
                 t0_s: float, t1_s: float) -> None:
        """Record a span from two `time.perf_counter()` readings the
        caller already took (the feed's existing block timers) —
        perf_counter and perf_counter_ns share one clock, so no second
        timestamp is paid."""
        self._append((name, cat, (t0_s * 1e9 - self._epoch_ns) / 1e3,
                      max(0.0, (t1_s - t0_s) * 1e6),
                      threading.get_ident(), "X"))

    def instant(self, name: str, cat: str = "host") -> None:
        """A zero-duration marker (Chrome-trace "i" event)."""
        self._append((name, cat,
                      (time.perf_counter_ns() - self._epoch_ns) / 1e3,
                      0.0, threading.get_ident(), "i"))

    @contextmanager
    def span(self, name: str, cat: str = "host"):
        tok = self.begin(name, cat)
        try:
            yield
        finally:
            self.end(tok)

    def _append(self, ev: Tuple) -> None:
        with self._lock:
            self._ring[self._n % self.capacity] = ev
            self._n += 1

    # -- export ---------------------------------------------------------------

    @property
    def dropped(self) -> int:
        return max(0, self._n - self.capacity)

    def events(self) -> List[Tuple]:
        """Recorded events, oldest first (ring unrolled)."""
        with self._lock:
            n = self._n
            if n <= self.capacity:
                return [e for e in self._ring[:n] if e is not None]
            head = n % self.capacity
            return [e for e in self._ring[head:] + self._ring[:head]
                    if e is not None]

    def trace_events(self) -> List[Dict[str, Any]]:
        """Chrome-trace event dicts (the `traceEvents` array)."""
        out: List[Dict[str, Any]] = []
        tids = set()
        for name, cat, ts, dur, tid, ph in self.events():
            tids.add(tid)
            ev: Dict[str, Any] = {"name": name, "cat": cat, "ph": ph,
                                  "ts": round(ts, 3),
                                  "pid": self._pid, "tid": tid}
            if ph == "X":
                ev["dur"] = round(dur, 3)
            else:
                ev["s"] = "t"           # instant scope: thread
            out.append(ev)
        # thread-name metadata so Perfetto labels the tracks
        names = {t.ident: t.name for t in threading.enumerate()}
        for tid in sorted(tids):
            out.append({"name": "thread_name", "ph": "M",
                        "pid": self._pid, "tid": tid,
                        "args": {"name": names.get(tid, f"tid-{tid}")}})
        return out

    def export(self, path: str) -> str:
        """Write the Perfetto/chrome://tracing-loadable JSON (atomic
        replace — a killed run leaves the previous file intact, not a
        torn one). Returns `path`."""
        doc = {
            "traceEvents": self.trace_events(),
            "displayTimeUnit": "ms",
            "otherData": {
                "producer": "veles_tpu.telemetry.tracer",
                "clock": "perf_counter_ns (us since epoch_unix)",
                "epoch_unix": round(self._epoch_unix, 6),
                "recorded": self._n,
                "dropped": self.dropped,
            },
        }
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path


# -- process-global tracer (the --trace flag's target) ------------------------

_ACTIVE: Optional[Tracer] = None


def install(capacity: int = 0) -> Tracer:
    """Install (and return) the process tracer. Idempotent: a second
    install returns the existing tracer so nested drivers share one
    timeline."""
    global _ACTIVE
    if _ACTIVE is None:
        _ACTIVE = Tracer(capacity)
    return _ACTIVE


def active() -> Optional[Tracer]:
    """The installed tracer, or None (tracing off). Hot paths capture
    this ONCE and None-check per record — the pre-bound-handle
    contract."""
    return _ACTIVE


def uninstall() -> Optional[Tracer]:
    """Remove and return the process tracer (tests; idempotent)."""
    global _ACTIVE
    tr, _ACTIVE = _ACTIVE, None
    return tr


@contextmanager
def span(name: str, cat: str = "host"):
    """Convenience span for COLD paths (no-op when tracing is off).
    Hot loops pre-bind `active()` instead — this helper pays a module
    lookup per call."""
    tr = _ACTIVE
    if tr is None:
        yield
        return
    tok = tr.begin(name, cat)
    try:
        yield
    finally:
        tr.end(tok)


# -- profile windows ----------------------------------------------------------

class ProfileController:
    """Bracket driver steps N..M with jax.profiler start/stop.

    Armed from the CLI (``--profile-window N:M``) or at runtime over
    HTTP (``POST /profile`` on web_status -> `request()`, which opens a
    window of K steps at the next step boundary). The driver calls
    `on_step(k)` at the top of every iteration and `finalize()` on the
    way out; the disarmed fast path is one attribute check, no lock.

    `start_fn`/`stop_fn` default to jax.profiler (imported lazily so a
    jax-free process can hold a controller); tests inject fakes.
    """

    def __init__(self, start_fn=None, stop_fn=None) -> None:
        self._lock = threading.Lock()
        self._start_fn = start_fn
        self._stop_fn = stop_fn
        #: fast-path gate: False = nothing armed, nothing running
        self._hot = False
        self._window: Optional[Tuple[int, int, str]] = None
        #: HTTP-armed request: (n_steps, out_dir) pending the next step
        self._pending: Optional[Tuple[int, str]] = None
        self._running = False
        self._running_dir = ""
        #: completed window records (observability / tests)
        self.windows: List[Dict[str, Any]] = []

    # -- arming ---------------------------------------------------------------

    @staticmethod
    def parse_spec(spec: str) -> Tuple[int, int]:
        """``"N:M"`` -> (N, M), validated. Raises ValueError."""
        lo, sep, hi = spec.partition(":")
        if not sep:
            raise ValueError(f"want N:M (got {spec!r})")
        start, stop = int(lo), int(hi)
        if start < 0 or stop < start:
            raise ValueError(
                f"want 0 <= N <= M (got {start}:{stop})")
        return start, stop

    def arm(self, start: int, stop: int, out_dir: str) -> None:
        """CLI path: capture steps `start`..`stop` inclusive."""
        with self._lock:
            self._window = (int(start), int(stop), out_dir)
            self._hot = True

    def request(self, n_steps: int, out_dir: str = "") -> Dict[str, Any]:
        """HTTP path: open a window of `n_steps` steps at the next step
        boundary of the live run. Returns the armed request (echoed to
        the client). A window already running/armed is replaced —
        last writer wins, like re-POSTing."""
        n = max(1, min(int(n_steps), 100_000))
        out = out_dir or self._default_dir()
        with self._lock:
            self._pending = (n, out)
            self._hot = True
        return {"steps": n, "dir": out}

    @staticmethod
    def _default_dir() -> str:
        return os.environ.get("VELES_PROFILE_DIR", "telemetry_profile")

    # -- driver hooks ---------------------------------------------------------

    def on_step(self, step: int) -> None:
        """Called at the top of every driver iteration with the global
        step index about to run."""
        if not self._hot:
            return
        with self._lock:
            if self._pending is not None:
                n, out = self._pending
                self._pending = None
                self._window = (step, step + n - 1, out)
            win = self._window
            if win is None:
                self._hot = self._running
                if not self._running:
                    return
            if win is not None and not self._running:
                if step > win[1]:
                    # run resumed past the window (e.g. restarted from a
                    # later snapshot): drop it rather than arm forever
                    self._window = None
                    self._hot = self._pending is not None
                elif win[0] <= step:
                    self._begin(win[2], step)
            elif self._running and win is not None and step > win[1]:
                self._finish(step - 1)
                self._window = None
                self._hot = self._pending is not None

    def finalize(self) -> None:
        """End-of-run: close a still-open window (a window whose M
        exceeds the run length still yields a capture)."""
        with self._lock:
            if self._running:
                self._finish(-1)
            self._window = None
            self._pending = None
            self._hot = False

    # -- jax.profiler plumbing (lock held by callers) -------------------------

    def _begin(self, out_dir: str, step: int) -> None:
        start = self._start_fn
        if start is None:
            import jax
            start = jax.profiler.start_trace
        try:
            os.makedirs(out_dir, exist_ok=True)
            start(out_dir)
        except Exception as e:  # noqa: BLE001 — profiling must never
            # kill training (double-start, backend without profiler...)
            self.windows.append({"error": str(e)[:200], "step": step})
            self._log().warning("profile window failed to start at "
                                "step %d: %s", step, e)
            # a start that failed once fails every step of the window
            # the same way (e.g. whole-run -p profiling already active):
            # drop the window instead of retrying per step — a 100k-step
            # HTTP window would otherwise flood the log and the windows
            # list at one entry per step
            self._window = None
            self._hot = self._pending is not None
            return
        self._running = True
        self._running_dir = out_dir
        self._t0 = time.perf_counter()
        self._step0 = step
        tr = _ACTIVE
        if tr is not None:
            tr.instant(f"profile_window.start@{step}", "profile")

    def _finish(self, step: int) -> None:
        stop = self._stop_fn
        if stop is None:
            import jax
            stop = jax.profiler.stop_trace
        try:
            stop()
        except Exception as e:  # noqa: BLE001
            self.windows.append({"error": str(e)[:200], "step": step})
            self._log().warning("profile window failed to stop at "
                                "step %d: %s", step, e)
        else:
            rec = {
                "dir": self._running_dir, "first_step": self._step0,
                "last_step": step,
                "wall_s": round(time.perf_counter() - self._t0, 6)}
            self.windows.append(rec)
            self._log().info(
                "profile window captured: steps %d..%s -> %s",
                self._step0, step if step >= 0 else "<run end>",
                self._running_dir)
            tr = _ACTIVE
            if tr is not None:
                tr.instant(f"profile_window.stop@{step}", "profile")
        self._running = False

    @staticmethod
    def _log():
        import logging
        return logging.getLogger("veles.telemetry")


_CONTROLLER: Optional[ProfileController] = None


def profile_controller() -> ProfileController:
    """The process's profile-window controller (created on first use)."""
    global _CONTROLLER
    if _CONTROLLER is None:
        _CONTROLLER = ProfileController()
    return _CONTROLLER


def reset_profile_controller() -> None:
    """Drop the process controller (tests)."""
    global _CONTROLLER
    _CONTROLLER = None
