"""Unified telemetry plane (docs/OBSERVABILITY.md).

Three coordinated layers, built once so every direction that needs
per-step cost data (quantized collectives' per-collective byte/time
attribution, the kernel search's priority order) consumes the same
producers:

- `telemetry.tracer` — step-timeline tracing: a low-overhead ring-buffer
  span recorder over the driver loop (feed pops, async dispatch, the
  in-flight device window, Decision/snapshot bookkeeping, cluster
  beats), exported as Chrome-trace/Perfetto-loadable ``trace.json``
  (CLI ``--trace PATH``); plus ``--profile-window N:M`` /
  ``POST /profile`` on-chip capture windows bracketing steps with
  ``jax.profiler``.
- `telemetry.metrics` — ONE metrics registry (counters / gauges /
  histograms) behind a Prometheus text-format ``GET /metrics`` on
  web_status, the cluster coordinator (fleet-aggregated from member
  heartbeats) and serving, with a JSONL append sink mirroring every
  flush for offline analysis next to bench records.
- wiring — the driver loop, DeviceFeed, supervisor heartbeats/exit
  reports, bench children and chaos scenarios all route through the
  one registry, so "the same number" has one producer.

Import-light on purpose: stdlib only at import time (the resilience
supervisor and cluster member — jax-free parents — use the registry
too); jax is touched only inside profile windows.
"""

from veles_tpu.telemetry import metrics, tracer  # noqa: F401

__all__ = ["metrics", "tracer"]
