"""ONE metrics registry: counters / gauges / histograms behind a
Prometheus text-format exposition and a JSONL append sink.

Before this module the fleet's numbers were disjoint artifacts — feed
counters in `loader_throughput()`, `parallel/memstats.py` snapshots,
supervisor JSON exit reports, bench records — each with its own
producer. Everything now routes through a `MetricsRegistry`:

- the driver loop (`_run_with_step`) records step counts/time, examples
  and loss through PRE-BOUND handles (`step_handles()`; the velint
  ``hot-metric`` rule bans per-record name lookups in hot paths);
- the DeviceFeed's cumulative counters are MIRRORED in
  (`mirror_feed()` — the feed's stats dict stays the one producer);
- memstats snapshots land as gauges (`mirror_mem()`);
- web_status, the cluster coordinator (fleet-aggregated from member
  heartbeats) and serving each mount ``GET /metrics`` rendering
  `exposition()`;
- every flush is mirrored to a JSONL sink (`install_jsonl()` /
  `flush_installed()`) for offline analysis next to bench records,
  with size-capped rotation.

Prometheus exposition follows the text format 0.0.4 contract the
strict-parser test enforces: ``# HELP``/``# TYPE`` per family, counter
names ending ``_total`` exposed as monotone non-negative values,
histograms with cumulative ``_bucket{le=...}`` rows ending at
``le="+Inf"`` == ``_count``, label values escaped.

Import-light on purpose (stdlib only): the resilience supervisor and
cluster member — jax-free parents — record restarts/generations here
too.
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
import time
from types import SimpleNamespace
from typing import Any, Dict, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: default step-time buckets (seconds): sub-ms TPU steps through
#: multi-second CPU smoke steps
STEP_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
#: serving latency buckets (seconds)
LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0)
#: serving ring occupancy buckets (rows per dispatched round)
RING_OCCUPANCY_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0,
                          256.0, 512.0)

#: bound on distinct label-value children per family — a scrape target
#: must stay O(1) even if a caller labels by something unbounded
_MAX_CHILDREN = 1024


def _escape(value: str) -> str:
    return (str(value).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _fmt(v: float) -> str:
    if v != v:
        return "NaN"
    if v in (math.inf, -math.inf):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Child:
    """One (label-value) instrument. Float math under the family lock
    is overkill for CPython's GIL but keeps totals exact if that ever
    changes."""

    __slots__ = ("value", "sum", "count", "bucket_counts")

    def __init__(self, n_buckets: int = 0) -> None:
        self.value = 0.0
        self.sum = 0.0
        self.count = 0
        self.bucket_counts = [0] * n_buckets


class Family:
    """A named metric family; with no labelnames the family IS its
    single child and exposes the record methods directly (the
    pre-bound-handle idiom: `h = reg.counter(...)` then `h.inc()`)."""

    def __init__(self, name: str, kind: str, help_: str,
                 labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = ()) -> None:
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
        if kind == "counter" and not name.endswith("_total"):
            raise ValueError(
                f"counter {name!r} must end in _total (prometheus "
                "naming contract the exposition test enforces)")
        self.name = name
        self.kind = kind
        self.help = help_
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(float(b) for b in buckets)
        if self.buckets != tuple(sorted(set(self.buckets))):
            raise ValueError(f"buckets must be sorted/unique: {buckets}")
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], _Child] = {}
        if not self.labelnames:
            self._default = self._children.setdefault(
                (), _Child(len(self.buckets)))

    def labels(self, **labelvalues: str) -> "_BoundChild":
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: want labels {self.labelnames}, got "
                f"{tuple(labelvalues)}")
        key = tuple(str(labelvalues[ln])[:128]
                    for ln in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if len(self._children) >= _MAX_CHILDREN:
                    # cardinality cap: fold overflow into one bucket
                    # rather than growing the scrape without bound
                    key = ("_overflow",) * len(self.labelnames)
                child = self._children.setdefault(
                    key, _Child(len(self.buckets)))
        return _BoundChild(self, child)

    # -- unlabeled record methods (proxy to the default child) ---------------

    def inc(self, amount: float = 1.0) -> None:
        _BoundChild(self, self._default).inc(amount)

    def set_total(self, total: float) -> None:
        _BoundChild(self, self._default).set_total(total)

    def set(self, value: float) -> None:
        _BoundChild(self, self._default).set(value)

    def observe(self, value: float) -> None:
        _BoundChild(self, self._default).observe(value)

    def set_histogram_totals(self, sum_: float, count: float) -> None:
        """Fleet aggregation: seed the unlabeled child's `_sum`/`_count`
        from flattened child snapshots. Bucket detail is unknown at the
        aggregator, so only the ``+Inf`` bucket (== count) carries —
        cumulative monotonicity holds (0, …, 0, count)."""
        if self.kind != "histogram":
            raise TypeError(f"{self.name} is a {self.kind}")
        with self._lock:
            self._default.sum = float(sum_)
            self._default.count = int(count)

    @property
    def value(self) -> float:
        return self._default.value

    # -- rendering ------------------------------------------------------------

    def _sample_lines(self) -> List[str]:
        out: List[str] = []
        with self._lock:
            items = sorted(self._children.items())
        for key, ch in items:
            lbl = ",".join(f'{ln}="{_escape(v)}"' for ln, v in
                           zip(self.labelnames, key))
            if self.kind == "histogram":
                cum = 0
                base = lbl + "," if lbl else ""
                for ub, n in zip(self.buckets, ch.bucket_counts):
                    cum += n
                    out.append(f'{self.name}_bucket{{{base}le='
                               f'"{_fmt(ub)}"}} {cum}')
                out.append(f'{self.name}_bucket{{{base}le="+Inf"}} '
                           f'{ch.count}')
                suffix = f"{{{lbl}}}" if lbl else ""
                out.append(f"{self.name}_sum{suffix} {_fmt(ch.sum)}")
                out.append(f"{self.name}_count{suffix} {ch.count}")
            else:
                suffix = f"{{{lbl}}}" if lbl else ""
                out.append(f"{self.name}{suffix} {_fmt(ch.value)}")
        return out

    def _snapshot_into(self, out: Dict[str, float]) -> None:
        """Flat unlabeled view for heartbeats/JSONL (labeled children
        ride the exposition only — the flat dict must stay small and
        key-stable)."""
        ch = self._children.get(())
        if ch is None:
            return
        if self.kind == "histogram":
            out[f"{self.name}_sum"] = ch.sum
            out[f"{self.name}_count"] = float(ch.count)
        else:
            out[self.name] = ch.value


class _BoundChild:
    """A (family, child) pair — the pre-bound handle hot paths hold."""

    __slots__ = ("_f", "_c")

    def __init__(self, family: Family, child: _Child) -> None:
        self._f = family
        self._c = child

    def inc(self, amount: float = 1.0) -> None:
        if self._f.kind not in ("counter", "gauge"):
            raise TypeError(f"{self._f.name} is a {self._f.kind}")
        if self._f.kind == "counter" and amount < 0:
            raise ValueError(f"counter {self._f.name} cannot decrease")
        with self._f._lock:
            self._c.value += amount

    def set_total(self, total: float) -> None:
        """Mirror an EXTERNAL cumulative accumulator (the feed's stats
        dict, a coordinator's restart count) — monotone enforced so the
        exposed counter never goes backwards mid-scrape."""
        if self._f.kind != "counter":
            raise TypeError(f"{self._f.name} is a {self._f.kind}")
        with self._f._lock:
            self._c.value = max(self._c.value, float(total))

    def set(self, value: float) -> None:
        if self._f.kind != "gauge":
            raise TypeError(f"{self._f.name} is a {self._f.kind}")
        with self._f._lock:
            self._c.value = float(value)

    def observe(self, value: float) -> None:
        if self._f.kind != "histogram":
            raise TypeError(f"{self._f.name} is a {self._f.kind}")
        v = float(value)
        with self._f._lock:
            self._c.sum += v
            self._c.count += 1
            for i, ub in enumerate(self._f.buckets):
                if v <= ub:
                    self._c.bucket_counts[i] += 1
                    break

    @property
    def value(self) -> float:
        return self._c.value


class MetricsRegistry:
    """Named families + the exposition/snapshot views over them."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: Dict[str, Family] = {}

    def _get(self, name: str, kind: str, help_: str,
             labelnames: Sequence[str],
             buckets: Sequence[float] = ()) -> Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = Family(name, kind, help_, labelnames, buckets)
                self._families[name] = fam
                return fam
        if fam.kind != kind or (tuple(labelnames) != fam.labelnames
                                and labelnames):
            raise ValueError(
                f"metric {name!r} already registered as {fam.kind}"
                f"{fam.labelnames} (got {kind}{tuple(labelnames)})")
        return fam

    def counter(self, name: str, help_: str = "",
                labelnames: Sequence[str] = ()) -> Family:
        return self._get(name, "counter", help_, labelnames)

    def gauge(self, name: str, help_: str = "",
              labelnames: Sequence[str] = ()) -> Family:
        return self._get(name, "gauge", help_, labelnames)

    def histogram(self, name: str, help_: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = STEP_BUCKETS) -> Family:
        return self._get(name, "histogram", help_, labelnames, buckets)

    def exposition(self) -> str:
        """Prometheus text format 0.0.4 (the strict-parser contract)."""
        lines: List[str] = []
        with self._lock:
            families = sorted(self._families.values(),
                              key=lambda f: f.name)
        for fam in families:
            lines.append(f"# HELP {fam.name} "
                         f"{_escape(fam.help or fam.name)}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            lines.extend(fam._sample_lines())
        return "\n".join(lines) + "\n"

    def snapshot_flat(self) -> Dict[str, float]:
        """{name: value} over unlabeled children (heartbeat payloads,
        JSONL lines); histograms flatten to `_sum`/`_count`."""
        out: Dict[str, float] = {}
        with self._lock:
            families = list(self._families.values())
        for fam in families:
            fam._snapshot_into(out)
        return out


def histogram_quantile(family: Family, q: float,
                       **labelvalues: str) -> Optional[float]:
    """Prometheus-style quantile estimate from a histogram family's
    cumulative buckets (linear interpolation inside the bucket, the
    ``histogram_quantile()`` PromQL rule) — the READ-BACK path
    tools/loadtest.py reports p50/p99 through, so a latency number in a
    record is always derivable from the scraped registry, never a
    side-channel list. None when the (labeled) child has no
    observations. The estimate's resolution is the bucket grid; the
    last bucket clamps to its upper bound (+Inf falls back to the
    highest finite bound)."""
    if family.kind != "histogram":
        raise TypeError(f"{family.name} is a {family.kind}")
    if labelvalues:
        key = tuple(str(labelvalues[ln])[:128]
                    for ln in family.labelnames)
    else:
        key = ()
    with family._lock:
        ch = family._children.get(key)
        if ch is None or ch.count == 0:
            return None
        counts = list(ch.bucket_counts)
        total = ch.count
    rank = max(0.0, min(1.0, float(q))) * total
    cum = 0
    lo = 0.0
    for ub, n in zip(family.buckets, counts):
        if cum + n >= rank and n > 0:
            frac = (rank - cum) / n
            return lo + (ub - lo) * frac
        cum += n
        lo = ub
    # rank lands in the +Inf bucket: clamp to the highest finite bound
    return family.buckets[-1] if family.buckets else None


#: exposition content type (scrape endpoints set it verbatim)
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


# -- the standard families ----------------------------------------------------

def register_standard(reg: MetricsRegistry) -> None:
    """Register the step/feed/mem/restart families every scrape
    endpoint must present (zero-valued until a producer runs) — the
    acceptance contract for web_status, the coordinator and serving."""
    reg.counter("veles_step_total", "training steps dispatched")
    reg.histogram("veles_step_seconds",
                  "driver wall time per step (dispatch to dispatch)",
                  buckets=STEP_BUCKETS)
    reg.counter("veles_examples_total",
                "valid training examples consumed")
    reg.gauge("veles_examples_per_second",
              "examples/s over the last completed epoch")
    reg.gauge("veles_loss", "last class-pass mean loss")
    reg.gauge("veles_epoch", "decision epoch counter")
    reg.counter("veles_feed_h2d_bytes_total",
                "host->device batch bytes through the DeviceFeed")
    reg.counter("veles_feed_loader_block_seconds_total",
                "driver time blocked on the host loader")
    reg.counter("veles_feed_device_sync_seconds_total",
                "driver time blocked on the device at class-pass "
                "boundaries")
    reg.counter("veles_feed_on_demand_total",
                "feed pops that had to produce synchronously (1 is the "
                "unavoidable first batch; growth = loader too slow)")
    reg.gauge("veles_mem_live_bytes", "live jax.Array bytes per device",
              labelnames=("device",))
    reg.gauge("veles_mem_live_bytes_max",
              "live jax.Array bytes on the fullest device")
    reg.counter("veles_restart_total",
                "supervised restarts (supervisor or cluster)")
    reg.gauge("veles_generation",
              "supervision generation / attempt counter")
    reg.counter("veles_collective_bytes_total",
                "modeled per-device collective egress bytes by op and "
                "link leg (dcn/ici) — the ZeRO grad_reduce exchange + "
                "param all-gather, fed per dispatched train step from "
                "FusedTrainStep.collective_accounting (byte model in "
                "docs/SCALING.md)",
                labelnames=("op", "leg"))
    reg.counter("veles_collective_seconds_total",
                "measured wall seconds inside timed collective windows "
                "(tools/ablate.py --collectives harness; the driver "
                "models bytes, never syncs for time)",
                labelnames=("op",))
    reg.gauge("veles_serving_queue_depth",
              "predict requests queued for the serving dispatch loop "
              "(ring admission / merge batcher), sampled at every "
              "enqueue and round")
    reg.histogram("veles_serving_ring_occupancy",
                  "occupied rows per dispatched serving ring round — "
                  "ring efficiency measured, not claimed (a low "
                  "occupancy under load means admission, not the "
                  "device, is the bottleneck)",
                  buckets=RING_OCCUPANCY_BUCKETS)
    reg.counter("veles_serving_swap_applied_total",
                "hot weight swaps applied to the serving ring "
                "(watcher pushes + explicit rollbacks; the blue/green "
                "pointer moved, no recompile, no drain)")
    reg.counter("veles_serving_swap_refused_total",
                "hot swaps refused by stage — the ring kept serving "
                "the current generation (reasons: fetch_failed, "
                "verify_failed, import_failed, geometry, "
                "wire_transform, device_put, equivalence, nonfinite, "
                "merge_core, no_previous)",
                labelnames=("reason",))
    reg.gauge("veles_serving_generation_age_seconds",
              "seconds the live weight generation has been serving "
              "(resets to 0 at every applied swap/rollback)")
    # fleet front door (serving_router.py) — present on every router
    # scrape even before the first beacon lands; the labelnames here
    # MUST match the router's bindings (the registry re-get contract)
    reg.counter("veles_router_requests_total",
                "client requests through the fleet router by terminal "
                "outcome (ok / shed / error / bad)",
                labelnames=("outcome",))
    reg.counter("veles_router_dispatch_total",
                "per-replica dispatch attempts by outcome (ok / fail / "
                "shed / client_error / hedge)",
                labelnames=("replica", "outcome"))
    reg.counter("veles_router_hedges_total",
                "hedged dispatches (first replica exceeded its "
                "measured p99)")
    reg.counter("veles_router_retries_total",
                "dispatch retries after a replica failure or shed")
    reg.gauge("veles_router_replicas_live",
              "replicas currently routable (status up, beacon fresh)")
    reg.gauge("veles_router_fleet_capacity",
              "summed capacity hint across routable replicas — the "
              "HPA-shaped autoscale signal (deploy/veles-serving.yaml)")
    reg.histogram("veles_router_latency_seconds",
                  "end-to-end /predict latency through the router "
                  "(includes retries and hedges)",
                  buckets=LATENCY_BUCKETS)


_DEFAULT: Optional[MetricsRegistry] = None
_DEFAULT_LOCK = threading.Lock()


def default_registry() -> MetricsRegistry:
    """The process registry (standard families pre-registered)."""
    global _DEFAULT
    if _DEFAULT is None:
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                reg = MetricsRegistry()
                register_standard(reg)
                _DEFAULT = reg
    return _DEFAULT


def reset_default_registry() -> None:
    """Drop the process registry (tests)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = None


def step_handles(reg: Optional[MetricsRegistry] = None) -> SimpleNamespace:
    """Pre-bound instruments for the driver loop — bound ONCE before
    the loop so the hot path never does a name lookup (the velint
    ``hot-metric`` contract)."""
    reg = reg or default_registry()
    return SimpleNamespace(
        steps=reg.counter("veles_step_total"),
        step_seconds=reg.histogram("veles_step_seconds"),
        examples=reg.counter("veles_examples_total"),
        examples_per_s=reg.gauge("veles_examples_per_second"),
        loss=reg.gauge("veles_loss"),
        epoch=reg.gauge("veles_epoch"),
    )


def collective_handles(acct: Optional[Dict[str, Any]],
                       reg: Optional[MetricsRegistry] = None
                       ) -> Optional[SimpleNamespace]:
    """Pre-bound veles_collective_bytes_total children + per-step byte
    amounts for one step's collective accounting dict
    (FusedTrainStep.collective_accounting()) — bound ONCE outside the
    driver loop, so the hot path pays four float adds and never a name
    or label lookup (the hot-metric contract). None when the step
    traces no registry collective."""
    if not acct:
        return None
    reg = reg or default_registry()
    fam = reg.counter("veles_collective_bytes_total",
                      labelnames=("op", "leg"))
    return SimpleNamespace(
        dcn=fam.labels(op=acct["op"], leg="dcn"),
        ici=fam.labels(op=acct["op"], leg="ici"),
        ag_dcn=fam.labels(op="param_allgather", leg="dcn"),
        ag_ici=fam.labels(op="param_allgather", leg="ici"),
        dcn_bytes=float(acct.get("dcn_bytes", 0)),
        ici_bytes=float(acct.get("ici_bytes", 0)),
        ag_dcn_bytes=float(acct.get("allgather_dcn_bytes", 0)),
        ag_ici_bytes=float(acct.get("allgather_ici_bytes", 0)),
        mark=f"{acct['op']}:{acct.get('variant', '?')}")


def mirror_feed(stats: Optional[Dict[str, Any]],
                reg: Optional[MetricsRegistry] = None) -> None:
    """Mirror the DeviceFeed's cumulative stats dict into the feed
    counters — the feed stays the ONE producer; set_total keeps the
    exposed counters monotone across feed restarts within a process."""
    if not stats:
        return
    reg = reg or default_registry()
    reg.counter("veles_feed_h2d_bytes_total").set_total(
        stats.get("bytes_h2d", 0))
    reg.counter("veles_feed_loader_block_seconds_total").set_total(
        stats.get("loader_block_s", 0.0))
    reg.counter("veles_feed_device_sync_seconds_total").set_total(
        stats.get("device_sync_s", 0.0))
    reg.counter("veles_feed_on_demand_total").set_total(
        stats.get("on_demand", 0))


def mirror_mem(mem: Optional[Dict[str, Any]],
               reg: Optional[MetricsRegistry] = None) -> None:
    """Mirror a memstats snapshot (parallel/memstats.py — the one
    accounting rule) into the mem gauges."""
    if not mem:
        return
    reg = reg or default_registry()
    per_dev = reg.gauge("veles_mem_live_bytes", labelnames=("device",))
    for dev, b in (mem.get("live_bytes") or {}).items():
        per_dev.labels(device=str(dev)).set(float(b))
    reg.gauge("veles_mem_live_bytes_max").set(
        float(mem.get("live_bytes_max", 0)))


def scrape_mem(reg: Optional[MetricsRegistry] = None) -> None:
    """Scrape-time mem refresh: sample memstats (never initializes a
    backend) into the gauges. Guarded — a scrape must never fail on
    accounting."""
    try:
        from veles_tpu.parallel.memstats import device_memory_stats
        mirror_mem(device_memory_stats(), reg)
    except Exception:  # noqa: BLE001
        pass


# -- JSONL sink ---------------------------------------------------------------

class JsonlSink:
    """Append-only JSONL mirror of registry flushes, with size-capped
    rotation: when the file exceeds `max_bytes` it is renamed to
    ``<path>.1`` (replacing any previous rotation) and a fresh file
    starts — two generations bound total disk use."""

    def __init__(self, path: str, max_bytes: int = 16 << 20) -> None:
        self.path = path
        self.max_bytes = max(4096, int(max_bytes))
        self._lock = threading.Lock()

    def write(self, obj: Dict[str, Any]) -> None:
        line = json.dumps(obj, sort_keys=True)
        with self._lock:
            try:
                if os.path.exists(self.path) \
                        and os.path.getsize(self.path) + len(line) + 1 \
                        > self.max_bytes:
                    os.replace(self.path, self.path + ".1")
                with open(self.path, "a") as f:
                    f.write(line + "\n")
            except OSError:
                pass    # a full disk must never fail the producer


_SINK: Optional[JsonlSink] = None


def install_jsonl(path: str, max_bytes: int = 0) -> JsonlSink:
    """Install the process JSONL sink (CLI --trace sidecar, env
    ``VELES_METRICS_JSONL``). Idempotent on the same path."""
    global _SINK
    if _SINK is None or _SINK.path != path:
        _SINK = JsonlSink(
            path, max_bytes or int(os.environ.get(
                "VELES_METRICS_JSONL_MAX_BYTES", str(16 << 20))))
    return _SINK


def installed_sink() -> Optional[JsonlSink]:
    return _SINK


def uninstall_jsonl() -> None:
    global _SINK
    _SINK = None


def flush_installed(extra: Optional[Dict[str, Any]] = None,
                    reg: Optional[MetricsRegistry] = None) -> None:
    """Mirror the registry's flat snapshot to the installed sink (one
    JSONL line per flush); no-op when no sink is installed."""
    sink = _SINK
    if sink is None:
        return
    row: Dict[str, Any] = {"ts": round(time.time(), 3)}
    if extra:
        row.update(extra)
    row["metrics"] = (reg or default_registry()).snapshot_flat()
    sink.write(row)


def snapshot_flat() -> Dict[str, float]:
    """The default registry's flat snapshot (heartbeat payloads)."""
    return default_registry().snapshot_flat()
