"""VideoAE — fully-connected autoencoder over video frames, the
reference's `veles/znicz/samples/VideoAE` slot (SURVEY.md §2.8 samples
row). The upstream sample learned a compact code for frames of a video
stream with an All2All encoder/decoder trained on per-frame MSE; this
build keeps that shape: frames are samples, the workflow is
All2AllTanh(code) → All2All(frame) with `loss="mse"` against the input
frame (StandardWorkflow's MSE path), so it exercises the FC-autoencoder
path that the conv autoencoder sample (`samples/autoencoder.py`) does
not.

Data note: zero-egress environment — frames come from a deterministic
synthetic "video": a 2-D Gaussian blob translating with constant
per-sequence velocity plus pixel noise (temporally coherent, learnable).
Point `root.video_ae.loader.data_path` at a `.npy` of shape (N, H, W)
to train on real frames instead.

Exposes the reference's `run(load, main)` module convention.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from veles_tpu.config import root
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.znicz.standard_workflow import StandardWorkflow

root.video_ae.loader.minibatch_size = 50
root.video_ae.loader.n_validation = 100
root.video_ae.loader.n_train = 500
root.video_ae.loader.frame_hw = 12
root.video_ae.loader.seq_len = 10
root.video_ae.loader.noise = 0.05
root.video_ae.loader.data_path = ""
root.video_ae.code_size = 32
root.video_ae.decision.max_epochs = 12
root.video_ae.decision.fail_iterations = 40
root.video_ae.gd.learning_rate = 0.03
root.video_ae.gd.gradient_moment = 0.9


def make_video(n_frames: int, hw: int, seq_len: int, noise: float,
               seed: int = 515) -> np.ndarray:
    """(n_frames, hw, hw) float32 frames: per-sequence random start +
    velocity, blob drifts across the frame (wrapping), gaussian pixel
    noise. Deterministic for a given seed."""
    rng = np.random.RandomState(seed)
    n_seq = -(-n_frames // seq_len)
    yy, xx = np.mgrid[0:hw, 0:hw].astype(np.float32)
    frames = []
    for _ in range(n_seq):
        pos = rng.uniform(0, hw, 2)
        vel = rng.uniform(-1.5, 1.5, 2)
        for _t in range(seq_len):
            cy, cx = pos % hw
            blob = np.exp(-(((yy - cy) ** 2 + (xx - cx) ** 2)
                            / (2 * (hw / 6.0) ** 2)))
            frames.append(blob + noise * rng.randn(hw, hw))
            pos = pos + vel
    return np.asarray(frames[:n_frames], np.float32)


class SyntheticVideoLoader(FullBatchLoader):
    """FullBatchLoader over synthetic video frames; targets = inputs
    (flattened) so StandardWorkflow's MSE path reconstructs the frame."""

    def __init__(self, workflow=None, frame_hw: int = 12, seq_len: int = 10,
                 n_validation: int = 100, n_train: int = 500,
                 noise: float = 0.05, data_path: str = "",
                 **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.frame_hw = frame_hw
        self.seq_len = seq_len
        self.split: Tuple[int, int, int] = (0, n_validation, n_train)
        self.noise = noise
        self.data_path = data_path

    def load_data(self) -> None:
        n = sum(self.split)
        if self.data_path:
            frames = np.load(self.data_path).astype(np.float32)[:n]
            assert frames.ndim == 3, "expected (N, H, W) frames"
        else:
            frames = make_video(n, self.frame_hw, self.seq_len, self.noise)
        flat = frames.reshape(len(frames), -1)
        self.bind_arrays(flat, flat.copy(), *self.split)


def create_workflow() -> StandardWorkflow:
    cfg = root.video_ae
    loader = SyntheticVideoLoader(
        frame_hw=cfg.loader.frame_hw, seq_len=cfg.loader.seq_len,
        n_validation=cfg.loader.n_validation, n_train=cfg.loader.n_train,
        noise=cfg.loader.noise, data_path=cfg.loader.data_path,
        minibatch_size=cfg.loader.minibatch_size)
    d = int(cfg.loader.frame_hw) ** 2
    return StandardWorkflow(
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": cfg.code_size,
             "weights_stddev": 0.1},
            {"type": "all2all", "output_sample_shape": d,
             "weights_stddev": 0.1},
        ],
        loader=loader, loss="mse",
        decision_config=cfg.decision.to_dict(),
        gd_config=cfg.gd.to_dict(),
        name="VideoAEWorkflow")


def run(load, main):
    load(create_workflow)
    main()
