"""MNIST-style fully-connected softmax workflow — config 1 of
BASELINE.json:7 and the reference's flagship first sample
(`veles/znicz/samples/MNIST`: All2AllTanh hidden layer → All2AllSoftmax,
EvaluatorSoftmax, DecisionGD, GD chain).

Data note: zero-egress environment — runs on the deterministic synthetic
MNIST-shaped dataset (veles_tpu/loader/synthetic.py) unless the config
points `root.mnist.loader.data_path` at an on-disk IDX/np dataset.

Exposes the reference's `run(load, main)` module convention consumed by the
CLI (`veles_tpu/__main__.py`).
"""

from __future__ import annotations

import numpy as np

from veles_tpu.config import root
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.loader.synthetic import SyntheticClassifierLoader
from veles_tpu.znicz.standard_workflow import StandardWorkflow

# defaults (overridable by config modules / CLI dotted args)
root.mnist.loader.minibatch_size = 100
root.mnist.loader.n_validation = 200
root.mnist.loader.n_train = 1000
root.mnist.loader.data_path = ""
root.mnist.layers = [
    {"type": "all2all_tanh", "output_sample_shape": 100,
     "weights_stddev": 0.05},
    {"type": "softmax", "output_sample_shape": 10, "weights_stddev": 0.05},
]
root.mnist.decision.max_epochs = 10
root.mnist.decision.fail_iterations = 50
root.mnist.gd.learning_rate = 0.1
root.mnist.gd.gradient_moment = 0.9
root.mnist.gd.weights_decay = 0.0


class MnistWorkflow(StandardWorkflow):
    """All2AllTanh(100) → All2AllSoftmax(10)."""


def _load_idx(path: str):
    """Minimal IDX (ubyte) reader for on-disk MNIST files."""
    import gzip
    import struct
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, = struct.unpack(">I", f.read(4))
        ndim = magic & 0xFF
        dims = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        return np.frombuffer(f.read(), np.uint8).reshape(dims)


def make_loader(cfg=None) -> FullBatchLoader:
    """Build the MNIST loader from a config node (default
    `root.mnist.loader`; `samples/mnist_simple.py` passes its own)."""
    if cfg is None:
        cfg = root.mnist.loader
    if cfg.data_path:
        data = _load_idx(f"{cfg.data_path}/train-images-idx3-ubyte.gz")
        labels = _load_idx(f"{cfg.data_path}/train-labels-idx1-ubyte.gz")
        x = (data.astype(np.float32) - 127.5) / 127.5
        n_valid = int(cfg.n_validation)
        n_train = len(x) - n_valid
        loader = FullBatchLoader(minibatch_size=cfg.minibatch_size)
        loader.load_data = lambda: loader.bind_arrays(  # type: ignore
            x, labels.astype(np.int64), 0, n_valid, n_train)
        return loader
    return SyntheticClassifierLoader(
        n_classes=10, sample_shape=(28, 28),
        n_validation=cfg.n_validation, n_train=cfg.n_train,
        minibatch_size=cfg.minibatch_size)


def create_workflow() -> MnistWorkflow:
    return MnistWorkflow(
        layers=root.mnist.layers,
        loader=make_loader(),
        loss="softmax", n_classes=10,
        decision_config=root.mnist.decision.to_dict(),
        gd_config=root.mnist.gd.to_dict(),
        name="MnistWorkflow")


def run(load, main):
    """Reference module convention: `load` builds the workflow (or restores
    a snapshot), `main` initializes + runs it."""
    load(create_workflow)
    main()
