"""Sample workflows (parity: reference `veles/znicz/samples/` — each sample
is a workflow module + a config module mutating the global `root`, run via
the CLI: `python -m veles_tpu <workflow.py> <config.py> [root.x=y ...]`)."""
