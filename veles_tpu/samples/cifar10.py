"""CIFAR-10-style convolutional workflow — config 2 of BASELINE.json:7.

Parity: reference `veles/znicz/samples/CIFAR10` — conv/pooling/LRN tower
with fully-connected softmax head, built declaratively through
StandardWorkflow (SURVEY.md §2.8). Exposes `run(load, main)`.

Data note: zero-egress environment — runs on the synthetic CIFAR-shaped
dataset unless `root.cifar.loader.data_path` points at an on-disk
`cifar-10-batches-py` directory (the standard pickled batches).
"""

from __future__ import annotations

import os
import pickle

import numpy as np

from veles_tpu.config import root
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.loader.synthetic import SyntheticClassifierLoader
from veles_tpu.znicz import conv, normalization, pooling  # noqa: F401
from veles_tpu.znicz.standard_workflow import StandardWorkflow

root.cifar.loader.minibatch_size = 100
root.cifar.loader.n_validation = 400
root.cifar.loader.n_train = 2000
root.cifar.loader.data_path = ""
root.cifar.layers = [
    {"type": "conv_strictrelu", "n_kernels": 32, "kx": 5, "ky": 5,
     "padding": (2, 2), "weights_stddev": 0.05},
    {"type": "max_pooling", "ksize": (2, 2)},
    {"type": "lrn"},
    {"type": "conv_strictrelu", "n_kernels": 32, "kx": 5, "ky": 5,
     "padding": (2, 2), "weights_stddev": 0.05},
    {"type": "avg_pooling", "ksize": (2, 2)},
    {"type": "all2all_strictrelu", "output_sample_shape": 64,
     "weights_stddev": 0.05},
    {"type": "softmax", "output_sample_shape": 10, "weights_stddev": 0.05},
]
root.cifar.decision.max_epochs = 10
root.cifar.decision.fail_iterations = 50
root.cifar.gd.learning_rate = 0.05
root.cifar.gd.gradient_moment = 0.9
root.cifar.gd.weights_decay = 0.0004


class Cifar10Workflow(StandardWorkflow):
    """conv→pool→LRN→conv→pool→fc→softmax (the reference CIFAR geometry)."""


def _load_cifar_batches(path: str):
    xs, ys = [], []
    for name in sorted(os.listdir(path)):
        if not name.startswith("data_batch"):
            continue
        with open(os.path.join(path, name), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        xs.append(np.asarray(d[b"data"], np.uint8))
        ys.append(np.asarray(d[b"labels"], np.int64))
    x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return (x.astype(np.float32) - 127.5) / 127.5, np.concatenate(ys)


def make_loader() -> FullBatchLoader:
    cfg = root.cifar.loader
    if cfg.data_path:
        x, y = _load_cifar_batches(cfg.data_path)
        n_valid = int(cfg.n_validation)
        loader = FullBatchLoader(minibatch_size=cfg.minibatch_size)
        loader.load_data = lambda: loader.bind_arrays(  # type: ignore
            x, y, 0, n_valid, len(x) - n_valid)
        return loader
    return SyntheticClassifierLoader(
        n_classes=10, sample_shape=(32, 32, 3),
        n_validation=cfg.n_validation, n_train=cfg.n_train,
        minibatch_size=cfg.minibatch_size, noise=0.4)


def create_workflow() -> Cifar10Workflow:
    return Cifar10Workflow(
        layers=root.cifar.layers,
        loader=make_loader(), loss="softmax", n_classes=10,
        decision_config=root.cifar.decision.to_dict(),
        gd_config=root.cifar.gd.to_dict(),
        name="Cifar10Workflow")


def run(load, main):
    load(create_workflow)
    main()
