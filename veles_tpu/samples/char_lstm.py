"""Character-LSTM language-model workflow — config 5 of BASELINE.json:10
("Character-LSTM text workflow, sequence batching on TPU").

Parity: the reference's char-RNN sample (host-unrolled all2all graph);
here the recurrence is one `lax.scan` inside jit (znicz/lstm.py) and the
per-timestep projection + CE ride the standard All2AllSoftmax/Evaluator
stack over flattened (N·T) predictions. Exposes `run(load, main)`.
"""

from __future__ import annotations

from veles_tpu.config import root
from veles_tpu.loader.text import CharSequenceLoader, synthetic_text
from veles_tpu.znicz import lstm  # noqa: F401 (registers the layer type)
from veles_tpu.znicz.standard_workflow import StandardWorkflow

root.char_lstm.loader.minibatch_size = 32
root.char_lstm.loader.seq_len = 32
root.char_lstm.loader.n_validation = 40
root.char_lstm.n_units = 64
root.char_lstm.decision.max_epochs = 5
root.char_lstm.decision.fail_iterations = 20
root.char_lstm.gd.learning_rate = 0.05
root.char_lstm.gd.gradient_moment = 0.9


class CharLSTMWorkflow(StandardWorkflow):
    """LSTM(H) → All2AllSoftmax(V) over flattened timesteps."""


def create_workflow(text: str = None) -> CharLSTMWorkflow:
    cfg = root.char_lstm
    loader = CharSequenceLoader(
        text=text, seq_len=cfg.loader.seq_len,
        n_validation=cfg.loader.n_validation,
        minibatch_size=cfg.loader.minibatch_size)
    return CharLSTMWorkflow(
        layers=[
            {"type": "lstm", "n_units": cfg.n_units},
            {"type": "softmax", "output_sample_shape": loader.n_vocab,
             "weights_stddev": 0.05},
        ],
        loader=loader, loss="softmax", n_classes=loader.n_vocab,
        decision_config=cfg.decision.to_dict(),
        gd_config=cfg.gd.to_dict(),
        name="CharLSTMWorkflow")


def run(load, main):
    load(create_workflow)
    main()
