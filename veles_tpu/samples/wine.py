"""Wine tabular-classification workflow.

Parity: reference `veles/znicz/samples/Wine` (SURVEY.md §2.8) — the
smallest sample: a single softmax layer over the 13-feature UCI wine
dataset, the reference's "hello world" after MNIST. Reads the classic
`wine.data` CSV when `root.wine.loader.data_path` points at it; otherwise
a synthetic 13-feature stand-in (zero-egress default). Exposes
`run(load, main)`.
"""

from __future__ import annotations

import numpy as np

from veles_tpu.config import root
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.loader.synthetic import SyntheticClassifierLoader
from veles_tpu.znicz.standard_workflow import StandardWorkflow

root.wine.loader.minibatch_size = 30
root.wine.loader.n_validation = 40
root.wine.loader.n_train = 138
root.wine.loader.data_path = ""
root.wine.layers = [
    {"type": "softmax", "output_sample_shape": 3, "weights_stddev": 0.05},
]
root.wine.decision.max_epochs = 50
root.wine.decision.fail_iterations = 50
root.wine.gd.learning_rate = 0.3
root.wine.gd.gradient_moment = 0.9


class WineWorkflow(StandardWorkflow):
    """13 features → softmax(3)."""


def make_loader() -> FullBatchLoader:
    cfg = root.wine.loader
    if cfg.data_path:
        raw = np.loadtxt(cfg.data_path, delimiter=",")
        labels = raw[:, 0].astype(np.int64) - 1   # classes are 1..3
        x = raw[:, 1:].astype(np.float32)
        x = (x - x.mean(0)) / x.std(0)            # standardize features
        n_valid = int(cfg.n_validation)
        from veles_tpu import prng
        perm = prng.get("wine_split").permutation(len(x))
        x, labels = x[perm], labels[perm]
        loader = FullBatchLoader(minibatch_size=cfg.minibatch_size)
        loader.load_data = lambda: loader.bind_arrays(  # type: ignore
            x, labels, 0, n_valid, len(x) - n_valid)
        return loader
    return SyntheticClassifierLoader(
        n_classes=3, sample_shape=(13,),
        n_validation=cfg.n_validation, n_train=cfg.n_train,
        minibatch_size=cfg.minibatch_size, noise=0.8)


def create_workflow() -> WineWorkflow:
    return WineWorkflow(
        layers=root.wine.layers, loader=make_loader(),
        loss="softmax", n_classes=3,
        decision_config=root.wine.decision.to_dict(),
        gd_config=root.wine.gd.to_dict(), name="WineWorkflow")


def run(load, main):
    load(create_workflow)
    main()
