"""MnistSimple — the reference's minimal one-matmul MNIST sample
(`veles/znicz/samples/MnistSimple`, SURVEY.md §2.8 samples row): a single
All2AllSoftmax layer straight from pixels to class logits. It exists as
the smallest possible StandardWorkflow — the "hello world" a reference
user reaches for before the two-layer `samples/mnist.py`.

Data note: zero-egress environment — trains on the deterministic
synthetic MNIST-shaped dataset unless `root.mnist_simple.loader.data_path`
points at on-disk IDX files (same contract as `samples/mnist.py`).

Exposes the reference's `run(load, main)` module convention.
"""

from __future__ import annotations

from veles_tpu.config import root
from veles_tpu.znicz.standard_workflow import StandardWorkflow

root.mnist_simple.loader.minibatch_size = 100
root.mnist_simple.loader.n_validation = 200
root.mnist_simple.loader.n_train = 1000
root.mnist_simple.loader.data_path = ""
root.mnist_simple.layers = [
    {"type": "softmax", "output_sample_shape": 10, "weights_stddev": 0.05},
]
root.mnist_simple.decision.max_epochs = 5
root.mnist_simple.decision.fail_iterations = 25
root.mnist_simple.gd.learning_rate = 0.1
root.mnist_simple.gd.gradient_moment = 0.9


class MnistSimpleWorkflow(StandardWorkflow):
    """All2AllSoftmax(10) — logistic regression on pixels."""


def create_workflow() -> MnistSimpleWorkflow:
    # share samples/mnist.py's loader factory (incl. the on-disk IDX
    # path) but read this sample's config subtree
    from veles_tpu.samples import mnist

    cfg = root.mnist_simple
    loader = mnist.make_loader(cfg.loader)
    return MnistSimpleWorkflow(
        layers=cfg.layers,
        loader=loader,
        loss="softmax", n_classes=10,
        decision_config=cfg.decision.to_dict(),
        gd_config=cfg.gd.to_dict(),
        name="MnistSimpleWorkflow")


def run(load, main):
    load(create_workflow)
    main()
