"""Mixture-of-experts classifier sample (expert parallelism).

No reference analog (SURVEY.md §2.4: EP absent from the 2015 codebase) —
this sample exists so the EP axis is exercised end-to-end through the
same `run(load, main)` convention as every reference-parity sample: a
switch-style top-1 MoE FFN between two dense layers, trainable either
dense-local (granular, or fused via CLI `--fused`) or expert-parallel
over the mesh data axis — programmatically via
`run_fused(mesh=..., mode="dp", ep=True)` or
`build_fused_step(mesh=..., mode="dp", ep=True)` on a multi-device host
(the CLI `--fused` path is single-process dense-local).

Data note: zero-egress environment — synthetic classifier dataset by
default (veles_tpu/loader/synthetic.py).
"""

from __future__ import annotations

from veles_tpu.config import root
from veles_tpu.loader.synthetic import SyntheticClassifierLoader
from veles_tpu.znicz import moe  # noqa: F401 (registers the "moe" type)
from veles_tpu.znicz.standard_workflow import StandardWorkflow

root.moe.loader.minibatch_size = 64
root.moe.loader.n_validation = 256
root.moe.loader.n_train = 1024
root.moe.loader.n_classes = 8
root.moe.layers = [
    {"type": "all2all_tanh", "output_sample_shape": 64,
     "weights_stddev": 0.1},
    {"type": "moe", "n_experts": 8, "hidden": 128,
     "capacity_factor": 2.0, "weights_stddev": 0.1},
    {"type": "softmax", "output_sample_shape": 8, "weights_stddev": 0.05},
]
root.moe.decision.max_epochs = 8
root.moe.decision.fail_iterations = 50
root.moe.gd.learning_rate = 0.05
root.moe.gd.gradient_moment = 0.9

#: GA-searchable hyperparameters (CLI --optimize)
TUNABLES = {
    "root.moe.gd.learning_rate": (0.005, 0.3),
    "root.moe.gd.gradient_moment": (0.0, 0.95),
}


class MoEWorkflow(StandardWorkflow):
    """All2AllTanh(64) -> MoE(8 experts, hidden 128) -> Softmax(8)."""


def create_workflow() -> MoEWorkflow:
    cfg = root.moe.loader
    loader = SyntheticClassifierLoader(
        n_classes=cfg.n_classes, sample_shape=(32,),
        n_validation=cfg.n_validation, n_train=cfg.n_train,
        minibatch_size=cfg.minibatch_size, noise=0.4)
    return MoEWorkflow(
        layers=root.moe.layers,
        loader=loader, loss="softmax", n_classes=cfg.n_classes,
        decision_config=root.moe.decision.to_dict(),
        gd_config=root.moe.gd.to_dict(),
        name="MoEWorkflow")


def run(load, main):
    load(create_workflow)
    main()
