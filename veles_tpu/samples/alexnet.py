"""ImageNet AlexNet workflow — config 3 of BASELINE.json:7, the primary
benchmark config (north star: samples/sec/chip + all-reduce scaling).

Parity: the reference's znicz imagenet workflow (`veles/znicz/samples/`
AlexNet dirs): 5 conv blocks with LRN + overlapping max-pooling, two
4096-wide fully-connected layers with dropout, 1000-way softmax —
Krizhevsky et al. 2012 geometry expressed as a declarative layer list.

TPU-first: NHWC layouts; training runs through the fused sharded step
(`run_fused` / FusedTrainStep), bf16 compute on the MXU with f32 master
weights; data-parallel gradient all-reduce over the mesh "data" axis, and
optional tensor parallelism over "model" for the wide FC layers.

Data note: zero-egress environment — trains on the deterministic synthetic
ImageNet-shaped dataset (loader/synthetic.py) by default; set
`root.alexnet.loader.data_path` to a class-per-directory image tree and
create_workflow builds a prefetching ImageDirectoryLoader instead.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from veles_tpu.config import root
from veles_tpu.loader.synthetic import SyntheticClassifierLoader
from veles_tpu.znicz.standard_workflow import StandardWorkflow

root.alexnet.loader.minibatch_size = 128
root.alexnet.loader.n_validation = 128
root.alexnet.loader.n_train = 512
root.alexnet.loader.input_hw = 227
root.alexnet.loader.data_path = ""
root.alexnet.n_classes = 1000
root.alexnet.decision.max_epochs = 10
root.alexnet.decision.fail_iterations = 10
root.alexnet.gd.learning_rate = 0.01
root.alexnet.gd.gradient_moment = 0.9
root.alexnet.gd.weights_decay = 0.0005


def alexnet_layers(n_classes: int = 1000, width_mult: float = 1.0,
                   fc_width: int = 4096,
                   init: str = "reference") -> List[Dict[str, Any]]:
    """The Krizhevsky-2012 layer list (single-tower). `width_mult`/
    `fc_width` scale the net down for tiny-shape dry runs and tests.

    init="reference": the faithful fixed stddevs (0.01 conv / 0.005 fc,
    drawn with the unit's default uniform filling at matched std) —
    correct for the full 90-epoch recipe, but they VANISH at reduced
    width (activation std shrinks ~5x per layer; measured in
    tests/test_alexnet_functional.py's history). init="scaled": Kaiming
    √(2/fan_in) for the convs (fan-ins are static here) and the LeCun
    fan-in default for the FC tail (fan-in depends on input_hw, so it is
    left to init_params) — use for any width_mult < 1 run that must
    actually learn."""
    if init not in ("reference", "scaled"):
        raise ValueError(f"unknown init {init!r}")
    w = lambda n: max(int(n * width_mult), 1)  # noqa: E731

    def conv_std(kx: int, cin: int, ref: float) -> Optional[float]:
        if init == "reference":
            return ref
        return float(np.sqrt(2.0 / (kx * kx * cin)))

    fc_std = 0.005 if init == "reference" else None
    head_std = 0.01 if init == "reference" else None
    return [
        {"type": "conv_strictrelu", "n_kernels": w(96), "kx": 11, "ky": 11,
         "stride": (4, 4), "padding": (0, 0),
         "weights_stddev": conv_std(11, 3, 0.01)},
        {"type": "norm", "k": 2.0, "alpha": 1e-4, "beta": 0.75, "n": 5},
        {"type": "max_pooling", "ksize": (3, 3), "stride": (2, 2)},
        {"type": "conv_strictrelu", "n_kernels": w(256), "kx": 5, "ky": 5,
         "stride": (1, 1), "padding": (2, 2),
         "weights_stddev": conv_std(5, w(96), 0.01)},
        {"type": "norm", "k": 2.0, "alpha": 1e-4, "beta": 0.75, "n": 5},
        {"type": "max_pooling", "ksize": (3, 3), "stride": (2, 2)},
        {"type": "conv_strictrelu", "n_kernels": w(384), "kx": 3, "ky": 3,
         "stride": (1, 1), "padding": (1, 1),
         "weights_stddev": conv_std(3, w(256), 0.01)},
        {"type": "conv_strictrelu", "n_kernels": w(384), "kx": 3, "ky": 3,
         "stride": (1, 1), "padding": (1, 1),
         "weights_stddev": conv_std(3, w(384), 0.01)},
        {"type": "conv_strictrelu", "n_kernels": w(256), "kx": 3, "ky": 3,
         "stride": (1, 1), "padding": (1, 1),
         "weights_stddev": conv_std(3, w(384), 0.01)},
        {"type": "max_pooling", "ksize": (3, 3), "stride": (2, 2)},
        {"type": "all2all_strictrelu", "output_sample_shape": fc_width,
         "weights_stddev": fc_std},
        {"type": "dropout", "dropout_ratio": 0.5},
        {"type": "all2all_strictrelu", "output_sample_shape": fc_width,
         "weights_stddev": fc_std},
        {"type": "dropout", "dropout_ratio": 0.5},
        {"type": "softmax", "output_sample_shape": n_classes,
         "weights_stddev": head_std},
    ]


class AlexNetWorkflow(StandardWorkflow):
    """loader → 5 conv blocks → FC 4096×2 (dropout) → softmax 1000."""


def create_workflow(minibatch_size: Optional[int] = None,
                    input_hw: Optional[int] = None,
                    n_classes: Optional[int] = None,
                    width_mult: float = 1.0, fc_width: int = 4096,
                    n_train: Optional[int] = None,
                    n_validation: Optional[int] = None,
                    init: str = "reference") -> AlexNetWorkflow:
    cfg = root.alexnet
    mb = minibatch_size or cfg.loader.minibatch_size
    hw = input_hw or cfg.loader.input_hw
    nc = n_classes or cfg.n_classes
    if cfg.loader.get("data_path"):
        import os
        path = cfg.loader.data_path
        if os.path.exists(os.path.join(path, "manifest.json")):
            # packed memmap format (loader/memmap.py): the ImageNet-scale
            # path — pack once with pack_image_dataset, train many times
            from veles_tpu.loader.memmap import MemmapImageLoader
            loader = MemmapImageLoader(data_path=path, minibatch_size=mb)
        else:
            from veles_tpu.loader.image import ImageDirectoryLoader
            loader = ImageDirectoryLoader(
                data_path=path, size_hw=(hw, hw),
                n_validation=(n_validation if n_validation is not None
                              else cfg.loader.n_validation),
                minibatch_size=mb)
    else:
        loader = SyntheticClassifierLoader(
            n_classes=min(nc, 64),  # prototype count, not the head width
            sample_shape=(hw, hw, 3),
            n_validation=(n_validation if n_validation is not None
                          else cfg.loader.n_validation),
            n_train=n_train if n_train is not None else cfg.loader.n_train,
            minibatch_size=mb, noise=0.5)
    return AlexNetWorkflow(
        layers=alexnet_layers(nc, width_mult, fc_width, init=init),
        loader=loader, loss="softmax", n_classes=nc,
        decision_config=cfg.decision.to_dict(),
        gd_config=cfg.gd.to_dict(),
        name="AlexNetWorkflow")


def run(load, main):
    load(create_workflow)
    main()
