"""Convolutional autoencoder workflow — the AE half of config 4 in
BASELINE.json:9, with optional RBM pretraining.

Parity: reference autoencoder samples (`veles/znicz/samples/ImagenetAE`-
style, SURVEY.md §2.8 "Autoencoder units"): Conv → MaxPooling encoder,
Depooling → Deconv decoder (depooling routed by the encoder's recorded
max offsets), EvaluatorMSE against the INPUT, epoch-driven decision, GD
chain through the decoder and encoder. Exposes `run(load, main)`.
"""

from __future__ import annotations

from veles_tpu.config import root
from veles_tpu.loader.synthetic import SyntheticClassifierLoader
from veles_tpu.units import Unit
from veles_tpu.workflow import Repeater, Workflow
from veles_tpu.znicz.conv import Conv
from veles_tpu.znicz.cutter import Cutter  # noqa: F401 (registers gd)
from veles_tpu.znicz.deconv import Deconv
from veles_tpu.znicz.decision import DecisionGD
from veles_tpu.znicz.depooling import Depooling
from veles_tpu.znicz.evaluator import EvaluatorMSE
from veles_tpu.znicz.gd_conv import GradientDescentConv
from veles_tpu.znicz.gd_deconv import GDDeconv
from veles_tpu.znicz.gd_pooling import GDMaxPooling
from veles_tpu.znicz.nn_units import gd_for
from veles_tpu.znicz.pooling import MaxPooling

root.ae.loader.minibatch_size = 50
root.ae.loader.n_train = 400
root.ae.loader.n_validation = 100
root.ae.n_kernels = 8
root.ae.decision.max_epochs = 5
root.ae.gd.learning_rate = 0.002
root.ae.gd.gradient_moment = 0.9


class AEWorkflow(Workflow):
    """conv → maxpool → depool → deconv, MSE against the input."""

    def __init__(self, workflow=None, n_kernels: int = 8,
                 decision_config=None, gd_config=None, loader=None,
                 **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        assert loader is not None
        self.repeater = Repeater(self, name="repeater")
        self.loader = loader
        if loader.workflow is not self:
            self.add_unit(loader)
            loader.workflow = self

        # -- encoder ---------------------------------------------------------
        self.conv = Conv(self, n_kernels=n_kernels, kx=3, ky=3,
                         padding=(1, 1), weights_stddev=0.05)
        self.conv.link_attrs(self.loader, ("input", "minibatch_data"))
        self.pool = MaxPooling(self, ksize=(2, 2))
        self.pool.link_attrs(self.conv, ("input", "output"))

        # -- decoder (untied weights; reference supports both) ---------------
        self.depool = Depooling(self).link_pool(self.pool)
        self.depool.link_attrs(self.pool, ("input", "output"))
        self.deconv = Deconv(self, n_kernels=n_kernels, kx=3, ky=3,
                             padding=(1, 1), n_channels=1,
                             weights_stddev=0.05)
        self.deconv.link_attrs(self.depool, ("input", "output"))

        # -- evaluator: reconstruct the INPUT --------------------------------
        self.evaluator = EvaluatorMSE(self)
        self.evaluator.link_attrs(self.deconv, ("input", "output"))
        self.evaluator.link_attrs(self.loader, ("target", "minibatch_data"))

        self.decision = DecisionGD(self, **(decision_config or {}))
        self.decision.link_attrs(self.loader, "minibatch_class",
                                 "last_minibatch", "class_lengths")
        self.decision.link_attrs(self.evaluator, ("n_err", "loss"), "loss")

        # -- gradient chain (reverse of forward order) ------------------------
        gd_kw = gd_config or {}
        self.gd_deconv = GDDeconv(self, **gd_kw).link_forward(self.deconv)
        self.gd_deconv.link_attrs(self.evaluator, "err_output")
        self.gd_depool = gd_for(Depooling)(self, **gd_kw)
        self.gd_depool.link_forward(self.depool)
        self.gd_depool.link_attrs(self.gd_deconv, ("err_output", "err_input"))
        self.gd_pool = GDMaxPooling(self, **gd_kw).link_forward(self.pool)
        self.gd_pool.link_attrs(self.gd_depool, ("err_output", "err_input"))
        self.gd_conv = GradientDescentConv(self, **gd_kw)
        self.gd_conv.link_forward(self.conv)
        self.gd_conv.link_attrs(self.gd_pool, ("err_output", "err_input"))
        self.gds = [self.gd_deconv, self.gd_depool, self.gd_pool,
                    self.gd_conv]

        # -- control ----------------------------------------------------------
        self.repeater.link_from(self.start_point)
        self.loader.link_from(self.repeater)
        self.conv.link_from(self.loader)
        self.pool.link_from(self.conv)
        self.depool.link_from(self.pool)
        self.deconv.link_from(self.depool)
        self.evaluator.link_from(self.deconv)
        self.decision.link_from(self.evaluator)
        prev: Unit = self.decision
        for g in self.gds:
            g.link_from(prev)
            prev = g
        self.repeater.link_from(prev)
        self.end_point.link_from(self.decision)
        self._wire_gates()

    def _wire_gates(self) -> None:
        for g in self.gds:
            g.gate_skip = self.loader.not_train | self.decision.complete
        self.end_point.gate_block = ~self.decision.complete
        self.repeater.gate_block = self.decision.complete

    def initialize(self, device=None, **kwargs) -> None:
        self._wire_gates()
        super().initialize(device=device, **kwargs)


def create_workflow() -> AEWorkflow:
    cfg = root.ae
    loader = SyntheticClassifierLoader(
        n_classes=4, sample_shape=(8, 8, 1), autoencoder=True,
        n_validation=cfg.loader.n_validation, n_train=cfg.loader.n_train,
        minibatch_size=cfg.loader.minibatch_size, noise=0.2)
    return AEWorkflow(n_kernels=cfg.n_kernels,
                      decision_config=cfg.decision.to_dict(),
                      gd_config=cfg.gd.to_dict(),
                      loader=loader, name="AEWorkflow")


def run(load, main):
    load(create_workflow)
    main()
