"""Kohonen SOM workflow — the unsupervised half of config 4 in
BASELINE.json:9 ("Autoencoder + Kohonen SOM unsupervised workflows").

Parity: reference `veles/znicz/samples/Kohonen` — loader → KohonenTrainer
(neighborhood-decay update) with a KohonenForward computing winners/hits,
epoch-count stopping. Exposes the `run(load, main)` CLI convention.
"""

from __future__ import annotations

from veles_tpu.config import root
from veles_tpu.loader.synthetic import SyntheticClassifierLoader
from veles_tpu.units import Unit
from veles_tpu.workflow import Repeater, Workflow
from veles_tpu.znicz.decision import DecisionEpochs
from veles_tpu.znicz.kohonen import KohonenForward, KohonenTrainer

root.kohonen.loader.minibatch_size = 50
root.kohonen.loader.n_train = 500
root.kohonen.shape = (6, 6)
root.kohonen.max_epochs = 10
root.kohonen.learning_rate = 0.5
root.kohonen.plot = False


class KohonenWorkflow(Workflow):
    """repeater → loader → trainer → forward(winners) → decision → loop,
    with the reference's KohonenHits activation map rendered per epoch
    when `plot=True`."""

    def __init__(self, workflow=None, shape=(6, 6), max_epochs: int = 10,
                 learning_rate: float = 0.5, loader=None, plot: bool = False,
                 **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        assert loader is not None
        self.repeater = Repeater(self, name="repeater")
        self.loader = loader
        if loader.workflow is not self:
            self.add_unit(loader)
            loader.workflow = self

        self.trainer = KohonenTrainer(self, shape=shape,
                                      learning_rate=learning_rate)
        self.trainer.link_attrs(self.loader, ("input", "minibatch_data"))
        self.forward = KohonenForward(self, shape=shape)
        self.forward.link_attrs(self.loader, ("input", "minibatch_data"))
        self.forward.link_attrs(self.trainer, "weights")

        self.decision = DecisionEpochs(self, max_epochs=max_epochs)
        self.decision.link_attrs(self.loader, "minibatch_class",
                                 "last_minibatch", "class_lengths")
        self.trainer.link_decision(self.decision)

        self.plotter = None
        if plot:
            from veles_tpu.plotting_units import KohonenHits
            self.plotter = KohonenHits(self, shape=shape)
            self.plotter.link_attrs(self.forward, ("input", "hits"))

        self.repeater.link_from(self.start_point)
        self.loader.link_from(self.repeater)
        self.trainer.link_from(self.loader)
        self.forward.link_from(self.trainer)
        self.decision.link_from(self.forward)
        if self.plotter is not None:
            self.plotter.link_from(self.decision)
        self.repeater.link_from(self.decision)
        self.end_point.link_from(self.decision)
        self._wire_gates()

    def _wire_gates(self) -> None:
        self.end_point.gate_block = ~self.decision.complete
        self.repeater.gate_block = self.decision.complete
        if self.plotter is not None:
            # once per epoch, like the reference's SOM-hits rendering
            self.plotter.gate_skip = ~self.loader.epoch_ended

    def initialize(self, device=None, **kwargs) -> None:
        self._wire_gates()
        super().initialize(device=device, **kwargs)


def create_workflow() -> KohonenWorkflow:
    cfg = root.kohonen
    loader = SyntheticClassifierLoader(
        n_classes=cfg.shape[0] * cfg.shape[1] // 4 or 4,
        sample_shape=(8,), n_validation=0, n_train=cfg.loader.n_train,
        minibatch_size=cfg.loader.minibatch_size, noise=0.15)
    return KohonenWorkflow(shape=tuple(cfg.shape),
                           max_epochs=cfg.max_epochs,
                           learning_rate=cfg.learning_rate,
                           plot=bool(cfg.plot),
                           loader=loader, name="KohonenWorkflow")


def run(load, main):
    load(create_workflow)
    main()
