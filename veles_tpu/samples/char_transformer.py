"""Character-transformer language model — the long-context training
config (sequence parallelism exercised end-to-end).

No reference analog (SURVEY.md §5.7: the 2015 codebase has no attention);
this sample exists because long-context/distributed support is
first-class in the TPU build: the same workflow trains locally, or with
the sequence dim sharded over a mesh "seq" axis (ring/Ulysses attention,
FusedTrainStep "seq" mode) — `root.char_transformer.parallel_mode`
selects the kernel. Exposes `run(load, main)`.

Geometry: one-hot chars -> SeqLinear embed (+learned positions) ->
causal MultiHeadAttention (residual) -> SeqFFN (residual) -> per-token
SeqSoftmax(V).
"""

from __future__ import annotations

from veles_tpu.config import root
from veles_tpu.loader.text import CharSequenceLoader
from veles_tpu.znicz import attention  # noqa: F401 (registers layer type)
from veles_tpu.znicz import transformer  # noqa: F401 (registers types)
from veles_tpu.znicz.standard_workflow import StandardWorkflow

root.char_transformer.loader.minibatch_size = 32
root.char_transformer.loader.seq_len = 32
root.char_transformer.loader.n_validation = 40
root.char_transformer.embed = 64
root.char_transformer.n_heads = 4
root.char_transformer.ffn = 128
root.char_transformer.parallel_mode = "local"  # | "ring" | "ulysses"
#: 0 = dense SeqFFN; N = replace it with an N-expert token-routed MoE
#: (composes with parallel_mode: per-token routing is shard-local under
#: the seq axis, identical to global routing at ample capacity)
root.char_transformer.moe_experts = 0
#: per-expert slot budget (capacity = factor x tokens / experts). 2.0 is
#: the standard conditional-compute setting; raise to n_experts for
#: zero-drop exact-equivalence runs (the SP x MoE test does)
root.char_transformer.moe_capacity_factor = 2.0
root.char_transformer.decision.max_epochs = 5
root.char_transformer.decision.fail_iterations = 20
root.char_transformer.gd.learning_rate = 0.2
root.char_transformer.gd.gradient_moment = 0.9


class CharTransformerWorkflow(StandardWorkflow):
    """embed → causal attention → FFN → per-token softmax(V)."""


def create_workflow(text: str = None) -> CharTransformerWorkflow:
    cfg = root.char_transformer
    loader = CharSequenceLoader(
        text=text, seq_len=cfg.loader.seq_len,
        n_validation=cfg.loader.n_validation,
        minibatch_size=cfg.loader.minibatch_size)
    e = cfg.embed
    if cfg.moe_experts:
        from veles_tpu.znicz import moe  # noqa: F401 (registers "moe")
        ffn = {"type": "moe", "n_experts": cfg.moe_experts,
               "hidden": cfg.ffn, "residual": True,
               "capacity_factor": float(cfg.moe_capacity_factor),
               "weights_stddev": 0.05}
    else:
        ffn = {"type": "seq_ffn", "hidden": cfg.ffn,
               "activation": "tanh", "weights_stddev": 0.05}
    return CharTransformerWorkflow(
        layers=[
            {"type": "seq_linear", "output_features": e,
             "pos_embed": True, "weights_stddev": 0.05},
            {"type": "attention", "n_heads": cfg.n_heads, "causal": True,
             "residual": True, "parallel_mode": cfg.parallel_mode,
             "weights_stddev": 0.05},
            ffn,
            {"type": "seq_softmax", "output_features": loader.n_vocab,
             "weights_stddev": 0.05},
        ],
        loader=loader, loss="softmax", n_classes=loader.n_vocab,
        decision_config=cfg.decision.to_dict(),
        gd_config=cfg.gd.to_dict(),
        name="CharTransformerWorkflow")


def run(load, main):
    load(create_workflow)
    main()
