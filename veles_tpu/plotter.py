"""Plotter infrastructure: plotting units + detached renderer.

Parity: reference `veles/plotter.py` + `veles/graphics_server.py` /
`graphics_client.py` (SURVEY.md §2.5) — plotting units accumulate data in
the training process and publish plot SPECS to a renderer that runs OFF
the training thread, so rendering never stalls the hot loop.

TPU-first shape of the same idea: specs go onto a queue consumed by a
daemon renderer thread (matplotlib Agg → PNG files); with matplotlib
absent the specs are still recorded and written as JSON, so headless/CI
runs keep the data. The ZMQ PUB hop of the reference collapses to an
in-process queue — the isolation that mattered (no rendering on the
training thread) is preserved, the transport is not load-bearing.
"""

from __future__ import annotations

import json
import os
import queue
import threading
from typing import Any, Dict, List, Optional

from veles_tpu.logger import Logger
from veles_tpu.units import Unit


def _have_matplotlib() -> bool:
    try:
        import matplotlib  # noqa: F401
        return True
    except ImportError:
        return False


class GraphicsRenderer(Logger):
    """Consumer of plot specs; renders PNGs (or JSON when matplotlib is
    unavailable) into `directory`.

    Two isolation levels, mirroring the reference's graphics_server →
    graphics_client split:
    - default: a daemon THREAD (rendering off the training thread; the
      transport hop of the reference collapses to an in-process queue)
    - `process=True`: a detached renderer PROCESS — the full reference
      design, for runs where matplotlib work is heavy enough that even
      GIL contention with the training thread matters. The child is a
      plain `python -m veles_tpu.plotter --render-worker DIR` subprocess
      fed length-delimited pickled specs over stdin by the feeder thread
      — NOT multiprocessing, whose spawn bootstrap re-imports the user's
      `__main__` (a workflow script without an import guard would
      re-train inside the renderer). `rendered` is not tracked in the
      parent in this mode; the artifact contract is the files on disk."""

    def __init__(self, directory: str = "plots",
                 process: bool = False,
                 tensorboard_dir: str = "") -> None:
        self.directory = directory
        self.process = process
        #: optional TensorBoard sink (SURVEY.md §5.5 TPU-equiv: "plotter
        #: API writing to TensorBoard/matplotlib"): every "lines" spec's
        #: new points also land as scalars tagged "<name>/<label>"
        self.tensorboard_dir = tensorboard_dir
        self._tb_writer = None
        self._tb_counts: Dict[tuple, int] = {}
        self._q: "queue.Queue[Optional[Dict[str, Any]]]" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._proc = None
        self.rendered: List[str] = []
        #: per-plot-name merged line series: several AccumulatingPlotters
        #: publishing under one name (train/validation error) draw on ONE
        #: figure, like the reference's multi-series error chart
        self._series: Dict[str, Dict[str, Any]] = {}

    def start(self) -> None:
        os.makedirs(self.directory, exist_ok=True)
        if self.process:
            import subprocess
            import sys
            cmd = [sys.executable, "-m", "veles_tpu.plotter",
                   "--render-worker", self.directory]
            if self.tensorboard_dir:
                cmd += ["--tensorboard", self.tensorboard_dir]
            self._proc = subprocess.Popen(cmd, stdin=subprocess.PIPE)
        # in process mode the same daemon thread becomes the pipe FEEDER,
        # so a slow child never blocks a publishing (training) thread
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="graphics-renderer")
        self._thread.start()

    def publish(self, spec: Dict[str, Any]) -> None:
        self._q.put(spec)

    def clear_series(self, name: str) -> None:
        """Drop the merged line-series cache for `name` (rides the queue,
        so it is ordered with in-flight publishes): a NEW workflow
        plotting under a name an earlier run used starts clean instead
        of inheriting the old curves."""
        self.publish({"name": name, "kind": "__clear__"})

    def stop(self) -> None:
        if self._thread is None:
            return
        self._q.put(None)
        self._thread.join(timeout=30)
        feeder_done = not self._thread.is_alive()
        self._thread = None
        if feeder_done:
            # a hung render thread may still be writing scalars; closing
            # under it would just spawn a stray unflushed writer
            self._tb_close()
        if self._proc is not None:
            if feeder_done:
                # EOF tells the worker to finish its queue and exit
                try:
                    self._proc.stdin.close()
                except OSError:
                    pass
                try:
                    self._proc.wait(timeout=30)
                except Exception:  # noqa: BLE001
                    pass
            if self._proc.poll() is None:
                # feeder stuck on a full pipe or the child is hung:
                # kill AND reap (an unreaped child stays a zombie for
                # the rest of the training process)
                self._proc.kill()
                try:
                    self._proc.wait(timeout=5)
                except Exception:  # noqa: BLE001
                    pass
            self._proc = None

    # -- rendering -----------------------------------------------------------

    def _loop(self) -> None:
        import pickle
        import struct
        while True:
            spec = self._q.get()
            if spec is None:
                return
            try:
                if self._proc is not None:
                    blob = pickle.dumps(spec, protocol=4)
                    self._proc.stdin.write(struct.pack("<Q", len(blob)))
                    self._proc.stdin.write(blob)
                    self._proc.stdin.flush()
                    continue
                path = self._render(spec)
                if path:
                    self.rendered.append(path)
            except Exception as e:  # noqa: BLE001 — rendering must never
                self.warning("render failed: %s", e)   # kill training

    def _tb_scalars(self, spec: Dict[str, Any]) -> None:
        """Append each series' NEW points as TensorBoard scalars
        (tag "<plot>/<label>", step = point index)."""
        if self._tb_writer is None:
            try:
                from torch.utils.tensorboard import SummaryWriter
                # The only cross-thread reader is stop()'s _tb_close,
                # which runs strictly after the render thread's join()
                # succeeded (feeder_done gate) — a join-ordered
                # happens-before the static pass cannot see.
                # velint: disable=shared-write-no-lock
                self._tb_writer = SummaryWriter(self.tensorboard_dir)
            except Exception as e:  # noqa: BLE001 — optional sink
                self.warning("tensorboard sink unavailable (%s); "
                             "disabling it for this run", e)
                self.tensorboard_dir = ""   # one warning, zero retries
                return
        for label, ys in spec["series"].items():
            key = (spec["name"], label)
            start = self._tb_counts.get(key, 0)
            try:
                for i in range(start, len(ys)):
                    self._tb_writer.add_scalar(
                        f"{spec['name']}/{label}", float(ys[i]), i)
                    # commit per point: a later failure must not rewind
                    # already-written labels into duplicate events
                    self._tb_counts[key] = i + 1
            except Exception as e:  # noqa: BLE001 — sink must never kill
                self.warning("tensorboard sink failed on %s/%s: %s",
                             spec["name"], label, e)

    def _tb_close(self) -> None:
        if self._tb_writer is not None:
            try:
                self._tb_writer.close()
            except Exception:  # noqa: BLE001
                pass
            self._tb_writer = None

    def _render(self, spec: Dict[str, Any]) -> Optional[str]:
        name = spec["name"]
        if spec.get("kind") == "__clear__":
            self._series.pop(name, None)    # new run under the same name
            for key in [k for k in self._tb_counts if k[0] == name]:
                self._tb_counts.pop(key)    # TB restarts from step 0 too
            return None
        base = os.path.join(self.directory, name)
        if spec.get("kind") == "lines":
            # merge multi-publisher series (train/validation under one
            # name) for BOTH the png and the headless-json paths
            merged = self._series.setdefault(name, {})
            merged.update(spec["series"])
            spec = dict(spec, series=dict(merged))
            if self.tensorboard_dir:
                self._tb_scalars(spec)
        if not _have_matplotlib():
            path = base + ".json"
            with open(path, "w") as f:
                json.dump(spec, f, default=lambda a: getattr(
                    a, "tolist", lambda: str(a))())
            return path
        import matplotlib
        matplotlib.use("Agg", force=False)
        import matplotlib.pyplot as plt
        fig = plt.figure(figsize=(6, 4), dpi=110)
        ax = fig.add_subplot(111)
        kind = spec["kind"]
        if kind == "lines":
            for label, ys in spec["series"].items():
                ax.plot(ys, label=label)
            ax.legend()
            ax.set_xlabel(spec.get("xlabel", "epoch"))
            ax.set_ylabel(spec.get("ylabel", ""))
        elif kind == "matrix":
            im = ax.imshow(spec["data"], cmap="viridis")
            fig.colorbar(im, ax=ax)
        elif kind == "images":
            import numpy as np
            plt.close(fig)
            tiles = spec["data"]
            n = len(tiles)
            cols = int(np.ceil(np.sqrt(n)))
            rows = -(-n // cols)
            fig, axes = plt.subplots(rows, cols, figsize=(cols, rows),
                                     dpi=110)
            axes = np.atleast_1d(axes).ravel()
            for a in axes:
                a.axis("off")
            for a, tile in zip(axes, tiles):
                t = np.asarray(tile)
                t = (t - t.min()) / max(float(t.max() - t.min()), 1e-9)
                a.imshow(t.squeeze(), cmap="gray")
        else:
            plt.close(fig)
            raise ValueError(f"unknown plot kind {kind!r}")
        ax.set_title(spec.get("title", name))
        path = base + ".png"
        fig.savefig(path, bbox_inches="tight")
        plt.close(fig)
        return path


#: process-wide default renderer (lazily started); units use it unless an
#: explicit renderer is linked.
_default_renderer: Optional[GraphicsRenderer] = None


def stop_default_renderer() -> None:
    """Drain + stop the process-wide renderer (no-op when never started).
    End-of-run publishers call this BEFORE reading the plots directory so
    queued specs are flushed to files; a later get_renderer() starts a
    fresh one."""
    global _default_renderer
    if _default_renderer is not None:
        _default_renderer.stop()
        _default_renderer = None


def get_renderer(directory: str = "plots") -> GraphicsRenderer:
    global _default_renderer
    if _default_renderer is None:
        # root.common.graphics_process=1 selects the detached renderer
        # PROCESS (full reference graphics_client isolation);
        # root.common.tensorboard_dir adds the TensorBoard scalar sink
        from veles_tpu.config import root
        process = bool(root.common.get("graphics_process", False))
        tb = str(root.common.get("tensorboard_dir", "") or "")
        _default_renderer = GraphicsRenderer(directory, process=process,
                                             tensorboard_dir=tb)
        _default_renderer.start()
    return _default_renderer


class Plotter(Unit):
    """Base plotting unit: subclasses build a spec in `make_spec()`; firing
    publishes it to the renderer. Like the reference, plotters are gated
    (typically on epoch end) so they cost nothing per minibatch."""

    def __init__(self, workflow=None, renderer: Optional[GraphicsRenderer]
                 = None, directory: str = "plots", **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self._renderer = renderer
        self.directory = directory

    @property
    def renderer(self) -> GraphicsRenderer:
        if self._renderer is None:
            self._renderer = get_renderer(self.directory)
        return self._renderer

    def make_spec(self) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def run(self) -> None:
        # reference CLI parity: the disable-plotting flag turns every
        # plotter into a no-op (CLI --no-plot sets this root knob)
        from veles_tpu.config import root
        if root.common.get("plotting_disabled", False):
            return
        spec = self.make_spec()
        if spec is not None:
            self.renderer.publish(spec)

    def __getstate__(self):
        d = super().__getstate__()
        d["_renderer"] = None  # daemon thread: recreated on demand
        return d


def _render_worker(directory: str, tensorboard_dir: str = "") -> int:
    """`python -m veles_tpu.plotter --render-worker DIR` — the detached
    renderer process: length-delimited pickled specs on stdin until EOF.
    Plain subprocess instead of multiprocessing so the user's `__main__`
    (their workflow script) is never re-imported here."""
    import pickle
    import struct
    import sys

    r = GraphicsRenderer(directory, tensorboard_dir=tensorboard_dir)
    os.makedirs(directory, exist_ok=True)
    stdin = sys.stdin.buffer
    try:
        while True:
            header = stdin.read(8)
            if len(header) < 8:
                return 0
            (size,) = struct.unpack("<Q", header)
            blob = stdin.read(size)
            if len(blob) < size:
                return 0
            try:
                r._render(pickle.loads(blob))
            except Exception:  # noqa: BLE001 — rendering must never crash
                import traceback
                traceback.print_exc()
    finally:
        r._tb_close()


if __name__ == "__main__":
    import argparse

    _p = argparse.ArgumentParser(prog="veles_tpu.plotter")
    _p.add_argument("--render-worker", required=True, metavar="DIR")
    _p.add_argument("--tensorboard", default="", metavar="DIR")
    _args = _p.parse_args()
    raise SystemExit(_render_worker(_args.render_worker,
                                    _args.tensorboard))
