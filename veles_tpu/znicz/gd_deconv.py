"""Gradient unit for Deconv.

Parity: reference `veles/znicz/gd_deconv.py` (`GDDeconv`) — err_output →
err_input through the deconv adjoint (which is a plain forward conv) plus
the SGD weight update; no bias (SURVEY.md §2.8).

TPU-first: backward + update is one jitted function whose two convolutions
come from `jax.vjp` of the forward deconv (ops.xla.deconv2d_backward).
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import numpy as np

from veles_tpu.ops import reference as ref
from veles_tpu.ops import xla as ox
from veles_tpu.ops.optim import SGDConfig, sgd_update
from veles_tpu.znicz.deconv import Deconv
from veles_tpu.znicz.nn_units import GradientDescentBase, register_gd


@register_gd(Deconv)
class GDDeconv(GradientDescentBase):
    def link_forward(self, fwd) -> "GDDeconv":
        self.link_attrs(fwd, "weights", "input", "output")
        self._stride = fwd.stride
        self._padding = fwd.padding
        return self

    def initialize(self, device=None, **kwargs: Any):
        if not self.err_output or not self.weights:
            return False
        if not self.vel_w:
            self.vel_w.reset(np.zeros(self.weights.shape,
                                      self.weights.dtype))
        if not self.err_input or self.err_input.shape != self.input.shape:
            self.err_input.reset(np.zeros(self.input.shape, np.float32))
        return super().initialize(device=device, **kwargs)

    def xla_init(self):
        stride, padding = self._stride, self._padding
        cfg = SGDConfig(lr=self.learning_rate,
                        momentum=self.gradient_moment,
                        weight_decay=self.weights_decay,
                        l1_decay=self.l1_decay)

        def step(x, w, err_y, vw, lr_scale):
            err_x, dw = ox.deconv2d_backward(x, w, err_y, stride, padding)
            new_p, new_v = sgd_update({"w": w}, {"w": dw}, {"w": vw},
                                      cfg, lr_scale)
            return err_x, new_p["w"], new_v["w"]

        self._fn = self.jit(step, donate_argnums=(3,))
        return None

    def numpy_run(self) -> None:
        err_x, dw = ref.deconv2d_backward(
            self.input.mem, self.weights.mem, self.err_output.mem,
            self._stride, self._padding)
        w, vw = self._sgd_host(self.weights.mem, dw, self.vel_w.mem, False)
        self.err_input.mem = err_x
        self.weights.mem = w
        self.vel_w.mem = vw

    def xla_run(self) -> None:
        d = self.device
        err_x, w, vw = self._fn(
            self.input.devmem(d), self.weights.devmem(d),
            self.err_output.devmem(d), self.vel_w.devmem(d),
            jnp.float32(self.lr_scale))
        self.err_input.set_devmem(err_x)
        self.weights.set_devmem(w)
        self.vel_w.set_devmem(vw)
