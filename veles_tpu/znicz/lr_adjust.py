"""Learning-rate scheduling unit.

Parity: reference `veles/znicz/lr_adjust.py` (SURVEY.md §2.8 [M]) — the
Caffe-era policy set (fixed/step/multistep/exp/inv/poly) applied to the
GD units' learning rate over training iterations.

TPU-first: the GD units (and FusedTrainStep) read a runtime `lr_scale`
multiplier that is a TRACED scalar in the compiled step, so schedule
changes never retrace/recompile — the reference re-set a kernel argument,
we re-set one device scalar.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional

from veles_tpu.units import Unit


def step_policy(base: float, gamma: float, step: int):
    """lr(it) = base · gamma^floor(it/step)."""
    return lambda it: base * (gamma ** (it // step))


def exp_policy(base: float, gamma: float):
    """lr(it) = base · gamma^it."""
    return lambda it: base * (gamma ** it)


def inv_policy(base: float, gamma: float, power: float):
    """lr(it) = base / (1 + gamma·it)^power (the Caffe-era 'inv')."""
    return lambda it: base / ((1.0 + gamma * it) ** power)


def fixed_policy(base: float):
    """lr(it) = base."""
    return lambda it: base


def poly_policy(base: float, power: float, max_iter: int):
    """lr(it) = base · (1 − it/max_iter)^power, clamped at 0."""
    if max_iter <= 0:
        raise ValueError(f"poly policy needs max_iter > 0, got {max_iter}")
    return lambda it: base * max(1.0 - it / max_iter, 0.0) ** power


def multistep_policy(base: float, gamma: float, steps):
    """lr(it) = base · gamma^(#{s in steps : it ≥ s})."""
    steps = sorted(steps)
    return lambda it: base * (gamma ** sum(1 for s in steps if it >= s))


#: one source of truth: name -> builder over the full cfg tuple
_BUILDERS = {
    "step": lambda b, g, s, p, m, ms: step_policy(b, g, s),
    "exp": lambda b, g, s, p, m, ms: exp_policy(b, g),
    "inv": lambda b, g, s, p, m, ms: inv_policy(b, g, p),
    "fixed": lambda b, g, s, p, m, ms: fixed_policy(b),
    "poly": lambda b, g, s, p, m, ms: poly_policy(b, p, m),
    "multistep": lambda b, g, s, p, m, ms: multistep_policy(b, g, ms),
}
_POLICIES = tuple(sorted(_BUILDERS))


def _build_policy(policy, base, gamma, step, power, max_iter, steps):
    try:
        builder = _BUILDERS[policy]
    except KeyError:
        raise ValueError(f"unknown lr policy {policy!r}") from None
    return builder(base, gamma, step, power, max_iter, steps)


class LearningRateAdjust(Unit):
    """Applies a policy to every linked GD unit's `lr_scale` each firing
    (wire it after the gradient chain; one firing per training
    minibatch = one 'iteration' like the reference)."""

    def __init__(self, workflow=None, policy: str = "exp",
                 base: float = 1.0, gamma: float = 0.999,
                 step: int = 100, power: float = 0.75,
                 max_iter: int = 10000,
                 steps: Optional[Iterable[int]] = None,
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        if policy not in _POLICIES:
            raise ValueError(
                f"unknown lr policy {policy!r}; one of {sorted(_POLICIES)}")
        self.policy_name = policy
        # an explicit empty list means "no decay steps", not the default
        steps = tuple(steps) if steps is not None else (1000, 5000)
        self._cfg = (policy, base, gamma, step, power, max_iter, steps)
        self._policy = _build_policy(*self._cfg)
        self.iteration = 0
        self.gd_units: list = []

    def link_gds(self, gds: Iterable[Unit]) -> "LearningRateAdjust":
        self.gd_units = list(gds)
        return self

    @property
    def current_scale(self) -> float:
        return float(self._policy(self.iteration))

    def run(self) -> None:
        scale = self.current_scale
        for g in self.gd_units:
            g.lr_scale = scale
        self.iteration += 1

    # policy closures don't pickle; rebuild from the stored config
    def __getstate__(self):
        d = super().__getstate__()
        d.pop("_policy", None)
        return d

    def __setstate__(self, state):
        super().__setstate__(state)
        cfg = self._cfg
        if len(cfg) == 5:       # pre-r4 snapshot: no max_iter/steps
            cfg = cfg + (10000, (1000, 5000))
            self._cfg = cfg
        self._policy = _build_policy(*cfg)
