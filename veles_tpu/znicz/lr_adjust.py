"""Learning-rate scheduling unit.

Parity: reference `veles/znicz/lr_adjust.py` (SURVEY.md §2.8 [M]) —
step/exp/inv policies applied to the GD units' learning rate over
training iterations.

TPU-first: the GD units (and FusedTrainStep) read a runtime `lr_scale`
multiplier that is a TRACED scalar in the compiled step, so schedule
changes never retrace/recompile — the reference re-set a kernel argument,
we re-set one device scalar.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional

from veles_tpu.units import Unit


def step_policy(base: float, gamma: float, step: int):
    """lr(it) = base · gamma^floor(it/step)."""
    return lambda it: base * (gamma ** (it // step))


def exp_policy(base: float, gamma: float):
    """lr(it) = base · gamma^it."""
    return lambda it: base * (gamma ** it)


def inv_policy(base: float, gamma: float, power: float):
    """lr(it) = base / (1 + gamma·it)^power (the Caffe-era 'inv')."""
    return lambda it: base / ((1.0 + gamma * it) ** power)


_POLICIES = {"step": step_policy, "exp": exp_policy, "inv": inv_policy}


class LearningRateAdjust(Unit):
    """Applies a policy to every linked GD unit's `lr_scale` each firing
    (wire it after the gradient chain; one firing per training
    minibatch = one 'iteration' like the reference)."""

    def __init__(self, workflow=None, policy: str = "exp",
                 base: float = 1.0, gamma: float = 0.999,
                 step: int = 100, power: float = 0.75,
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        if policy not in _POLICIES:
            raise ValueError(
                f"unknown lr policy {policy!r}; one of {sorted(_POLICIES)}")
        self.policy_name = policy
        if policy == "step":
            self._policy = step_policy(base, gamma, step)
        elif policy == "exp":
            self._policy = exp_policy(base, gamma)
        else:
            self._policy = inv_policy(base, gamma, power)
        self._cfg = (policy, base, gamma, step, power)
        self.iteration = 0
        self.gd_units: list = []

    def link_gds(self, gds: Iterable[Unit]) -> "LearningRateAdjust":
        self.gd_units = list(gds)
        return self

    @property
    def current_scale(self) -> float:
        return float(self._policy(self.iteration))

    def run(self) -> None:
        scale = self.current_scale
        for g in self.gd_units:
            g.lr_scale = scale
        self.iteration += 1

    # policy closures don't pickle; rebuild from the stored config
    def __getstate__(self):
        d = super().__getstate__()
        d.pop("_policy", None)
        return d

    def __setstate__(self, state):
        super().__setstate__(state)
        policy, base, gamma, step, power = self._cfg
        if policy == "step":
            self._policy = step_policy(base, gamma, step)
        elif policy == "exp":
            self._policy = exp_policy(base, gamma)
        else:
            self._policy = inv_policy(base, gamma, power)
