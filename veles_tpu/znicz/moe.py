"""Mixture-of-experts units (expert parallelism).

Not in the reference (SURVEY.md §2.4: EP absent) — added so the parallel
layer covers the full dp/tp/sp/ep axis set. Follows the house pattern:
Forward twin + vjp-driven GD twin; the dense routing form is the golden
model, the shard_map expert-parallel form (ops.moe.moe_forward_ep) is its
mesh twin, equivalence-tested on the virtual 8-device mesh.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from veles_tpu.memory import Array
from veles_tpu.ops import moe as om
from veles_tpu.ops.optim import SGDConfig, sgd_update
from veles_tpu.znicz.nn_units import (Forward, GradientDescentBase,
                                      register_gd)


class MoELayer(Forward):
    """Top-1 (switch) MoE FFN: x (N, D) -> (N, D). Params: router wr
    (D, E), expert FFNs w1 (E, D, H), b1, w2 (E, H, D), b2."""

    def __init__(self, workflow=None, n_experts: int = 4,
                 hidden: int = 64, capacity_factor: float = 2.0,
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.n_experts = n_experts
        self.hidden = hidden
        self.capacity_factor = capacity_factor
        self.wr = Array()
        self.w1 = Array()
        self.b1 = Array()
        self.w2 = Array()
        self.b2 = Array()

    def param_arrays(self) -> Dict[str, Array]:
        return {"wr": self.wr, "w1": self.w1, "b1": self.b1,
                "w2": self.w2, "b2": self.b2}

    def capacity(self, n_tokens: int) -> int:
        return max(1, int(self.capacity_factor * n_tokens
                          / self.n_experts))

    def initialize(self, device=None, **kwargs: Any):
        if not self.input:
            return False
        n = self.input.shape[0]
        d = int(np.prod(self.input.shape[1:]))
        e, h = self.n_experts, self.hidden
        if not self.wr:
            std = self.weights_stddev or self.default_stddev(d)
            self.wr.reset(self._fill((d, e), self.weights_filling, std))
            self.w1.reset(self._fill((e, d, h), self.weights_filling, std))
            self.b1.reset(np.zeros((e, h), np.float32))
            self.w2.reset(self._fill((e, h, d), self.weights_filling,
                                     self.weights_stddev
                                     or self.default_stddev(h)))
            self.b2.reset(np.zeros((e, d), np.float32))
        if not self.output or self.output.shape != (n, d):
            self.output.reset(np.zeros((n, d), np.float32))
        return super().initialize(device=device, **kwargs)

    def _apply(self, params, x):
        x2 = x.reshape(x.shape[0], -1)
        return om.moe_forward(x2, params["wr"], params["w1"], params["b1"],
                              params["w2"], params["b2"],
                              capacity=self.capacity(x2.shape[0]))

    def fused_apply(self, params, x, *, key=None, train=True):
        return self._apply(params, x)

    def xla_init(self):
        self._fn = self.jit(lambda x, p: self._apply(p, x))
        return None

    def numpy_run(self) -> None:
        params = {k: jnp.asarray(a.mem)
                  for k, a in self.param_arrays().items()}
        self.output.mem = np.asarray(self._apply(params, self.input.mem))

    def xla_run(self) -> None:
        dv = self.device
        params = {k: a.devmem(dv) for k, a in self.param_arrays().items()}
        self.output.set_devmem(self._fn(self.input.devmem(dv), params))


@register_gd(MoELayer)
class GDMoELayer(GradientDescentBase):
    """Backward via jax.vjp of the dense routing forward + SGD update.
    (The top-1 argmax is non-differentiable by construction — gradients
    flow through the gate value and the expert FFNs, switch-style.)"""

    def link_forward(self, fwd: MoELayer) -> "GDMoELayer":
        self.link_attrs(fwd, "wr", "w1", "b1", "w2", "b2", "input",
                        "output")
        self._fwd = fwd
        return self

    _PNAMES = ("wr", "w1", "b1", "w2", "b2")

    def initialize(self, device=None, **kwargs: Any):
        if not self.err_output or not self.wr:
            return False
        for name in self._PNAMES:
            vname = f"vel_{name}"
            if getattr(self, vname, None) is None or not getattr(self,
                                                                 vname):
                arr = Array()
                arr.reset(np.zeros(getattr(self, name).shape, np.float32))
                setattr(self, vname, arr)
        if not self.err_input or self.err_input.shape != self.input.shape:
            self.err_input.reset(np.zeros(self.input.shape, np.float32))
        return super().initialize(device=device, **kwargs)

    def xla_init(self):
        fwd = self._fwd
        cfg = SGDConfig(lr=self.learning_rate,
                        momentum=self.gradient_moment,
                        weight_decay=self.weights_decay,
                        l1_decay=self.l1_decay)

        def step(x, params, err_y, vel, lr_scale):
            _, vjp = jax.vjp(lambda p, xx: fwd._apply(p, xx), params, x)
            grads, err_x = vjp(err_y)
            new_p, new_v = sgd_update(params, grads, vel, cfg, lr_scale)
            return err_x, new_p, new_v

        self._fn = self.jit(step, donate_argnums=(3,))
        return None

    def numpy_run(self) -> None:
        self.xla_run()  # vjp is the only backward model

    def xla_run(self) -> None:
        dv = self.device
        params = {n: getattr(self, n).devmem(dv) for n in self._PNAMES}
        vel = {n: getattr(self, f"vel_{n}").devmem(dv)
               for n in self._PNAMES}
        err_x, new_p, new_v = self._fn(
            self.input.devmem(dv), params, self.err_output.devmem(dv),
            vel, jnp.float32(self.lr_scale))
        self.err_input.set_devmem(err_x.reshape(self.input.shape))
        for n in self._PNAMES:
            getattr(self, n).set_devmem(new_p[n])
            getattr(self, f"vel_{n}").set_devmem(new_v[n])

    def __getstate__(self):
        st = super().__getstate__()
        st.pop("_fwd", None)
        return st


from veles_tpu.znicz import standard_workflow as _sw  # noqa: E402

_sw.LAYER_TYPES.update({"moe": MoELayer})
