"""Mixture-of-experts units (expert parallelism).

Not in the reference (SURVEY.md §2.4: EP absent) — added so the parallel
layer covers the full dp/tp/sp/ep axis set. Follows the house pattern:
Forward twin + vjp-driven GD twin. The dense routing form
(ops.moe.moe_forward) is the golden model and the granular/local fused
path; when FusedTrainStep is built with `ep=True` it sets `ep_axis_name`
on the unit and `fused_apply` dispatches to the expert-parallel
shard_map form (ops.moe.moe_forward_ep) with the expert tensors sharded
over the mesh data axis — an EP MoE model trains end-to-end and matches
the dense golden (tests/test_moe_pipeline.py).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from veles_tpu.memory import Array
from veles_tpu.ops import moe as om
from veles_tpu.znicz.nn_units import (Forward, GradientDescentVJP,
                                      register_gd)


class MoELayer(Forward):
    """Top-1 (switch) MoE FFN. Params: router wr (D, E), expert FFNs
    w1 (E, D, H), b1, w2 (E, H, D), b2.

    Input forms (`route` selects; D is always the routing feature dim):
    - (N, D) classifier features — each SAMPLE is a routing token;
    - (N, S, D) sequence activations (transformer stacks) — each TOKEN
      routes independently (the standard MoE-transformer block; output
      keeps the (N, S, D) shape, optionally residual).
    route="auto" treats 3-D input as a token sequence; pass "sample" to
    flatten 3-D samples (e.g. images) to one routing row per sample."""

    #: params sharded on their leading (expert) dim when the fused step
    #: runs expert-parallel; the router wr stays replicated (every shard
    #: routes its own tokens over ALL experts before the all_to_all)
    ep_params = ("w1", "b1", "w2", "b2")

    def __init__(self, workflow=None, n_experts: int = 4,
                 hidden: int = 64, capacity_factor: float = 2.0,
                 residual: bool = False, route: str = "auto",
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        assert route in ("auto", "token", "sample"), route
        self.n_experts = n_experts
        self.hidden = hidden
        self.capacity_factor = capacity_factor
        #: y = x + moe(x) — the transformer-block form (tokens the
        #: capacity dropped keep their residual value instead of zero)
        self.residual = residual
        #: "auto" | "token" | "sample" — see class docstring
        self.route = route
        #: mesh axis name the expert dim is sharded over; set by
        #: FusedTrainStep(ep=True) at trace time so fused_apply runs the
        #: all_to_all expert exchange instead of the dense local form.
        #: None = dense local (the golden model).
        self.ep_axis_name = None
        self.wr = Array()
        self.w1 = Array()
        self.b1 = Array()
        self.w2 = Array()
        self.b2 = Array()

    def param_arrays(self) -> Dict[str, Array]:
        return {"wr": self.wr, "w1": self.w1, "b1": self.b1,
                "w2": self.w2, "b2": self.b2}

    def capacity(self, n_tokens: int) -> int:
        return max(1, int(self.capacity_factor * n_tokens
                          / self.n_experts))

    def initialize(self, device=None, **kwargs: Any):
        if not self.input:
            return False
        shape = self.input.shape
        # (N, S, D) sequence input: the token feature dim routes;
        # (N, ...) classifier input: the flattened sample routes
        token_wise = self._token_wise(len(shape))
        d = (int(shape[-1]) if token_wise
             else int(np.prod(shape[1:])))
        out_shape = (tuple(shape) if token_wise else (shape[0], d))
        e, h = self.n_experts, self.hidden
        if self.wr and self.wr.shape[0] != d:
            raise ValueError(
                f"{self.name}: router expects feature dim "
                f"{self.wr.shape[0]} but input routes dim {d} — a "
                "restored snapshot trained under a different `route` "
                f"mode? (route={self.route!r}, input {tuple(shape)})")
        if not self.wr:
            std = self.weights_stddev or self.default_stddev(d)
            self.wr.reset(self._fill((d, e), self.weights_filling, std))
            self.w1.reset(self._fill((e, d, h), self.weights_filling, std))
            self.b1.reset(np.zeros((e, h), np.float32))
            self.w2.reset(self._fill((e, h, d), self.weights_filling,
                                     self.weights_stddev
                                     or self.default_stddev(h)))
            self.b2.reset(np.zeros((e, d), np.float32))
        if not self.output or self.output.shape != out_shape:
            self.output.reset(np.zeros(out_shape, np.float32))
        return super().initialize(device=device, **kwargs)

    def _token_wise(self, ndim: int) -> bool:
        if self.route == "token":
            return True
        if self.route == "sample":
            return False
        return ndim == 3      # auto: 3-D activations are token sequences

    def _apply(self, params, x, axis_name=None):
        if self._token_wise(x.ndim):   # (N, S, D): route per TOKEN
            n, s, d = x.shape
            y = self._apply_tokens(params, x.reshape(n * s, d),
                                   axis_name)
            y = y.reshape(n, s, d)
            return x + y if self.residual else y
        x2 = x.reshape(x.shape[0], -1)
        y = self._apply_tokens(params, x2, axis_name)
        return x2 + y if self.residual else y

    def _apply_tokens(self, params, x2, axis_name):
        if axis_name is not None:
            # inside shard_map: x2.shape[0] is the per-shard token count.
            # When capacity_factor·n_loc/n_experts divides exactly, the
            # per-source-shard capacities total the dense form's global
            # slots; with truncation/clamping the drop sets can differ —
            # dense/EP equivalence is exact only in zero-drop configs.
            return om.moe_forward_ep(
                x2, params["wr"], params["w1"], params["b1"],
                params["w2"], params["b2"], axis_name,
                capacity=self.capacity(x2.shape[0]))
        return om.moe_forward(x2, params["wr"], params["w1"], params["b1"],
                              params["w2"], params["b2"],
                              capacity=self.capacity(x2.shape[0]))

    def fused_apply(self, params, x, *, key=None, train=True):
        return self._apply(params, x, axis_name=self.ep_axis_name)

    def xla_init(self):
        self._fn = self.jit(lambda x, p: self._apply(p, x))
        return None

    def numpy_run(self) -> None:
        params = {k: jnp.asarray(a.mem)
                  for k, a in self.param_arrays().items()}
        self.output.mem = np.asarray(self._apply(params, self.input.mem))

    def xla_run(self) -> None:
        dv = self.device
        params = {k: a.devmem(dv) for k, a in self.param_arrays().items()}
        self.output.set_devmem(self._fn(self.input.devmem(dv), params))


@register_gd(MoELayer)
class GDMoELayer(GradientDescentVJP):
    """Backward via jax.vjp of the dense routing forward + SGD update.
    (The top-1 argmax is non-differentiable by construction — gradients
    flow through the gate value and the expert FFNs, switch-style.)"""


from veles_tpu.znicz import standard_workflow as _sw  # noqa: E402

_sw.LAYER_TYPES.update({"moe": MoELayer})
