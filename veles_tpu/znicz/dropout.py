"""Dropout units.

Parity: reference `veles/znicz/dropout.py` — `DropoutForward` (device-RNG
mask kernel, `dropout_ratio`), `DropoutBackward` (same mask applied to the
error flow). Dropout is identity on validation/test minibatches
(SURVEY.md §2.8).

TPU-first: the mask comes from `jax.random` (counter-based, reproducible
from the snapshot seed) on the XLA path and the host PRNG on the numpy
golden path — the same RNG split the reference had between its xorshift
device kernel and numpy.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from veles_tpu import prng
from veles_tpu.loader.base import TRAIN
from veles_tpu.memory import Array
from veles_tpu.ops import reference as ref
from veles_tpu.ops import variants
from veles_tpu.ops import xla as ox
from veles_tpu.znicz.nn_units import Forward, GradientDescentBase, register_gd


class DropoutForward(Forward):
    """y = x·mask while training (mask pre-scaled by 1/keep); identity on
    validation/test minibatches. `minibatch_class` is linked from the
    loader by StandardWorkflow (link_loader hook)."""

    def __init__(self, workflow=None, dropout_ratio: float = 0.5,
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.dropout_ratio = dropout_ratio
        self.mask = Array()
        self.minibatch_class = TRAIN

    def param_arrays(self):
        return {}

    def link_loader(self, loader) -> None:
        self.link_attrs(loader, "minibatch_class")

    def initialize(self, device=None, **kwargs: Any):
        if not self.input:
            return False
        if not self.output or self.output.shape != self.input.shape:
            self.output.reset(np.zeros(self.input.shape, np.float32))
        return super().initialize(device=device, **kwargs)

    @property
    def training(self) -> bool:
        return self.minibatch_class == TRAIN

    fused_needs_key = True

    #: lowering-variant registry op for the mask bit source (candidates
    #: "threefry" | "rbg"; default "auto" keeps the legacy backend-
    #: dependent pick — hardware RBG on accelerators, threefry on CPU)
    variant_op = "dropout"

    def variant_signature(self):
        # batch dim excluded: tune-then-inherit across batch sizes
        if getattr(self, "variant_override", None) is not None \
                or not self.input:
            return None
        return {"sample_shape": list(self.input.shape[1:]),
                "dtype": str(np.asarray(self.input.mem).dtype),
                "params": {"dropout_ratio": self.dropout_ratio}}

    def fused_apply(self, params, x, *, key=None, train=True):
        if not train:
            return x
        v = variants.resolve("dropout", unit=self)
        return x * v.apply(key, x.shape, self.dropout_ratio, x.dtype)

    def xla_init(self):
        ratio = self.dropout_ratio

        def fwd(x, key):
            mask = ox.make_dropout_mask(key, x.shape, ratio, x.dtype)
            return x * mask, mask

        self._fn = self.jit(fwd)
        return None

    def numpy_run(self) -> None:
        if not self.training:
            self.output.mem = self.input.mem.copy()
            return
        self.mask.mem = ref.make_dropout_mask(
            prng.get().state, self.input.shape, self.dropout_ratio)
        self.output.mem = ref.dropout_forward(self.input.mem, self.mask.mem)

    def xla_run(self) -> None:
        d = self.device
        if not self.training:
            self.output.set_devmem(self.input.devmem(d))
            return
        y, mask = self._fn(self.input.devmem(d), prng.get().next_key())
        self.output.set_devmem(y)
        self.mask.set_devmem(mask)


@register_gd(DropoutForward)
class DropoutBackward(GradientDescentBase):
    """err_input = err_output·mask (identity when the forward ran in
    eval mode — but the GD chain only runs on TRAIN minibatches anyway)."""

    def link_forward(self, fwd):
        self.link_attrs(fwd, "input", "output", "mask")
        return self

    def initialize(self, device=None, **kwargs: Any):
        if not self.err_output or not self.input:
            return False
        if not self.err_input or self.err_input.shape != self.input.shape:
            self.err_input.reset(np.zeros(self.input.shape, np.float32))
        return super().initialize(device=device, **kwargs)

    def xla_init(self):
        self._fn = self.jit(lambda err, mask: err * mask)
        return None

    def numpy_run(self) -> None:
        if not self.mask:  # no training forward ran yet: identity
            self.err_input.mem = self.err_output.mem.copy()
            return
        self.err_input.mem = ref.dropout_backward(self.err_output.mem,
                                                  self.mask.mem)

    def xla_run(self) -> None:
        d = self.device
        if not self.mask:  # no training forward ran yet: identity
            self.err_input.set_devmem(self.err_output.devmem(d))
            return
        self.err_input.set_devmem(
            self._fn(self.err_output.devmem(d), self.mask.devmem(d)))


# -- layer-type registration --------------------------------------------------
from veles_tpu.znicz import standard_workflow as _sw  # noqa: E402

_sw.LAYER_TYPES.update({
    "dropout": DropoutForward,
})
