"""RBM pretraining units.

Parity: reference `veles/znicz/rbm_units.py` (SURVEY.md §2.8) —
binarization of inputs and CD-1 contrastive-divergence weight updates for
greedy layer-wise autoencoder pretraining.

TPU-first: the whole CD-1 step (h0 sample, v1/h1 reconstruction, three
gradient matmuls, update) is one jitted computation with on-device
Bernoulli sampling (jax.random); the reference ran a separate RNG kernel +
four GEMMs per step.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np

from veles_tpu import prng
from veles_tpu.memory import Array
from veles_tpu.ops import reference as ref
from veles_tpu.ops import xla as ox
from veles_tpu.znicz.nn_units import Forward


class Binarization(Forward):
    """output ~ Bernoulli(input) — stochastic binarization of activations
    in [0,1] (the reference fed binarized data into the RBM)."""

    def param_arrays(self):
        return {}

    def initialize(self, device=None, **kwargs: Any):
        if not self.input:
            return False
        if not self.output or self.output.shape != self.input.shape:
            self.output.reset(np.zeros(self.input.shape, np.float32))
        return super().initialize(device=device, **kwargs)

    def xla_init(self):
        def fwd(x, key):
            return (jax.random.uniform(key, x.shape) < x).astype(x.dtype)

        self._fn = self.jit(fwd)
        return None

    def numpy_run(self) -> None:
        gen = prng.get()
        u = gen.state.random_sample(self.input.shape)
        self.output.mem = (u < self.input.mem).astype(np.float32)

    def xla_run(self) -> None:
        d = self.device
        self.output.set_devmem(self._fn(self.input.devmem(d),
                                        prng.get().next_key()))


class RBMTrainer(Forward):
    """CD-1 trainer: owns W (V,H), visible/hidden biases; each run applies
    one contrastive-divergence update on the current minibatch and records
    the reconstruction MSE in `rec_err` (the decision's metric)."""

    def __init__(self, workflow=None, n_hidden: int = 64,
                 learning_rate: float = 0.1, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.n_hidden = n_hidden
        self.learning_rate = learning_rate
        self.bias_v = Array()
        self.bias_h = Array()
        self.rec_err = 0.0

    def param_arrays(self):
        return {"weights": self.weights, "bias_v": self.bias_v,
                "bias_h": self.bias_h}

    def initialize(self, device=None, **kwargs: Any):
        if not self.input:
            return False
        v = int(np.prod(self.input.shape[1:]))
        if not self.weights:
            gen = prng.get()
            self.weights.reset(gen.fill_normal(
                (v, self.n_hidden), 0.0, 0.01, np.float32))
        if not self.bias_v:
            self.bias_v.reset(np.zeros((v,), np.float32))
        if not self.bias_h:
            self.bias_h.reset(np.zeros((self.n_hidden,), np.float32))
        return super().initialize(device=device, **kwargs)

    def xla_init(self):
        lr = self.learning_rate

        def step(v0, w, bv, bh, key):
            dw, dbv, dbh = ox.rbm_cd1(v0, w, bv, bh, key)
            # ascent on log-likelihood (reference convention: += lr·grad)
            w2, bv2, bh2 = w + lr * dw, bv + lr * dbv, bh + lr * dbh
            # reconstruction error with the UPDATED weights
            h = jax.nn.sigmoid(v0 @ w2 + bh2)
            v1 = jax.nn.sigmoid(h @ w2.T + bv2)
            rec = ((v1 - v0) ** 2).mean()
            return w2, bv2, bh2, rec

        self._fn = self.jit(step)
        return None

    def numpy_run(self) -> None:
        v0 = self.input.mem.reshape(len(self.input), -1)
        gen = prng.get()
        dw, dbv, dbh = ref.rbm_cd1(v0, self.weights.mem, self.bias_v.mem,
                                   self.bias_h.mem, gen.state)
        lr = self.learning_rate
        self.weights.mem = self.weights.mem + lr * dw
        self.bias_v.mem = self.bias_v.mem + lr * dbv
        self.bias_h.mem = self.bias_h.mem + lr * dbh
        sig = lambda a: 1.0 / (1.0 + np.exp(-a))  # noqa: E731
        h = sig(v0 @ self.weights.mem + self.bias_h.mem)
        v1 = sig(h @ self.weights.mem.T + self.bias_v.mem)
        self.rec_err = float(((v1 - v0) ** 2).mean())

    def xla_run(self) -> None:
        d = self.device
        v0 = self.input.devmem(d).reshape(len(self.input), -1)
        w, bv, bh, rec = self._fn(v0, self.weights.devmem(d),
                                  self.bias_v.devmem(d),
                                  self.bias_h.devmem(d),
                                  prng.get().next_key())
        self.weights.set_devmem(w)
        self.bias_v.set_devmem(bv)
        self.bias_h.set_devmem(bh)
        self.rec_err = float(rec)
