"""NN base units: Forward (weight-holding layers) and GradientDescentBase.

Parity: reference `veles/znicz/nn_units.py` (`Forward`: uniform/gaussian
weight fills with `weights_stddev`; `GradientDescentBase`: learning_rate,
gradient_moment (momentum), L1/L2 weight decay, per-layer multipliers;
`NNWorkflow`). The forward/GD pairing registry mirrors the reference's
`MatchingObject` metaclass (SURVEY.md §2.8).

TPU-first notes:
- Weight init happens on host (numpy, seeded via veles_tpu.prng) and is
  transferred once; all per-step compute is a jitted XLA function.
- The GD units' weight update is expressed through `ops.optim.sgd_update`
  so the whole backward+update chain fuses into one XLA computation (the
  reference ran a separate hand-written weight-update kernel per layer).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple, Type

import jax
import jax.numpy as jnp
import numpy as np

from veles_tpu import prng
from veles_tpu.accelerated_units import XLAUnit
from veles_tpu.memory import Array

#: forward unit class -> its gradient unit class (filled by register_gd).
MATCHED_GD: Dict[type, type] = {}


def register_gd(forward_cls: type):
    """Class decorator pairing a GD unit with its forward unit (parity:
    the reference's MatchingObject metaclass registry)."""

    def deco(gd_cls: type) -> type:
        MATCHED_GD[forward_cls] = gd_cls
        return gd_cls

    return deco


def gd_for(forward_cls: type) -> type:
    """Resolve the gradient unit class for a forward unit class, walking the
    MRO so subclasses inherit their base's pairing."""
    for cls in forward_cls.__mro__:
        if cls in MATCHED_GD:
            return MATCHED_GD[cls]
    raise KeyError(f"no GD unit registered for {forward_cls.__name__}")


class Forward(XLAUnit):
    """Base of all weight-holding forward layers.

    Attributes (reference `Forward` contract):
    - `input`, `output`: activation Arrays (output allocated at initialize);
    - `weights`, `bias`: parameter Arrays, host-initialized with
      `weights_filling` ("uniform" | "gaussian") and `weights_stddev`
      (uniform fills draw from ±stddev·√3 so the std matches gaussian fills).
    """

    def __init__(self, workflow=None,
                 weights_filling: str = "uniform",
                 weights_stddev: Optional[float] = None,
                 bias_filling: str = "uniform",
                 bias_stddev: Optional[float] = None,
                 include_bias: bool = True,
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.weights_filling = weights_filling
        self.weights_stddev = weights_stddev
        self.bias_filling = bias_filling
        self.bias_stddev = bias_stddev
        self.include_bias = include_bias
        self.input = Array()
        self.output = Array()
        self.weights = Array()
        self.bias = Array()

    # -- parameter init helpers ----------------------------------------------

    def _fill(self, shape: Tuple[int, ...], filling: str,
              stddev: float, dtype=np.float32) -> np.ndarray:
        gen = prng.get()
        if filling == "uniform":
            lim = stddev * np.sqrt(3.0)
            return gen.fill_uniform(shape, -lim, lim, dtype)
        if filling == "gaussian":
            return gen.fill_normal(shape, 0.0, stddev, dtype)
        raise ValueError(f"unknown filling {filling!r}")

    def default_stddev(self, fan_in: int) -> float:
        """LeCun-style 1/√fan_in when the config gave no stddev."""
        return 1.0 / np.sqrt(max(fan_in, 1))

    def init_params(self, w_shape: Tuple[int, ...], fan_in: int,
                    dtype=np.float32) -> None:
        if not self.weights:
            stddev = self.weights_stddev or self.default_stddev(fan_in)
            self.weights.reset(self._fill(w_shape, self.weights_filling,
                                          stddev, dtype))
        if self.include_bias and not self.bias:
            bstd = self.bias_stddev or self.weights_stddev \
                or self.default_stddev(fan_in)
            self.bias.reset(self._fill((w_shape[-1],), self.bias_filling,
                                       bstd, dtype))
        elif not self.include_bias and not self.bias:
            self.bias.reset(np.zeros((w_shape[-1],), dtype))

    # -- pytree view (fused/sharded train step, veles_tpu.parallel) ----------

    def param_arrays(self) -> Dict[str, Array]:
        """The unit's trainable parameters as named Arrays."""
        return {"weights": self.weights, "bias": self.bias}

    #: set on layers whose fused_apply needs a PRNG key (dropout,
    #: stochastic pooling); the fused step folds a per-layer key in.
    fused_needs_key = False

    def fused_apply(self, params: Dict[str, Any], x, *, key=None,
                    train: bool = True):
        """Pure jnp forward for the fused/sharded train step
        (veles_tpu.parallel.FusedTrainStep). `params` holds jnp arrays
        keyed like `param_arrays()`. Static layer config (stride, ksize,
        activation...) is read from `self` — it is compile-time constant.

        Must be differentiable wrt `params` and `x`: the fused step takes
        grads with jax.grad instead of running the granular GD units."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support the fused train step")


class GradientDescentBase(XLAUnit):
    """Base of all gradient units.

    Consumes `err_output` (dL/d output of its forward twin), produces
    `err_input` (dL/d input) and applies the SGD update to the twin's
    parameters in place. Hyperparameters follow the reference:
    `learning_rate`, `gradient_moment` (momentum), `weights_decay` (L2),
    `l1_decay`, `learning_rate_bias` multiplier (reference used 2× lr on
    biases). The reference's `gradient_accumulation`/`apply_gradients`
    gate maps to the fused step's `train_accum` (parallel/fused.py): K
    scanned microbatches accumulate the exact full-batch gradient before
    ONE update — same capability, jit-native form.
    """

    def __init__(self, workflow=None,
                 learning_rate: float = 0.01,
                 gradient_moment: float = 0.0,
                 weights_decay: float = 0.0,
                 l1_decay: float = 0.0,
                 learning_rate_bias: float = 2.0,
                 optimizer: str = "sgd",
                 adam_beta1: float = 0.9,
                 adam_beta2: float = 0.999,
                 adam_eps: float = 1e-8,
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.learning_rate = learning_rate
        self.gradient_moment = gradient_moment
        self.weights_decay = weights_decay
        self.l1_decay = l1_decay
        self.learning_rate_bias = learning_rate_bias
        #: "sgd" (reference update rule) or "adam" — consumed by the fused
        #: step via pair_gd_configs; the granular per-unit backward keeps
        #: the reference SGD+momentum rule (its velocity buffers round-trip
        #: through snapshots; Adam state lives in the fused state pytree
        #: and round-trips through the sharded checkpoint instead).
        self.optimizer = optimizer
        self.adam_beta1 = adam_beta1
        self.adam_beta2 = adam_beta2
        self.adam_eps = adam_eps
        #: runtime-scalable lr multiplier (driven by the lr_adjust unit).
        self.lr_scale = 1.0
        self.err_output = Array()
        self.err_input = Array()
        # velocity buffers (momentum), allocated lazily
        self.vel_w = Array()
        self.vel_b = Array()

    def link_forward(self, fwd: Forward) -> "GradientDescentBase":
        """Wire the standard data links to the forward twin (parity: the
        reference StandardWorkflow linked weights/bias/input/output)."""
        self.link_attrs(fwd, "weights", "bias", "input", "output")
        return self

    # -- update math (host path; XLA path fuses via ops.optim) ---------------

    def _sgd_host(self, p: np.ndarray, g: np.ndarray, v: np.ndarray,
                  bias: bool) -> Tuple[np.ndarray, np.ndarray]:
        lr = self.learning_rate * self.lr_scale
        if bias:
            lr *= self.learning_rate_bias
        if self.weights_decay:
            g = g + self.weights_decay * p
        if self.l1_decay:
            g = g + self.l1_decay * np.sign(p)
        v_new = self.gradient_moment * v - lr * g
        return p + v_new, v_new

    def _ensure_velocity(self) -> None:
        if not self.vel_w and self.weights:
            self.vel_w.reset(np.zeros(self.weights.shape,
                                      self.weights.dtype))
        if not self.vel_b and self.bias:
            self.vel_b.reset(np.zeros(self.bias.shape, self.bias.dtype))


class GradientDescentVJP(GradientDescentBase):
    """Generic vjp-driven GD twin: the forward unit's `_apply(params, x)`
    IS the backward model (jax.vjp differentiates it), parameters are
    whatever `param_arrays()` names, and velocities live as vel_<name>.
    Used by the attention/MoE/transformer families, whose backward has no
    2015-reference twin to mirror (the conv/FC units keep hand-derived
    backward paths for reference parity)."""

    def link_forward(self, fwd: Forward):
        names = tuple(fwd.param_arrays())
        self._pnames = names
        self.link_attrs(fwd, "input", "output", *names)
        self._fwd = fwd
        return self

    def initialize(self, device=None, **kwargs: Any):
        if not self.err_output or (
                self._pnames and not getattr(self, self._pnames[0])):
            return False
        for name in self._pnames:
            vname = f"vel_{name}"
            if getattr(self, vname, None) is None \
                    or not getattr(self, vname):
                arr = Array()
                arr.reset(np.zeros(getattr(self, name).shape, np.float32))
                setattr(self, vname, arr)
        if not self.err_input or self.err_input.shape != self.input.shape:
            self.err_input.reset(np.zeros(self.input.shape, np.float32))
        return super().initialize(device=device, **kwargs)

    def _backward_model(self, params, x):
        return self._fwd._apply(params, x)

    def xla_init(self):
        from veles_tpu.ops.optim import SGDConfig, sgd_update
        cfg = SGDConfig(lr=self.learning_rate,
                        momentum=self.gradient_moment,
                        weight_decay=self.weights_decay,
                        l1_decay=self.l1_decay)

        def step(x, params, err_y, vel, lr_scale):
            _, vjp = jax.vjp(
                lambda p, xx: self._backward_model(p, xx), params, x)
            grads, err_x = vjp(err_y)
            new_p, new_v = sgd_update(params, grads, vel, cfg, lr_scale)
            return err_x, new_p, new_v

        self._fn = self.jit(step, donate_argnums=(3,))
        return None

    def numpy_init(self):
        # the vjp is the only backward model on EVERY backend (there is
        # no hand-derived numpy twin for these TPU-era families); build
        # the same jitted step — Array.devmem falls back to default jax
        # placement under NumpyDevice
        return self.xla_init()

    def numpy_run(self) -> None:
        self.xla_run()  # vjp is the only backward model

    def xla_run(self) -> None:
        dv = self.device
        params = {n: getattr(self, n).devmem(dv) for n in self._pnames}
        vel = {n: getattr(self, f"vel_{n}").devmem(dv)
               for n in self._pnames}
        err_y = self.err_output.devmem(dv)
        if hasattr(self, "_err_reshape"):
            # heads whose evaluator-facing output is flattened (N·S, V)
            # while the differentiated model emits (N, S, V)
            err_y = err_y.reshape(self._err_reshape())
        err_x, new_p, new_v = self._fn(
            self.input.devmem(dv), params, err_y, vel,
            jnp.float32(self.lr_scale))
        self.err_input.set_devmem(err_x.reshape(self.input.shape))
        for n in self._pnames:
            getattr(self, n).set_devmem(new_p[n])
            getattr(self, f"vel_{n}").set_devmem(new_v[n])

    def __getstate__(self):
        st = super().__getstate__()
        st.pop("_fwd", None)
        return st


class NNWorkflow:
    """Marker/mixin for workflows whose units form forward+GD chains
    (parity: reference `NNWorkflow`); see standard_workflow.py for the
    declarative builder and the fused train-step compiler."""
