"""Pooling forward units.

Parity: reference `veles/znicz/pooling.py` — `MaxPooling`, `MaxAbsPooling`
(keeps the signed value of the max-|·| element), `AvgPooling`,
`StochasticPooling` (Zeiler & Fergus sampling; device RNG). Edge windows
truncate (ceil-mode geometry), and max variants record flat argmax offsets
for the backward scatter (SURVEY.md §2.8).

TPU-first: forward is `lax.reduce_window` under jit; the backward in
gd_pooling uses `jax.vjp` (max/avg) or the recorded offsets (stochastic)
instead of the reference's hand-written scatter kernels.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Tuple

import numpy as np

from veles_tpu import prng
from veles_tpu.memory import Array
from veles_tpu.ops import reference as ref
from veles_tpu.ops import xla as ox
from veles_tpu.znicz.nn_units import Forward


class Pooling(Forward):
    """Common geometry: ksize (ky, kx), stride defaults to ksize
    (non-overlapping), ceil-mode output size. No trainable parameters —
    weights/bias Arrays stay empty."""

    def __init__(self, workflow=None, ksize: Tuple[int, int] = (2, 2),
                 stride: Optional[Tuple[int, int]] = None,
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.ksize = tuple(ksize)
        self.stride = tuple(stride) if stride is not None else self.ksize

    def output_hw(self) -> Tuple[int, int]:
        _, h, w, _ = self.input.shape
        return ref._pool_windows(self.input.mem, *self.ksize, *self.stride)

    def param_arrays(self):
        return {}

    def initialize(self, device=None, **kwargs: Any):
        if not self.input:
            return False
        n, _, _, c = self.input.shape
        oh, ow = self.output_hw()
        if not self.output or self.output.shape != (n, oh, ow, c):
            self.output.reset(np.zeros((n, oh, ow, c), np.float32))
        return super().initialize(device=device, **kwargs)


class MaxPooling(Pooling):
    use_abs = False

    #: fused-step lowering: "reduce_window" (backward = select_and_scatter)
    #: or "slices" (max-fold over shifted strided slices; backward =
    #: selects + pads). Layer dict key "lowering" overrides per layer;
    #: measured on chip via tools/ablate.py "slicepool" variant.
    lowering = "reduce_window"

    def __init__(self, workflow=None,
                 lowering: Optional[str] = None, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        if lowering is not None:
            if lowering not in ("reduce_window", "slices"):
                raise ValueError(f"unknown maxpool lowering {lowering!r}")
            self.lowering = lowering
        #: flat winner offsets into input (numpy path; backward scatter)
        self.input_offset = Array()

    def xla_init(self):
        self._fn = self.jit(partial(ox.maxpool_forward_with_idx,
                                    ksize=self.ksize, stride=self.stride,
                                    use_abs=self.use_abs))
        return None

    def fused_apply(self, params, x, *, key=None, train=True):
        if self.lowering == "slices":
            # differentiable for max AND maxabs (selects + pads backward)
            return ox.maxpool_forward_slices(x, self.ksize, self.stride,
                                             self.use_abs)
        if self.use_abs:
            # the custom-comparator reduce_window has no reverse-mode rule;
            # the patches/argmax formulation differentiates (gather vjp)
            return ox.maxpool_forward_with_idx(x, self.ksize, self.stride,
                                               use_abs=True)[0]
        # reduce_window flavor: differentiable, no offsets materialized
        return ox.maxpool_forward(x, self.ksize, self.stride, False)

    def numpy_run(self) -> None:
        y, idx = ref.maxpool_forward(self.input.mem, self.ksize, self.stride,
                                     self.use_abs)
        self.output.mem = y
        self.input_offset.mem = idx

    def xla_run(self) -> None:
        y, idx = self._fn(self.input.devmem(self.device))
        self.output.set_devmem(y)
        self.input_offset.set_devmem(idx)


class MaxAbsPooling(MaxPooling):
    use_abs = True


class AvgPooling(Pooling):
    def xla_init(self):
        self._fn = self.jit(partial(ox.avgpool_forward, ksize=self.ksize,
                                    stride=self.stride))
        return None

    def fused_apply(self, params, x, *, key=None, train=True):
        return ox.avgpool_forward(x, self.ksize, self.stride)

    def numpy_run(self) -> None:
        self.output.mem = ref.avgpool_forward(self.input.mem, self.ksize,
                                              self.stride)

    def xla_run(self) -> None:
        self.output.set_devmem(self._fn(self.input.devmem(self.device)))


class StochasticPooling(Pooling):
    """Sampling pooling; the winner offsets recorded at forward time drive
    the backward scatter on BOTH paths (unlike max pooling, re-running the
    forward in backward would re-sample)."""

    def __init__(self, workflow=None, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.input_offset = Array()

    fused_needs_key = True

    def xla_init(self):
        self._fn = self.jit(partial(ox.stochastic_pool_forward_with_idx,
                                    ksize=self.ksize, stride=self.stride))
        return None

    def fused_apply(self, params, x, *, key=None, train=True):
        if not train:  # deterministic at eval: average pooling stand-in
            return ox.avgpool_forward(x, self.ksize, self.stride)
        return ox.stochastic_pool_forward(x, key, self.ksize, self.stride)

    def numpy_run(self) -> None:
        y, idx = ref.stochastic_pool_forward(
            self.input.mem, prng.get().state, self.ksize, self.stride)
        self.output.mem = y
        self.input_offset.mem = idx

    def xla_run(self) -> None:
        y, idx = self._fn(self.input.devmem(self.device),
                          prng.get().next_key())
        self.output.set_devmem(y)
        self.input_offset.set_devmem(idx)


# -- layer-type registration --------------------------------------------------
from veles_tpu.znicz import standard_workflow as _sw  # noqa: E402

_sw.LAYER_TYPES.update({
    "max_pooling": MaxPooling,
    "maxabs_pooling": MaxAbsPooling,
    "avg_pooling": AvgPooling,
    "stochastic_pooling": StochasticPooling,
})
