"""Pooling forward units.

Parity: reference `veles/znicz/pooling.py` — `MaxPooling`, `MaxAbsPooling`
(keeps the signed value of the max-|·| element), `AvgPooling`,
`StochasticPooling` (Zeiler & Fergus sampling; device RNG). Edge windows
truncate (ceil-mode geometry), and max variants record flat argmax offsets
for the backward scatter (SURVEY.md §2.8).

TPU-first: forward is `lax.reduce_window` under jit; the backward in
gd_pooling uses `jax.vjp` (max/avg) or the recorded offsets (stochastic)
instead of the reference's hand-written scatter kernels.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Tuple

import numpy as np

from veles_tpu import prng
from veles_tpu.memory import Array
from veles_tpu.ops import reference as ref
from veles_tpu.ops import variants
from veles_tpu.ops import xla as ox
from veles_tpu.znicz.nn_units import Forward


class Pooling(Forward):
    """Common geometry: ksize (ky, kx), stride defaults to ksize
    (non-overlapping), ceil-mode output size. No trainable parameters —
    weights/bias Arrays stay empty."""

    def __init__(self, workflow=None, ksize: Tuple[int, int] = (2, 2),
                 stride: Optional[Tuple[int, int]] = None,
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.ksize = tuple(ksize)
        self.stride = tuple(stride) if stride is not None else self.ksize

    def output_hw(self) -> Tuple[int, int]:
        _, h, w, _ = self.input.shape
        return ref._pool_windows(self.input.mem, *self.ksize, *self.stride)

    def param_arrays(self):
        return {}

    def initialize(self, device=None, **kwargs: Any):
        if not self.input:
            return False
        n, _, _, c = self.input.shape
        oh, ow = self.output_hw()
        if not self.output or self.output.shape != (n, oh, ow, c):
            self.output.reset(np.zeros((n, oh, ow, c), np.float32))
        return super().initialize(device=device, **kwargs)


class _PoolShimMeta(type):
    """Deprecation shim: `MaxPooling.lowering = "slices"` (the hand-flip
    knob) writes through to the lowering-variant registry; the fused
    build path consults `variants.resolve("maxpool")` at trace time."""

    @property
    def lowering(cls) -> str:
        return variants.effective("maxpool")

    @lowering.setter
    def lowering(cls, value) -> None:
        variants.warn_deprecated_knob(
            "MaxPooling.lowering", f'variants.select("maxpool", {value!r})')
        variants.select("maxpool", value)   # validates the name


class MaxPooling(Pooling, metaclass=_PoolShimMeta):
    """Cross-op fusion note (ISSUE 13): when the searched `lrn_maxpool`
    winner is a FUSED point and this unit immediately follows an LRN in
    the fused chain (max flavor only — MaxAbsPooling never fuses — and
    no per-layer overrides on either side), the NORMALIZATION unit
    claims this unit's work: FusedTrainStep traces the one-pass fused
    kernel for the pair and this unit becomes a pass-through for that
    trace. Granular mode and every composed selection are untouched."""

    use_abs = False

    #: lowering-variant registry op (candidates: "reduce_window" —
    #: backward = select_and_scatter — or "slices" — max-fold over
    #: shifted strided slices, backward = selects + pads). The layer
    #: dict key "lowering" stays a per-layer override; the global
    #: choice is the registry's (tools/autotune.py).
    variant_op = "maxpool"

    #: class-level default so instances restored from PRE-registry
    #: pickled snapshots (whose __dict__ lacks the attribute) still
    #: resolve/report instead of raising AttributeError
    variant_override = None

    def __init__(self, workflow=None,
                 lowering: Optional[str] = None, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        #: explicit per-layer lowering (wins over the registry selection)
        self.variant_override = None
        if lowering is not None:
            variants.get("maxpool", lowering)   # validates
            self.variant_override = lowering
        #: flat winner offsets into input (numpy path; backward scatter)
        self.input_offset = Array()

    @property
    def lowering(self) -> str:
        return self.variant_override or variants.effective("maxpool")

    def variant_signature(self):
        # batch dim excluded: tune-then-inherit across batch sizes
        if self.variant_override is not None or not self.input:
            return None
        return {"sample_shape": list(self.input.shape[1:]),
                "dtype": str(np.asarray(self.input.mem).dtype),
                "params": {"ksize": list(self.ksize),
                           "stride": list(self.stride),
                           "use_abs": bool(self.use_abs)}}

    def xla_init(self):
        self._fn = self.jit(partial(ox.maxpool_forward_with_idx,
                                    ksize=self.ksize, stride=self.stride,
                                    use_abs=self.use_abs))
        return None

    def fused_apply(self, params, x, *, key=None, train=True):
        v = variants.resolve("maxpool", unit=self)
        return v.apply(x, self.ksize, self.stride, self.use_abs)

    def numpy_run(self) -> None:
        y, idx = ref.maxpool_forward(self.input.mem, self.ksize, self.stride,
                                     self.use_abs)
        self.output.mem = y
        self.input_offset.mem = idx

    def xla_run(self) -> None:
        y, idx = self._fn(self.input.devmem(self.device))
        self.output.set_devmem(y)
        self.input_offset.set_devmem(idx)


class MaxAbsPooling(MaxPooling):
    use_abs = True


class AvgPooling(Pooling):
    def xla_init(self):
        self._fn = self.jit(partial(ox.avgpool_forward, ksize=self.ksize,
                                    stride=self.stride))
        return None

    def fused_apply(self, params, x, *, key=None, train=True):
        return ox.avgpool_forward(x, self.ksize, self.stride)

    def numpy_run(self) -> None:
        self.output.mem = ref.avgpool_forward(self.input.mem, self.ksize,
                                              self.stride)

    def xla_run(self) -> None:
        self.output.set_devmem(self._fn(self.input.devmem(self.device)))


class StochasticPooling(Pooling):
    """Sampling pooling; the winner offsets recorded at forward time drive
    the backward scatter on BOTH paths (unlike max pooling, re-running the
    forward in backward would re-sample)."""

    def __init__(self, workflow=None, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.input_offset = Array()

    fused_needs_key = True

    def xla_init(self):
        self._fn = self.jit(partial(ox.stochastic_pool_forward_with_idx,
                                    ksize=self.ksize, stride=self.stride))
        return None

    def fused_apply(self, params, x, *, key=None, train=True):
        if not train:  # deterministic at eval: average pooling stand-in
            return ox.avgpool_forward(x, self.ksize, self.stride)
        return ox.stochastic_pool_forward(x, key, self.ksize, self.stride)

    def numpy_run(self) -> None:
        y, idx = ref.stochastic_pool_forward(
            self.input.mem, prng.get().state, self.ksize, self.stride)
        self.output.mem = y
        self.input_offset.mem = idx

    def xla_run(self) -> None:
        y, idx = self._fn(self.input.devmem(self.device),
                          prng.get().next_key())
        self.output.set_devmem(y)
        self.input_offset.set_devmem(idx)


# -- layer-type registration --------------------------------------------------
from veles_tpu.znicz import standard_workflow as _sw  # noqa: E402

_sw.LAYER_TYPES.update({
    "max_pooling": MaxPooling,
    "maxabs_pooling": MaxAbsPooling,
    "avg_pooling": AvgPooling,
    "stochastic_pooling": StochasticPooling,
})
