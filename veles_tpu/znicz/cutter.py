"""Cutter: spatial crop unit.

Parity: reference `veles/znicz/cutter.py` (`Cutter` [M], SURVEY.md §2.8) —
crops border pixels off the spatial dims (used by autoencoder pipelines to
trim deconv overshoot); the gradient zero-pads the error back.
"""

from __future__ import annotations

from typing import Any, Tuple

import numpy as np

from veles_tpu.ops import reference as ref
from veles_tpu.ops import xla as ox
from veles_tpu.znicz.nn_units import Forward, GradientDescentBase, register_gd


class Cutter(Forward):
    """y = x[:, cy:-cy, cx:-cx, :] for crop=(cy, cx)."""

    def __init__(self, workflow=None, crop: Tuple[int, int] = (1, 1),
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.crop = tuple(crop)

    def param_arrays(self):
        return {}

    def initialize(self, device=None, **kwargs: Any):
        if not self.input:
            return False
        n, h, w, c = self.input.shape
        cy, cx = self.crop
        out = (n, h - 2 * cy, w - 2 * cx, c)
        if not self.output or self.output.shape != out:
            self.output.reset(np.zeros(out, np.float32))
        return super().initialize(device=device, **kwargs)

    def xla_init(self):
        crop = self.crop
        self._fn = self.jit(lambda x: ox.cut_forward(x, crop))
        return None

    def fused_apply(self, params, x, *, key=None, train=True):
        return ox.cut_forward(x, self.crop)

    def numpy_run(self) -> None:
        self.output.mem = ref.cut_forward(self.input.mem, self.crop)

    def xla_run(self) -> None:
        self.output.set_devmem(self._fn(self.input.devmem(self.device)))


@register_gd(Cutter)
class GDCutter(GradientDescentBase):
    def link_forward(self, fwd) -> "GDCutter":
        self.link_attrs(fwd, "input")
        self._crop = fwd.crop
        return self

    def initialize(self, device=None, **kwargs: Any):
        if not self.err_output:
            return False
        if not self.err_input or self.err_input.shape != self.input.shape:
            self.err_input.reset(np.zeros(self.input.shape, np.float32))
        return super().initialize(device=device, **kwargs)

    def xla_init(self):
        shape, crop = tuple(self.input.shape), self._crop
        self._fn = self.jit(lambda e: ox.cut_backward(e, shape, crop))
        return None

    def numpy_run(self) -> None:
        self.err_input.mem = ref.cut_backward(
            self.err_output.mem, self.input.shape, self._crop)

    def xla_run(self) -> None:
        self.err_input.set_devmem(self._fn(self.err_output.devmem(self.device)))


from veles_tpu.znicz import standard_workflow as _sw  # noqa: E402

_sw.LAYER_TYPES.update({"cutter": Cutter})
