"""Kohonen self-organizing map units.

Parity: reference `veles/znicz/kohonen.py` (`KohonenForward`,
`KohonenTrainer` — SURVEY.md §2.8; config 4 in BASELINE.json:9). The
trainer's update is neighborhood-decay weight movement, NOT gradient
descent: every neuron moves toward the sample weighted by a Gaussian over
grid distance to the winner, with learning rate and neighborhood radius
decaying over epochs.

TPU-first: the winner search is one distance matmul on the MXU; the
order-dependent per-sample update is a `lax.scan` so a whole minibatch of
updates is a single compiled computation (ops.xla.kohonen_update).
"""

from __future__ import annotations

from typing import Any, Tuple

import numpy as np

from veles_tpu import prng
from veles_tpu.memory import Array
from veles_tpu.ops import reference as ref
from veles_tpu.ops import xla as ox
from veles_tpu.znicz.nn_units import Forward


def make_grid(shape: Tuple[int, int]) -> np.ndarray:
    """(rows*cols, 2) neuron coordinates for the neighborhood metric."""
    rows, cols = shape
    yy, xx = np.mgrid[0:rows, 0:cols]
    return np.stack([yy.ravel(), xx.ravel()], axis=1).astype(np.float32)


class KohonenForward(Forward):
    """Winner-take-all: output[i] = argmin_k ||x_i − w_k||² (int32)."""

    def __init__(self, workflow=None, shape: Tuple[int, int] = (8, 8),
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.shape = tuple(shape)
        #: per-neuron winner counts over the run (reference KohonenHits
        #: plotter's data source)
        self.hits = Array()

    @property
    def n_neurons(self) -> int:
        return self.shape[0] * self.shape[1]

    def param_arrays(self):
        return {}  # weights belong to (and are trained by) the trainer

    def initialize(self, device=None, **kwargs: Any):
        if not self.input:
            return False
        if not self.weights:
            return False  # linked from the trainer
        n = self.input.shape[0]
        if not self.output or self.output.shape != (n,):
            self.output.reset(np.zeros((n,), np.int32))
        if not self.hits:
            self.hits.reset(np.zeros((self.n_neurons,), np.int64))
        return super().initialize(device=device, **kwargs)

    def xla_init(self):
        self._fn = self.jit(ox.kohonen_forward)
        return None

    def numpy_run(self) -> None:
        x = self.input.mem.reshape(len(self.input), -1)
        winners = ref.kohonen_forward(x, self.weights.mem)
        self.output.mem = winners.astype(np.int32)
        np.add.at(self.hits.mem, winners, 1)

    def xla_run(self) -> None:
        d = self.device
        x = self.input.devmem(d).reshape(len(self.input), -1)
        winners = self._fn(x, self.weights.devmem(d))
        self.output.set_devmem(winners)
        # the hits histogram is host-side int64 state scattered with
        # np.add.at (no jax scatter-add twin on the granular path): the
        # winners pull is the unit's one deliberate per-minibatch sync
        # velint: disable=hot-sync
        np.add.at(self.hits.mem, np.asarray(winners), 1)


class KohonenTrainer(Forward):
    """Owns the SOM weights (n_neurons, D) and applies the neighborhood
    update per minibatch. lr/sigma decay exponentially per EPOCH (driven
    by the linked decision's epoch counter), matching the reference's
    time-decay schedules."""

    def __init__(self, workflow=None, shape: Tuple[int, int] = (8, 8),
                 learning_rate: float = 0.5, sigma: float = None,
                 lr_tau: float = 20.0, sigma_tau: float = 20.0,
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.shape = tuple(shape)
        self.learning_rate = learning_rate
        self.sigma0 = sigma if sigma is not None else max(self.shape) / 2.0
        self.lr_tau = lr_tau
        self.sigma_tau = sigma_tau
        self.grid = Array()
        self.epoch_number = 0  # linked from a decision unit when present

    @property
    def n_neurons(self) -> int:
        return self.shape[0] * self.shape[1]

    def link_decision(self, decision) -> "KohonenTrainer":
        self.link_attrs(decision, "epoch_number")
        return self

    def current_lr_sigma(self) -> Tuple[float, float]:
        t = float(self.epoch_number)
        lr = self.learning_rate * float(np.exp(-t / self.lr_tau))
        sigma = self.sigma0 * float(np.exp(-t / self.sigma_tau))
        return lr, max(sigma, 1e-3)

    def initialize(self, device=None, **kwargs: Any):
        if not self.input:
            return False
        d = int(np.prod(self.input.shape[1:]))
        if not self.weights:
            gen = prng.get()
            self.weights.reset(gen.fill_uniform(
                (self.n_neurons, d), -0.1, 0.1, np.float32))
        if not self.grid:
            self.grid.reset(make_grid(self.shape))
        return super().initialize(device=device, **kwargs)

    def xla_init(self):
        self._fn = self.jit(ox.kohonen_update)
        return None

    def numpy_run(self) -> None:
        x = self.input.mem.reshape(len(self.input), -1)
        lr, sigma = self.current_lr_sigma()
        self.weights.mem = ref.kohonen_update(
            x, self.weights.mem, self.grid.mem, lr, sigma)

    def xla_run(self) -> None:
        d = self.device
        x = self.input.devmem(d).reshape(len(self.input), -1)
        lr, sigma = self.current_lr_sigma()
        self.weights.set_devmem(self._fn(
            x, self.weights.devmem(d), self.grid.devmem(d),
            np.float32(lr), np.float32(sigma)))
