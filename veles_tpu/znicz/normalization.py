"""Local response normalization units (AlexNet-style, across channels).

Parity: reference `veles/znicz/normalization.py` — forward + dedicated
backward kernel (SURVEY.md §2.8; "normalization" named in BASELINE.json:4).

TPU-first: forward is a reduce_window over the channel axis inside jit; the
backward is `jax.vjp` of the forward (SURVEY.md §7 listed LRN backward as a
Pallas candidate — vjp-of-reduce_window fuses well enough on XLA that no
hand kernel is needed).
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import numpy as np

from veles_tpu.ops import reference as ref
from veles_tpu.ops import variants
from veles_tpu.ops import xla as ox
from veles_tpu.znicz.nn_units import Forward, GradientDescentBase, register_gd


def _lrn_shim_select() -> None:
    """Map the legacy two-bool knob state onto ONE registry selection."""
    variants.select(
        "lrn",
        "pallas_one_pass" if LRNormalizerForward._shim_prefer_pallas
        else ("cached_residual" if LRNormalizerForward._shim_cache_bwd
              else "banded_matmul"))


class _LRNShimMeta(type):
    """Deprecation shims: `LRNormalizerForward.prefer_pallas = x` /
    `.cache_bwd = x` (the r4/r5 hand-flip knobs) write through to the
    lowering-variant registry — the fused-step build path no longer
    reads these attributes (it consults `variants.resolve("lrn")` at
    trace time)."""

    @property
    def prefer_pallas(cls) -> bool:
        return cls._shim_prefer_pallas

    @prefer_pallas.setter
    def prefer_pallas(cls, value) -> None:
        variants.warn_deprecated_knob(
            "LRNormalizerForward.prefer_pallas",
            'variants.select("lrn", "pallas_one_pass")')
        cls._shim_prefer_pallas = bool(value)
        _lrn_shim_select()

    @property
    def cache_bwd(cls) -> bool:
        return cls._shim_cache_bwd

    @cache_bwd.setter
    def cache_bwd(cls, value) -> None:
        variants.warn_deprecated_knob(
            "LRNormalizerForward.cache_bwd",
            'variants.select("lrn", "cached_residual")')
        cls._shim_cache_bwd = bool(value)
        _lrn_shim_select()


class LRNormalizerForward(Forward, metaclass=_LRNShimMeta):
    """y = x · (k + α·Σ_window x²)^(−β), window of n channels.

    Cross-op fusion (ISSUE 13): when the searched `lrn_maxpool` winner
    is a FUSED point and this unit's immediate successor in the fused
    chain is a max pooling (max flavor, no per-layer overrides on either
    side), this unit CLAIMS the pooling's work — FusedTrainStep traces
    the one-pass `lrn_maxpool_pallas` kernel for the pair and the
    pooling unit passes through for that trace (fusion_pairs() names the
    claim; variant_table reports the fused winner for both member ops).
    Symmetrically, a `conv_stem` winner with `epi=lrn` lets the
    PRECEDING stem conv claim THIS unit's work as its epilogue."""

    #: lowering-variant registry op this unit consults at fused trace
    #: time (candidates: banded_matmul | cached_residual |
    #: pallas_one_pass; tools/autotune.py picks and persists the winner)
    variant_op = "lrn"

    def __init__(self, workflow=None, k: float = 2.0, alpha: float = 1e-4,
                 beta: float = 0.75, n: int = 5, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        if n % 2 == 0:
            # all four twins (XLA shifted-adds, Pallas, numpy reference,
            # C++ engine) use a ±n//2 window; even n would mean n+1 taps
            raise ValueError(f"LRN window n must be odd, got {n}")
        self.k = k
        self.alpha = alpha
        self.beta = beta
        self.n = n

    def param_arrays(self):
        return {}

    def initialize(self, device=None, **kwargs: Any):
        if not self.input:
            return False
        if not self.output or self.output.shape != self.input.shape:
            self.output.reset(np.zeros(self.input.shape, np.float32))
        return super().initialize(device=device, **kwargs)

    def xla_init(self):
        self._fn = self.jit(partial(ox.lrn_forward, k=self.k,
                                    alpha=self.alpha, beta=self.beta,
                                    n=self.n))
        return None

    #: DEPRECATED shim state (see _LRNShimMeta): the variant choice lives
    #: in the registry now; these only back the legacy attribute reads.
    _shim_prefer_pallas = False
    _shim_cache_bwd = False

    @property
    def prefer_pallas(self) -> bool:
        return type(self)._shim_prefer_pallas

    @property
    def cache_bwd(self) -> bool:
        return type(self)._shim_cache_bwd

    def variant_signature(self):
        """Autotune cache-key payload (None = not tunable as configured).
        Batch dim excluded ON PURPOSE: winners tuned at one batch must
        apply when bench/training runs at another (tune-then-inherit)."""
        if getattr(self, "variant_override", None) is not None \
                or not self.input:
            return None
        return {"sample_shape": list(self.input.shape[1:]),
                "dtype": str(np.asarray(self.input.mem).dtype),
                "params": {"k": self.k, "alpha": self.alpha,
                           "beta": self.beta, "n": self.n}}

    def fused_apply(self, params, x, *, key=None, train=True):
        v = variants.resolve("lrn", unit=self)
        return v.apply(x, k=self.k, alpha=self.alpha, beta=self.beta,
                       n=self.n)

    def numpy_run(self) -> None:
        self.output.mem = ref.lrn_forward(self.input.mem, self.k, self.alpha,
                                          self.beta, self.n)

    def xla_run(self) -> None:
        self.output.set_devmem(self._fn(self.input.devmem(self.device)))


@register_gd(LRNormalizerForward)
class LRNormalizerBackward(GradientDescentBase):
    def __init__(self, workflow=None, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.k = 2.0
        self.alpha = 1e-4
        self.beta = 0.75
        self.n = 5

    def link_forward(self, fwd):
        self.k, self.alpha, self.beta, self.n = (fwd.k, fwd.alpha, fwd.beta,
                                                 fwd.n)
        self.link_attrs(fwd, "input", "output")
        return self

    def initialize(self, device=None, **kwargs: Any):
        if not self.err_output or not self.input:
            return False
        if not self.err_input or self.err_input.shape != self.input.shape:
            self.err_input.reset(np.zeros(self.input.shape, np.float32))
        return super().initialize(device=device, **kwargs)

    def xla_init(self):
        fwd = partial(ox.lrn_forward, k=self.k, alpha=self.alpha,
                      beta=self.beta, n=self.n)

        def step(x, err_y):
            _, vjp = jax.vjp(fwd, x)
            (err_x,) = vjp(err_y)
            return err_x

        self._fn = self.jit(step)
        return None

    def numpy_run(self) -> None:
        self.err_input.mem = ref.lrn_backward(
            self.input.mem, self.err_output.mem, self.k, self.alpha,
            self.beta, self.n)

    def xla_run(self) -> None:
        d = self.device
        self.err_input.set_devmem(
            self._fn(self.input.devmem(d), self.err_output.devmem(d)))


class InputNormalize(Forward):
    """On-device input normalization: y = x·scale + offset − mean_image.

    The ImageNet-rate input path (loader/memmap.py `emit="uint8"`): the
    loader ships RAW uint8 minibatches (4x less host conversion + H2D
    traffic) and this paramless leading layer does the float conversion,
    scaling and mean subtraction ON DEVICE, where it fuses into the first
    conv's HBM read. Works identically in granular and fused modes; the
    backward is the constant `scale` (affine transform)."""

    def __init__(self, workflow=None, scale: float = 1.0 / 127.5,
                 offset: float = -1.0, use_loader_mean: bool = True,
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.scale = scale
        self.offset = offset
        self.use_loader_mean = use_loader_mean
        self._mean = None

    def param_arrays(self):
        return {}

    def link_loader(self, loader) -> None:
        self._loader = loader

    def initialize(self, device=None, **kwargs: Any):
        if not self.input:
            return False
        if self.use_loader_mean and self._mean is None:
            self._mean = getattr(getattr(self, "_loader", None),
                                 "mean_image", None)
        if not self.output or self.output.shape != self.input.shape:
            self.output.reset(np.zeros(self.input.shape, np.float32))
        return super().initialize(device=device, **kwargs)

    def _apply(self, params, x):
        import jax.numpy as jnp
        # keep an already-cast compute dtype (the fused step's bf16 entry
        # cast); only integer inputs are promoted
        dt = x.dtype if jnp.issubdtype(x.dtype, jnp.floating) \
            else jnp.float32
        y = x.astype(dt) * jnp.asarray(self.scale, dt) \
            + jnp.asarray(self.offset, dt)
        if self._mean is not None:
            y = y - jnp.asarray(self._mean, dt)
        return y

    def fused_apply(self, params, x, *, key=None, train=True):
        return self._apply(params, x)

    def xla_init(self):
        self._fn = self.jit(lambda x: self._apply({}, x))
        return None

    def numpy_run(self) -> None:
        y = self.input.mem.astype(np.float32) * self.scale + self.offset
        if self._mean is not None:
            y = y - self._mean
        self.output.mem = y

    def xla_run(self) -> None:
        self.output.set_devmem(self._fn(self.input.devmem(self.device)))

    def __getstate__(self):
        d = super().__getstate__()
        d["_loader"] = None   # re-linked by link_loader on restore
        return d


from veles_tpu.znicz.nn_units import GradientDescentVJP, register_gd \
    # noqa: E402


@register_gd(InputNormalize)
class GDInputNormalize(GradientDescentVJP):
    """err_input = err_output · scale — the closed-form vjp of the affine
    transform, used directly because the granular input may be uint8
    (non-differentiable primal); paramless, so there is no update."""

    def xla_init(self):
        scale = self._fwd.scale
        self._fn = self.jit(lambda e: e * scale)
        return None

    def numpy_run(self) -> None:
        self.err_input.mem = self.err_output.mem * self._fwd.scale

    def xla_run(self) -> None:
        self.err_input.set_devmem(
            self._fn(self.err_output.devmem(self.device)))


# -- layer-type registration --------------------------------------------------
from veles_tpu.znicz import standard_workflow as _sw  # noqa: E402

_sw.LAYER_TYPES.update({
    "norm": LRNormalizerForward,
    "lrn": LRNormalizerForward,
    "input_normalize": InputNormalize,
})
