"""Convolutional forward units.

Parity: reference `veles/znicz/conv.py` — `Conv` (linear), `ConvTanh`,
`ConvRELU` (softplus flavor), `ConvStrictRELU`, `ConvSigmoid`; stride /
padding "sliding window" semantics, implicit-GEMM kernels (SURVEY.md §2.8).

TPU-first: layouts are NHWC/HWIO (what XLA tiles best onto the MXU) and the
whole conv+bias+activation is one jitted `lax.conv_general_dilated` call —
the reference's hand-blocked OpenCL/CUDA implicit-GEMM kernels have no
analog here by design.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Tuple

import numpy as np

from veles_tpu.ops import reference as ref
from veles_tpu.ops import variants
from veles_tpu.ops import xla as ox
from veles_tpu.znicz.nn_units import Forward


class Conv(Forward):
    """y = act(conv2d(x, W) + b); x: (N,H,W,C), W: (ky,kx,C,n_kernels)."""

    activation = "linear"

    #: lowering-variant registry op for the strided thin-channel stem
    #: decision (candidates "direct" | "s2d"); consulted only when the
    #: layer's s2d knob is "auto" — explicit "on"/"off" stays a
    #: per-layer override, exactly like MaxPooling's `lowering` key.
    variant_op = "conv_stem"

    def __init__(self, workflow=None, n_kernels: int = 16,
                 kx: int = 3, ky: int = 3,
                 stride: Tuple[int, int] = (1, 1),
                 padding: Tuple[int, int] = (0, 0),
                 s2d: str = "auto",
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.n_kernels = n_kernels
        self.kx = kx
        self.ky = ky
        self.stride = tuple(stride)
        self.padding = tuple(padding)
        #: space-to-depth rewrite for thin-channel strided stems
        #: (ops.xla.conv2d_space_to_depth — exact, MXU-tile-friendly):
        #: "auto" = on when stride is square >1 and cin < 8; "on"/"off"
        #: force. Numerics identical either way (equivalence-tested).
        #: DEFAULT "auto" since r4's on-chip A/B: the rewrite won the
        #: AlexNet step 8,656 → 9,377 samples/s (tools/ablate.py).
        if s2d not in ("off", "on", "auto"):
            raise ValueError(f"s2d must be 'off'|'on'|'auto', got {s2d!r}")
        if s2d == "on" and not (self.stride[0] == self.stride[1]
                                and self.stride[0] > 1):
            raise ValueError(
                f"s2d='on' needs a square stride > 1 (got "
                f"{self.stride}): the rewrite repacks stride blocks")
        self.s2d = s2d

    def _s2d_applicable(self, cin: int) -> bool:
        """The auto heuristic's applicability test: a square-strided
        thin-channel stem (cin < 8 fills under 8/128 of an MXU tile)."""
        sy, sx = self.stride
        return sy == sx and sy > 1 and cin < 8

    def _use_s2d(self, cin: int) -> bool:
        if self.s2d == "on":
            return True         # applicability validated in __init__
        if self.s2d == "off":
            return False
        # "auto": the registry owns the decision for applicable stems
        # (default "s2d" — the r4 on-chip winner; tools/autotune.py can
        # re-measure and flip it per device/shape). A GENERATED winner
        # (gen[pack=..,acc=..], ops.templates) carries its packing in
        # the pack axis — the fused path consumes the full variant
        # apply; this boolean serves the granular xla_init path.
        if not self._s2d_applicable(cin):
            return False
        name = variants.resolve("conv_stem", unit=self).name
        if name in ("s2d", "direct"):
            return name == "s2d"
        from veles_tpu.ops import templates
        for t in templates.templates_for("conv_stem"):
            cfg = t.parse(name)
            if cfg is not None:
                return cfg.get("pack") == "s2d"
        return False

    def variant_effective(self):
        """The conv_stem lowering THIS layer actually traces, for
        variant_table() reporting: the per-layer s2d="on"/"off" override
        bypasses the registry, and an auto layer the rewrite can't apply
        to (stride 1 / wide cin) traces direct regardless of the
        selection — reporting the raw registry resolution for those
        would name a variant the step never traced. None = this layer
        carries no stem decision worth reporting. An `epi=lrn` winner
        reports its epi=none TWIN here: this method serves UNCLAIMED
        layers (FusedTrainStep skips claimed pairs and reports them
        itself), and an unclaimed stem passes no epilogue — the traced
        program is the epilogue-less one (the attention drop=0-twin
        rule)."""
        if self.s2d == "on":
            return "s2d"
        if self.s2d == "off":
            return "direct"
        if not self.input or not self._s2d_applicable(self.input.shape[-1]):
            return None
        name = variants.resolve("conv_stem", unit=self).name
        from veles_tpu.ops import templates
        if templates.fusion_config("conv_stem", name) is not None:
            for t in templates.templates_for("conv_stem"):
                cfg = t.parse(name)
                if cfg is not None and t.fuse_axis is not None:
                    return t.name({**cfg, t.fuse_axis: "none"})
        return name

    def variant_signature(self):
        """Tunable only when s2d='auto' AND the rewrite applies here."""
        if self.s2d != "auto" or not self.input \
                or not self._s2d_applicable(self.input.shape[-1]):
            return None
        # batch dim excluded: tune-then-inherit across batch sizes
        return {"sample_shape": list(self.input.shape[1:]),
                "dtype": str(np.asarray(self.input.mem).dtype),
                "params": {"n_kernels": self.n_kernels,
                           "kx": self.kx, "ky": self.ky,
                           "stride": list(self.stride),
                           "padding": list(self.padding),
                           "activation": self.activation}}

    def output_hw(self) -> Tuple[int, int]:
        _, h, w, _ = self.input.shape
        sy, sx = self.stride
        ph, pw = self.padding
        return ((h + 2 * ph - self.ky) // sy + 1,
                (w + 2 * pw - self.kx) // sx + 1)

    def initialize(self, device=None, **kwargs: Any):
        if not self.input:
            return False
        n, h, w, c = self.input.shape
        fan_in = self.kx * self.ky * c
        self.init_params((self.ky, self.kx, c, self.n_kernels), fan_in)
        oh, ow = self.output_hw()
        if not self.output or self.output.shape != (n, oh, ow, self.n_kernels):
            self.output.reset(np.zeros((n, oh, ow, self.n_kernels),
                                       np.float32))
        return super().initialize(device=device, **kwargs)

    def xla_init(self):
        self._fn = self.jit(partial(
            ox.conv2d_forward, stride=self.stride, padding=self.padding,
            activation=self.activation,
            s2d=self._use_s2d(self.input.shape[-1])))
        return None

    def fused_apply(self, params, x, *, key=None, train=True):
        if self.s2d == "auto" and self._s2d_applicable(x.shape[-1]):
            # the registry owns auto-mode applicable stems END TO END:
            # a generated winner's extra axes (the f32-accumulator
            # pin) trace here, not just its packing bit. Hand-written
            # names resolve to exactly the previous lowering.
            v = variants.resolve("conv_stem", unit=self)
            return v.apply(x, params["weights"], params["bias"],
                           self.stride, self.padding, self.activation)
        return ox.conv2d_forward(x, params["weights"], params["bias"],
                                 self.stride, self.padding,
                                 self.activation,
                                 s2d=self._use_s2d(x.shape[-1]))

    def numpy_run(self) -> None:
        self.output.mem = ref.conv2d_forward(
            self.input.mem, self.weights.mem, self.bias.mem,
            self.stride, self.padding, self.activation)

    def xla_run(self) -> None:
        d = self.device
        self.output.set_devmem(self._fn(
            self.input.devmem(d), self.weights.devmem(d),
            self.bias.devmem(d)))


class ConvTanh(Conv):
    activation = "tanh"


class ConvRELU(Conv):
    activation = "relu"


class ConvStrictRELU(Conv):
    activation = "strictrelu"


class ConvSigmoid(Conv):
    activation = "sigmoid"


# -- layer-type registration (import-time side effect; see standard_workflow
#    docstring for the cycle-avoidance rationale) -----------------------------
from veles_tpu.znicz import standard_workflow as _sw  # noqa: E402

_sw.LAYER_TYPES.update({
    "conv": Conv,
    "conv_tanh": ConvTanh,
    "conv_relu": ConvRELU,
    "conv_strictrelu": ConvStrictRELU,
    "conv_sigmoid": ConvSigmoid,
})
