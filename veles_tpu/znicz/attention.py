"""Multi-head self-attention units.

Not present in the reference (SURVEY.md §5.7: no attention anywhere in the
2015 codebase) — added because long-context support is first-class in the
TPU build. Follows the house unit pattern: a Forward twin with a
vjp-driven GD twin, fused_apply for the one-step compiled path, and a
`seq_axis_name` attribute (set by FusedTrainStep's "seq" mode) that
routes fused_apply to the ring or Ulysses sequence-parallel kernels over
the mesh "seq" axis (ops/attention.py) — sequence parallelism is
trainable end-to-end, not ops-level only.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from veles_tpu.memory import Array
from veles_tpu.ops import attention as oa
from veles_tpu.ops import variants
from veles_tpu.znicz.nn_units import (Forward, GradientDescentVJP,
                                      register_gd)


class MultiHeadAttention(Forward):
    """Self-attention block: input (N, S, E) -> output (N, S, E).
    Params: wq/wk/wv (E, H·D), wo (H·D, E). `parallel_mode` selects the
    in-mesh kernel for the fused path: "local" | "ring" | "ulysses"."""

    #: lowering-variant registry op the LOCAL long-S path consults at
    #: trace time (candidates: xla_mha | pallas | the search-generated
    #: pallas[blk_q=..,blk_k=..,kv_order=..] points from ops.templates)
    variant_op = "flash_attn"

    def __init__(self, workflow=None, n_heads: int = 4,
                 head_dim: int = None, causal: bool = True,
                 parallel_mode: str = "local", residual: bool = False,
                 use_flash: str = "auto", **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.n_heads = n_heads
        self.head_dim = head_dim
        self.causal = causal
        self.parallel_mode = parallel_mode
        #: y = x + attn(x) — the transformer-block form. Purely local
        #: (element-wise add), so it composes with every parallel_mode.
        self.residual = residual
        #: mesh axis name the sequence dim is sharded over; set by
        #: FusedTrainStep's "seq" mode so fused_apply runs the ring /
        #: Ulysses kernel instead of the local one. None = local.
        self.seq_axis_name = None
        #: mesh axis for megatron TP under shard_map (heads split across
        #: the model axis: wq/wk/wv column-sharded, wo row-sharded + one
        #: psum). Set by FusedTrainStep at trace time; None = whole.
        self.model_axis_name = None
        #: "auto": the Pallas flash kernel on TPU when S is long enough to
        #: beat the XLA einsum (and divisible into blocks); "on"/"off"
        #: force it. See ops/pallas_kernels.flash_attention_pallas.
        self.use_flash = use_flash
        self.wq = Array()
        self.wk = Array()
        self.wv = Array()
        self.wo = Array()

    def param_arrays(self) -> Dict[str, Array]:
        return {"wq": self.wq, "wk": self.wk, "wv": self.wv, "wo": self.wo}

    def initialize(self, device=None, **kwargs: Any):
        if not self.input:
            return False
        n, s, e = self.input.shape
        if self.head_dim is None:
            assert e % self.n_heads == 0, (e, self.n_heads)
            self.head_dim = e // self.n_heads
        hd = self.n_heads * self.head_dim
        if not self.wq:
            std = self.weights_stddev or self.default_stddev(e)
            for arr, shape in ((self.wq, (e, hd)), (self.wk, (e, hd)),
                               (self.wv, (e, hd)), (self.wo, (hd, e))):
                arr.reset(self._fill(shape, self.weights_filling, std))
        if not self.output or self.output.shape != (n, s, e):
            self.output.reset(np.zeros((n, s, e), np.float32))
        return super().initialize(device=device, **kwargs)

    def _flash_ok(self, s: int) -> bool:
        if self.use_flash == "off":
            return False
        if self.use_flash == "on":
            return True
        # auto: long sequences where a pallas path can run (a real TPU,
        # or interpret mode — the CPU autotune/search context); the
        # kernel fits its blocks to any S divisible by 128
        return variants.pallas_ok() and s >= 4096 and s % 128 == 0

    def _flash_variant(self):
        """The registry variant the local long-S path traces. use_flash
        ="on" forces the effective selection past the pallas_ok() gate
        (interpreter-mode tests drive the kernel on CPU); "auto" resolves
        normally, so GSPMD (allow_pallas cleared by the step) and
        pallas-less backends fall back to the einsum."""
        if self.use_flash == "on" and getattr(self, "allow_pallas", True):
            return variants.get("flash_attn",
                                variants.effective("flash_attn"))
        return variants.resolve("flash_attn", unit=self)

    def variant_signature(self):
        """Autotune cache-key payload (None = not tunable as configured:
        per-unit override, non-local parallel mode, flash forced off, or
        a sequence the flash gate would never route to the kernel).
        Batch dim excluded — tune-then-inherit, like every op."""
        if getattr(self, "variant_override", None) is not None \
                or not self.input:
            return None
        if self.parallel_mode != "local" or self.use_flash == "off":
            return None
        n, s, e = self.input.shape
        if self.use_flash != "on" \
                and not (s >= 4096 and s % 128 == 0):
            return None
        return {"sample_shape": [s, e], "heads": self.n_heads,
                "head_dim": self.head_dim, "causal": self.causal}

    def variant_effective(self):
        """The flash_attn variant this unit would actually trace — the
        einsum path when the gate keeps the kernel out — or None when no
        flash decision exists for this configuration (sequence-parallel
        modes run the ring/Ulysses kernels). A winner whose `drop` fuse
        axis is on reports its drop=0 TWIN: this unit feeds no dropout
        mask (its graph dropout follows the wo projection — a different
        tensor), so the kernel that actually traces is the unfused
        program, and the table must name that."""
        if self.parallel_mode != "local" \
                or self.seq_axis_name is not None or not self.input:
            return None
        s = self.input.shape[1]
        if not self._flash_ok(s):
            return "xla_mha"
        name = self._flash_variant().name
        from veles_tpu.ops import templates
        if templates.fusion_config("flash_attn", name) is not None:
            for t in templates.templates_for("flash_attn"):
                cfg = t.parse(name)
                if cfg is not None and t.fuse_axis is not None:
                    return t.name({**cfg, t.fuse_axis: 0})
        return name

    def ring_params(self) -> Dict[str, Any]:
        """Inner-hop tiling for the sequence-parallel RING path, taken
        from the flash_attn registry winner (carried ROADMAP item: the
        search results reach the ring hop, not just the local kernel):
        the selected variant's (blk_k, kv_order) become the hop's
        kv_block / block visit order. The hand-written "pallas"
        incumbent maps to its template seed; the einsum golden
        (xla_mha) carries no tiling preference — ring defaults apply
        ({}); the pallas gate does NOT apply here (the ring consumes
        the winner's TILE NUMBERS in plain XLA, not its kernel)."""
        from veles_tpu.ops import templates
        name = getattr(self, "variant_override", None) \
            or variants.effective("flash_attn")
        for t in templates.templates_for("flash_attn"):
            if name == t.base:
                cfg = dict(t.seed)
            elif isinstance(name, str) and "[" in name:
                cfg = t.parse(name)
            else:
                cfg = None
            if cfg:
                return {"kv_block": int(cfg["blk_k"]),
                        "kv_order": str(cfg["kv_order"])}
        return {}

    # -- pure forward ---------------------------------------------------------

    def tp_param_specs(self, model_axis: str, m: int):
        """Megatron TP for shard_map mode: whole heads split across the
        model axis (each shard attends with n_heads/m local heads), wo
        row-sharded with the psum in _apply. None when heads don't
        divide."""
        from jax.sharding import PartitionSpec as P
        if self.n_heads % m:
            return None
        return {"wq": P(None, model_axis), "wk": P(None, model_axis),
                "wv": P(None, model_axis), "wo": P(model_axis, None)}

    def _apply(self, params, x, axis_name=None, allow_flash=True,
               model_axis=None):
        n, s, e = x.shape
        d = self.head_dim
        # local head count follows the (possibly model-sharded) params
        h = params["wq"].shape[1] // d
        q = (x @ params["wq"]).reshape(n, s, h, d)
        k = (x @ params["wk"]).reshape(n, s, h, d)
        v = (x @ params["wv"]).reshape(n, s, h, d)
        if axis_name is None or self.parallel_mode == "local":
            # the Pallas kernels are custom-VJP fwd/bwd pairs, so the
            # differentiated fused/GD paths use them too when the gate
            # says long S beats the XLA einsum. WHICH kernel (hand-
            # written blocks or a search-generated point) is the
            # registry's call at trace time.
            if allow_flash and self._flash_ok(s):
                o = self._flash_variant().apply(q, k, v,
                                                causal=self.causal)
            else:
                o = oa.mha_forward(q, k, v, causal=self.causal)
        elif self.parallel_mode == "ring":
            o = oa.ring_attention(q, k, v, axis_name, causal=self.causal,
                                  **self.ring_params())
        elif self.parallel_mode == "ulysses":
            o = oa.ulysses_attention(q, k, v, axis_name,
                                     causal=self.causal)
        else:
            raise ValueError(f"unknown parallel_mode "
                             f"{self.parallel_mode!r}")
        y = o.reshape(n, s, h * d) @ params["wo"]
        if model_axis is not None:
            # row-parallel wo: per-head-group partials sum over model.
            # Justified stray-collective: the unit's own megatron TP
            # contract (tp_param_specs shards wo's contraction dim) —
            # the gradient rides this psum's transpose, unplaceable by
            # the step modules on the unit's behalf
            # velint: disable=stray-collective
            y = jax.lax.psum(y, model_axis)
        return x + y if self.residual else y

    def fused_apply(self, params, x, *, key=None, train=True):
        return self._apply(params, x, axis_name=self.seq_axis_name,
                           model_axis=self.model_axis_name)

    def xla_init(self):
        self._fn = self.jit(lambda x, p: self._apply(p, x))
        return None

    def numpy_run(self) -> None:
        # golden path: same math through jax on host (attention has no
        # 2015-reference numpy twin to mirror; mha_forward IS the model).
        # allow_flash=False so this stays an INDEPENDENT reference — a
        # golden that routed through the Pallas kernel would cross-check
        # the kernel against itself.
        params = {k: jnp.asarray(a.mem)
                  for k, a in self.param_arrays().items()}
        self.output.mem = np.asarray(
            self._apply(params, self.input.mem, allow_flash=False))

    def xla_run(self) -> None:
        dv = self.device
        params = {k: a.devmem(dv) for k, a in self.param_arrays().items()}
        self.output.set_devmem(self._fn(self.input.devmem(dv), params))


@register_gd(MultiHeadAttention)
class GDMultiHeadAttention(GradientDescentVJP):
    """Backward via jax.vjp of the forward + fused SGD update
    (GradientDescentVJP drives everything off param_arrays())."""


from veles_tpu.znicz import standard_workflow as _sw  # noqa: E402

_sw.LAYER_TYPES.update({"attention": MultiHeadAttention})
