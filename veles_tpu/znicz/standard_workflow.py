"""StandardWorkflow: declarative model builder.

Parity: reference `veles/znicz/standard_workflow.py` — builds
`loader → forwards… → evaluator → decision → gds…(reverse) → (loop)` from a
declarative `layers` list (`root.<model>.layers` in sample configs), with
the Decision's `complete` Bool gating the loop-back Repeater and EndPoint.

Layer dicts: {"type": <name>, ...kwargs}. Types live in the LAYER_TYPES
registry: the all2all family + softmax here; conv/pooling/normalization/
dropout modules append theirs when imported. An unknown type raises with
the currently-registered list.

TPU-first: the same graph can run granular (one jitted XLA computation per
unit — the debuggable mode, and the numpy golden mode for tests) or FUSED —
`build_fused_step()` compiles the entire forward+backward+update chain into
ONE donated XLA computation per minibatch, optionally sharded over a device
mesh (veles_tpu.parallel). That single fused step is the analog of the
reference's whole hot loop of §3.1 kernel enqueues.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from veles_tpu.loader.base import Loader
from veles_tpu.mutable import Bool
from veles_tpu.units import Unit
from veles_tpu.workflow import Repeater, Workflow
from veles_tpu.znicz import all2all, gd  # noqa: F401 (gd registers pairs)
from veles_tpu.znicz.decision import DecisionGD
from veles_tpu.znicz.evaluator import EvaluatorMSE, EvaluatorSoftmax
from veles_tpu.znicz.nn_units import Forward, gd_for

#: layer-type name -> forward unit class (conv/pool types appended by
#: veles_tpu.znicz.conv/pooling at import time to avoid import cycles).
LAYER_TYPES: Dict[str, type] = {
    "all2all": all2all.All2All,
    "all2all_tanh": all2all.All2AllTanh,
    "all2all_relu": all2all.All2AllRELU,
    "all2all_strictrelu": all2all.All2AllStrictRELU,
    "all2all_sigmoid": all2all.All2AllSigmoid,
    "softmax": all2all.All2AllSoftmax,
}


class StandardWorkflow(Workflow):
    """loader + declarative layer list -> full supervised training graph."""

    def __init__(self, workflow=None,
                 layers: Sequence[Dict[str, Any]] = (),
                 loader: Optional[Loader] = None,
                 loss: str = "softmax",
                 n_classes: int = 10,
                 decision_config: Optional[Dict[str, Any]] = None,
                 gd_config: Optional[Dict[str, Any]] = None,
                 snapshot_config: Optional[Dict[str, Any]] = None,
                 plot_config: Optional[Dict[str, Any]] = None,
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.layers_config = list(layers)
        self.loss = loss
        self.n_classes = n_classes
        self.repeater = Repeater(self, name="repeater")
        assert loader is not None, "StandardWorkflow needs a loader"
        self.loader = loader
        if loader.workflow is not self:
            self.add_unit(loader)
            loader.workflow = self

        # -- forwards --------------------------------------------------------
        self.forwards: List[Forward] = []
        prev: Unit = self.loader
        prev_attr = "minibatch_data"
        for spec in self.layers_config:
            spec = dict(spec)
            kind = spec.pop("type")
            if kind not in LAYER_TYPES:
                raise ValueError(
                    f"unknown layer type {kind!r}; registered types: "
                    f"{sorted(LAYER_TYPES)}")
            fwd = LAYER_TYPES[kind](self, **spec)
            fwd.link_attrs(prev, ("input", prev_attr))
            if hasattr(fwd, "link_loader"):  # dropout needs minibatch_class
                fwd.link_loader(self.loader)
            self.forwards.append(fwd)
            prev, prev_attr = fwd, "output"

        # -- evaluator ------------------------------------------------------
        if loss == "softmax":
            self.evaluator = EvaluatorSoftmax(self, n_classes=n_classes)
            self.evaluator.link_attrs(self.loader,
                                      ("labels", "minibatch_labels"))
        elif loss == "mse":
            self.evaluator = EvaluatorMSE(self)
            self.evaluator.link_attrs(self.loader,
                                      ("target", "minibatch_labels"))
        else:
            raise ValueError(f"unknown loss {loss!r}")
        # the Loader's pad mask weights the metrics: exact epoch totals
        # even when the final minibatch wraps (loader/base.py docstring)
        self.evaluator.link_attrs(self.loader,
                                  ("sample_weights", "minibatch_valid"))
        self.evaluator.link_attrs(prev, ("input", "output"))

        # -- decision -------------------------------------------------------
        self.decision = DecisionGD(self, **(decision_config or {}))
        self.decision.link_attrs(self.loader, "minibatch_class",
                                 "last_minibatch", "class_lengths")
        self.decision.link_attrs(self.evaluator, "n_err", "loss")

        # -- gradient chain (reverse order) ---------------------------------
        gd_kw = gd_config or {}
        self.gds: List[Unit] = []
        err_src: Unit = self.evaluator
        err_attr = "err_output"
        for fwd in reversed(self.forwards):
            g = gd_for(type(fwd))(self, **gd_kw)
            g.link_forward(fwd)
            g.link_attrs(err_src, ("err_output", err_attr))
            self.gds.append(g)
            err_src, err_attr = g, "err_input"

        # -- snapshotter (optional; gated on validation improvement) ---------
        self.snapshotter = None
        if snapshot_config is not None:
            from veles_tpu.snapshotter import Snapshotter
            self.snapshotter = Snapshotter(self, **snapshot_config)
            # gating (link_decision) happens in _wire_gates below

        # -- plotters (optional; reference StandardWorkflow wired error
        # curves / confusion / weight tiles from config the same way) ----
        self.plotters: List[Unit] = []
        if plot_config:
            self._build_plotters(plot_config)

        # -- control wiring --------------------------------------------------
        # start → repeater → loader → fwds → evaluator → decision → gds
        #   … last gd → repeater (loop); decision → end_point when complete
        self.repeater.link_from(self.start_point)
        self.loader.link_from(self.repeater)
        prev_u: Unit = self.loader
        for fwd in self.forwards:
            fwd.link_from(prev_u)
            prev_u = fwd
        self.evaluator.link_from(prev_u)
        self.decision.link_from(self.evaluator)
        prev_u = self.decision
        for g in self.gds:
            g.link_from(prev_u)
            prev_u = g
        self.repeater.link_from(prev_u)
        self.end_point.link_from(self.decision)
        if self.snapshotter is not None:
            self.snapshotter.link_from(self.decision)
        self._wire_gates()

    def _build_plotters(self, cfg: Dict[str, Any]) -> None:
        """Wire the reference's standard plot set from a config dict:
        {"error_curve": True, "confusion": True, "weights": True} (any
        subset). Plotters fire once per epoch (gated on the loader's
        epoch boundary) in granular mode; run_fused drives the same
        units at its epoch boundaries, accumulating the validation
        confusion matrix through the step's `confusion()` companion
        (single-host classifier heads; sequence heads and multi-host
        meshes skip it — see FusedTrainStep.confusion)."""
        from veles_tpu.plotting_units import (AccumulatingPlotter,
                                              MatrixPlotter, Weights2D)
        if cfg.get("error_curve"):
            for cls_idx, label in ((1, "validation"), (2, "train")):
                p = AccumulatingPlotter(self, plot_name="epoch_err",
                                        label=label,
                                        name=f"plot_err_{label}")
                p._metric_class = cls_idx
                self.plotters.append(p)
        if cfg.get("confusion") and self.loss == "softmax":
            p = MatrixPlotter(self, name="plot_confusion")
            p.link_attrs(self.evaluator, ("input", "confusion_matrix"))
            # per-epoch VALIDATION confusion (the reference's plot), not
            # an all-splits all-epochs accumulation: restrict the
            # evaluator's accumulation and reset it after each render
            self.evaluator.confusion_split = 1  # VALIDATION
            self.evaluator.link_attrs(self.loader, "minibatch_class")
            self.plotters.append(p)
        if cfg.get("weights") and self.forwards:
            p = Weights2D(self, name="plot_weights")
            p.link_attrs(self.forwards[0], ("input", "weights"))
            self.plotters.append(p)
        # one driver unit fires the whole set at epoch boundaries in the
        # granular pulse graph (run_fused calls _fire_plotters directly)
        driver = Unit(self, name="plot_driver")
        driver.run = self._fire_plotters  # type: ignore[method-assign]
        driver.link_from(self.decision)
        driver.gate_skip = ~self.loader.epoch_ended
        self._plot_driver = driver

    def _fire_plotters(self) -> None:
        """Refresh every plotter from current state (epoch boundary)."""
        from veles_tpu.config import root
        if root.common.get("plotting_disabled", False):
            return      # --no-plot: no specs, and no renderer ever starts
        from veles_tpu.plotting_units import MatrixPlotter
        if not getattr(self, "_plot_series_cleared", False):
            # a NEW workflow plotting under names an earlier run used in
            # this process starts clean (lazy: first fire, so building a
            # workflow that never runs starts no renderer thread)
            for p in self.plotters:
                if hasattr(p, "values"):
                    p.renderer.clear_series(p.plot_name)
            self._plot_series_cleared = True
        for p in self.plotters:
            cls_idx = getattr(p, "_metric_class", None)
            if cls_idx is not None:
                if self.loader.class_lengths[cls_idx] == 0:
                    continue    # no such split: don't plot a fake curve
                m = self.decision.epoch_metrics[cls_idx]
                if m is None:
                    continue
                p.input = float(m)
            if isinstance(p, MatrixPlotter) and p.input is not None \
                    and p.input and not np.any(p.input.mem):
                continue    # never accumulated (fused mode): a zeros
                # heatmap would read as a real (perfect-failure) matrix
            p.run()
        if getattr(self.evaluator, "confusion_split", None) is not None:
            self.evaluator.reset_metrics()   # next epoch starts fresh

    def _wire_gates(self) -> None:
        """(Re)build the derived gate Bools. Called from __init__ AND from
        initialize(): pickle snapshots freeze derived Bools to plain values
        (Bool.__getstate__ drops the closure), so a restored workflow must
        re-derive them or gates stay stuck at their snapshot-time values
        (e.g. gate_skip frozen True → silently no more weight updates)."""
        # re-link GD twins to their forwards: link_forward is idempotent,
        # and units that keep a direct forward reference (GDLSTM._fwd)
        # drop it from pickles and need it re-established after restore
        for g, fwd in zip(self.gds, reversed(self.forwards)):
            g.link_forward(fwd)
        if getattr(self, "_plot_driver", None) is not None:
            # derived Bool: freezes to a plain value in snapshots like
            # every other gate — re-derive or restored runs plot never
            # (frozen True) or per-minibatch (frozen False)
            self._plot_driver.gate_skip = ~self.loader.epoch_ended
        # skip weight updates on test/validation minibatches; freeze the
        # chain entirely once training completed
        for g in self.gds:
            g.gate_skip = self.loader.not_train | self.decision.complete
        self.end_point.gate_block = ~self.decision.complete
        # once complete, the loop-back pulse must die at the repeater
        self.repeater.gate_block = self.decision.complete
        if self.snapshotter is not None:
            self.snapshotter.link_decision(self.decision)

    # -- conveniences --------------------------------------------------------

    def __getstate__(self):
        d = super().__getstate__()
        # device-feed runtime (device arrays in flight, sharded-put
        # closures) and its counters are process-local volatile state:
        # dropping them keeps snapshots loadable AND byte-deterministic
        # for unchanged model state — the property the mirror's
        # digest-keyed idempotent push relies on (resilience/mirror.py)
        d.pop("device_feed", None)
        d.pop("feed_stats", None)
        # ditto the pre-flight prediction (analysis pass 6): it embeds
        # the HOST's device limit, which must not leak into a snapshot
        # another host restores
        d.pop("resource_report", None)
        return d

    def initialize(self, device=None, **kwargs: Any) -> None:
        self._wire_gates()
        super().initialize(device=device, **kwargs)

    def run_epochs(self, n: Optional[int] = None, device=None) -> None:
        """Initialize (if needed) and run until the decision completes."""
        if n is not None:
            self.decision.max_epochs = n
        if not self.is_initialized:
            self.initialize(device=device)
        self.run()

    # -- fused/sharded execution (veles_tpu.parallel) -------------------------

    def build_fused_step(self, mesh=None, mode: str = "auto",
                         compute_dtype=None, ep: bool = False,
                         input_normalize=None, zero_sharding="auto"):
        """Compile the whole forward+backward+update chain into one donated
        XLA step, optionally sharded over `mesh` (data/model axes; ep=True
        additionally shards MoE expert tensors over the data axis).
        `input_normalize` is the uint8-wire prologue spec (see
        `_wire_spec`); `zero_sharding` gates the ZeRO sharded weight
        update (on by default in dp mode — CLI `--zero-sharding`). See
        parallel.fused.FusedTrainStep."""
        from veles_tpu.parallel.fused import FusedTrainStep
        return FusedTrainStep(self, mesh=mesh, mode=mode,
                              compute_dtype=compute_dtype, ep=ep,
                              input_normalize=input_normalize,
                              zero_sharding=zero_sharding)

    def autotune(self, mesh=None, compute_dtype=None, **kwargs: Any):
        """Pick the fastest registered lowering for every tunable op this
        workflow contains (LRN, max-pooling, s2d stem, dropout RNG, and
        anything registered since) by timing candidates in-graph, and
        persist the decisions (ops.autotune cache). Selections are left
        in the registry, so the next build_fused_step/run_fused traces
        the winners. Returns the per-op report. CLI: `--autotune`."""
        from veles_tpu.ops.autotune import autotune_workflow
        return autotune_workflow(self, mesh=mesh,
                                 compute_dtype=compute_dtype, **kwargs)

    def build_pipeline_step(self, mesh, n_microbatches: int = 4,
                            boundaries=None, compute_dtype=None,
                            input_normalize=None):
        """Compile the chain as an S-stage GPipe pipeline over `mesh`'s
        "stage" axis (see parallel.pipeline.PipelineTrainStep). The
        workflow must be initialized first (stage shapes come from the
        units' allocated activations)."""
        from veles_tpu.parallel.pipeline import PipelineTrainStep
        return PipelineTrainStep(self, mesh, n_microbatches,
                                 boundaries=boundaries,
                                 compute_dtype=compute_dtype,
                                 input_normalize=input_normalize)

    def _wire_spec(self, uint8_wire="auto"):
        """uint8-over-the-wire negotiation with the loader (the device
        feed, loader/device_feed.py): when the loader offers a raw-bytes
        wire (`wire_format()`) and the graph does not already carry its
        own `input_normalize` layer, return the prologue spec the step
        builder should trace and the emit format the loader should
        switch to — host conversion work and H2D bytes both drop 4x,
        normalization fuses into the first layer's device read.
        `uint8_wire=False` PINS the host-normalized float wire (golden
        comparisons): a loader constructed with `emit="uint8"` is
        switched to float emission for the run — leaving it raw with no
        prologue would silently train on un-normalized 0..255 bytes."""
        from veles_tpu.znicz.normalization import InputNormalize
        if any(isinstance(u, InputNormalize) for u in self.forwards):
            return None     # the graph normalizes on device already
        if not uint8_wire:
            if getattr(self.loader, "emit", None) == "uint8" \
                    and hasattr(self.loader, "set_emit"):
                return {"emit": "float32", "normalize": None}
            return None
        wf = getattr(self.loader, "wire_format", None)
        return wf() if wf is not None else None

    def run_fused(self, epochs: Optional[int] = None, device=None,
                  mesh=None, mode: str = "auto", compute_dtype=None,
                  ep: bool = False,
                  accum_steps: Optional[int] = None,
                  nonfinite_guard: bool = False,
                  uint8_wire="auto",
                  feed_ahead: Optional[int] = None,
                  zero_sharding="auto") -> None:
        """Train with the fused step while keeping the graph semantics:
        the real Loader drives minibatches and the real Decision unit does
        the epoch/stop bookkeeping (so snapshot gating, best-error tracking
        and the `complete` Bool behave exactly as in granular mode).
        Batches reach the device through the async DeviceFeed
        (loader/device_feed.py): host prep AND the H2D transfer overlap
        device compute, and loaders offering a uint8 wire ship raw bytes
        with an on-device normalize prologue (`uint8_wire=False` opts
        out; `feed_ahead` sets the lookahead depth, default 1).

        `accum_steps=K` computes each minibatch's gradient as K scanned
        microbatches before the single update (train_accum) — activation
        memory O(minibatch/K), numerics equal to the plain step (the
        reference's gradient_accumulation slot, SURVEY.md §2.8).

        `nonfinite_guard=True` aborts with NonFiniteLossError the moment
        a class pass's loss goes NaN/inf — checked only at the class-pass
        boundary where the loss is already host-synced, so the guard adds
        no device syncs (resilience layer; the Launcher maps the error to
        a distinct exit code the Supervisor rolls back a snapshot on)."""
        if epochs is not None:
            self.decision.max_epochs = epochs
        if not self.is_initialized:
            self.initialize(device=device)
        wire = self._wire_spec(uint8_wire)
        step = self.build_fused_step(
            mesh=mesh, mode=mode, compute_dtype=compute_dtype, ep=ep,
            input_normalize=wire["normalize"] if wire else None,
            zero_sharding=zero_sharding)
        self._run_with_step(step, accum_steps=accum_steps,
                            nonfinite_guard=nonfinite_guard,
                            wire=wire, feed_ahead=feed_ahead)

    def run_pipelined(self, mesh=None, n_microbatches: int = 4,
                      epochs: Optional[int] = None, device=None,
                      boundaries=None, compute_dtype=None,
                      nonfinite_guard: bool = False,
                      uint8_wire="auto",
                      feed_ahead: Optional[int] = None) -> None:
        """Train as a GPipe pipeline over `mesh`'s "stage" axis (default:
        one stage per device) with the same Loader/Decision/Snapshotter
        semantics (and the same DeviceFeed input path) as run_fused. The
        CLI exposes this as `--pp M` (M = microbatches)."""
        if epochs is not None:
            self.decision.max_epochs = epochs
        if not self.is_initialized:
            self.initialize(device=device)
        if mesh is None:
            import jax

            from veles_tpu.parallel.pipeline import make_stage_mesh
            # one stage per device, capped at one UNIT per stage
            mesh = make_stage_mesh(
                jax.devices()[:max(1, len(self.forwards))])
        wire = self._wire_spec(uint8_wire)
        step = self.build_pipeline_step(
            mesh, n_microbatches, boundaries=boundaries,
            compute_dtype=compute_dtype,
            input_normalize=wire["normalize"] if wire else None)
        self._run_with_step(step, nonfinite_guard=nonfinite_guard,
                            wire=wire, feed_ahead=feed_ahead)

    def _run_with_step(self, step, accum_steps: Optional[int] = None,
                       nonfinite_guard: bool = False,
                       wire=None, feed_ahead: Optional[int] = None) -> None:
        """Drive any train/evaluate/write_back step object through the
        Loader + Decision bookkeeping (shared by run_fused /
        run_pipelined). Batches arrive through the async DeviceFeed —
        while step k executes, batch k+1's sharded device_put is already
        in flight (feed.prefetch() at the loop bottom, AFTER the
        snapshot window so pickled loader cursors stay exact-resume
        correct) — and each FeedBatch's Decision metadata is replayed
        onto the loader, so the epoch bookkeeping below is unchanged
        from the synchronous loop it replaces."""
        # static resource pre-flight (analysis pass 6, docs/ANALYSIS.md
        # — ISSUE 14): predict the per-device HBM footprint BEFORE the
        # first compile. The cheap resident model always runs (it rides
        # the heartbeat, so the supervisor reports predicted-vs-
        # measured); the traced high-water walk + limit comparison run
        # only when a device limit is known (TPU) — warn above 80%,
        # refuse above it with a per-component byte breakdown instead
        # of OOMing minutes into the compile.
        from veles_tpu.analysis import resources as _resources
        try:
            self.resource_report = _resources.preflight(
                self, step, feed_ahead=feed_ahead)
        except _resources.ResourcePreflightError:
            raise
        except Exception as e:  # noqa: BLE001 — an estimate must never
            # kill a run the measurement machinery exists to observe
            self.debug("resource pre-flight unavailable: %s", e)
            self.resource_report = None
        if accum_steps and accum_steps > 1:
            import types
            base = step
            step = types.SimpleNamespace(
                train=lambda s, x, y, w=None: base.train_accum(
                    s, x, y, accum_steps, w),
                evaluate=base.evaluate, init_state=base.init_state,
                write_back=base.write_back,
                # keep the full step surface: the confusion companion,
                # local_rows, sharding specs and mesh drive features
                # below this wrapper
                confusion=getattr(base, "confusion", None),
                local_rows=getattr(base, "local_rows", None),
                input_put_specs=getattr(base, "input_put_specs", None),
                collective_accounting=getattr(
                    base, "collective_accounting", None),
                mesh=getattr(base, "mesh", None))
        import time as _time

        from veles_tpu.config import root as _root
        from veles_tpu.loader.base import TRAIN
        from veles_tpu.loader.device_feed import DeviceFeed
        from veles_tpu.resilience.faults import active_plan
        from veles_tpu.telemetry import metrics as _tmetrics
        from veles_tpu.telemetry import tracer as _ttracer
        fault_plan = active_plan()   # None in production: zero per-step cost
        # telemetry plane (docs/OBSERVABILITY.md): the tracer handle and
        # the metric instruments are PRE-BOUND here, outside the loop —
        # the hot path pays None checks and float adds, never a name
        # lookup (the velint hot-metric contract). tr is None when no
        # --trace is active; the profile controller's disarmed on_step
        # is one attribute check.
        tr = _ttracer.active()
        prof = _ttracer.profile_controller()
        mh = _tmetrics.step_handles()
        # per-collective byte attribution (ISSUE 12): the ZeRO
        # grad_reduce exchange's modeled egress, pre-bound like every
        # other hot-path instrument; None when the step traces no
        # registry collective — the counters can't fabricate provenance
        _acct_fn = getattr(step, "collective_accounting", None)
        ch = _tmetrics.collective_handles(
            _acct_fn() if _acct_fn is not None else None)
        state = step.init_state()
        loader, ev, dec = self.loader, self.evaluator, self.decision
        # the feed uploads (sharded, async) itself; the loader's granular-
        # path device push would be a second, wasted H2D per minibatch
        prev_on_device, loader.on_device = loader.on_device, False
        # uint8 wire negotiated (run_fused/_wire_spec): raw bytes leave
        # the host, the step's input_normalize prologue converts on
        # device — restore the loader's emit format afterwards
        prev_emit = getattr(loader, "emit", None)
        if wire is not None and hasattr(loader, "set_emit"):
            loader.set_emit(wire["emit"])
            # mid-run snapshots pickle the CONSTRUCTED emit, not the
            # run-scoped negotiated one (Loader.__getstate__)
            loader._emit_pristine = prev_emit
        # multi-host input sharding: tell a prefetching loader which
        # global batch rows this process's shards own, so host decode
        # divides by the host count (non-local rows zero-fill; the jit
        # never transfers or reads them)
        prev_rows_fn = getattr(loader, "local_rows_fn", None)
        mesh = getattr(step, "mesh", None)
        if (hasattr(loader, "local_rows_fn")
                and hasattr(step, "local_rows") and mesh is not None):
            from veles_tpu.parallel.mesh import is_multihost
            if is_multihost(mesh):
                loader.local_rows_fn = step.local_rows
        ahead = 1 if feed_ahead is None else feed_ahead
        if self.snapshotter is not None and ahead > 1:
            # a snapshot taken with k pending batches pickles a loader
            # cursor k past the trained batch — the restore would skip
            # them, forking the resumed trajectory. Exact resume beats
            # deeper lookahead; loops that never pickle the loader
            # (bench) may run deeper.
            self.warning("feed_ahead=%d clamped to 1: snapshots require "
                         "an exact-resume loader cursor "
                         "(loader/device_feed.py)", ahead)
            ahead = 1
        feed = DeviceFeed.for_step(loader, step, ahead=ahead)
        #: observability handle: heartbeats/reports read feed_stats
        self.device_feed = feed
        try:
            # Metrics accumulate ON DEVICE across each class pass (lazy
            # scalar adds); the single host sync happens at last_minibatch,
            # so device execution pipelines across minibatches (the
            # evaluator docstring's fused-mode contract).
            acc_loss = acc_err = acc_conf = None
            acc_w = 0.0
            step_idx = 0
            #: the open in-flight "step" span: dispatch k .. dispatch
            #: k+1 (or the class-pass-boundary device sync, whichever
            #: first) — the host-visible window the device is executing
            #: step k in, which batch k+1's feed.device_put span rides
            #: under when the overlap works
            step_tok = None
            t_iter = _time.perf_counter()
            ep_examples = 0.0
            t_epoch = t_iter
            while not bool(dec.complete):
                prof.on_step(step_idx)
                if tr is not None:
                    tok = tr.begin("feed.next", "feed")
                b = feed.next()
                if tr is not None:
                    tr.end(tok)
                x, y, w = b.x, b.y, b.w
                if tr is not None and step_tok is not None:
                    tr.end(step_tok)     # step k-1's window closes at
                    step_tok = None      # the next dispatch
                if b.minibatch_class == TRAIN:
                    if tr is not None:
                        tok = tr.begin("train.dispatch", "step")
                    state, (loss, n_err) = step.train(state, x, y, w)
                    if ch is not None:
                        # the exchange rides inside the step just
                        # dispatched; count its modeled bytes now and
                        # mark the step on the timeline (an instant:
                        # its device duration is not host-observable
                        # without a sync — docs/OBSERVABILITY.md)
                        ch.dcn.inc(ch.dcn_bytes)
                        ch.ici.inc(ch.ici_bytes)
                        ch.ag_dcn.inc(ch.ag_dcn_bytes)
                        ch.ag_ici.inc(ch.ag_ici_bytes)
                        if tr is not None:
                            tr.instant(ch.mark, "collective")
                    if tr is not None:
                        tr.end(tok)
                        step_tok = tr.begin("step", "step")
                    if fault_plan is not None and fault_plan.nan_at_step():
                        loss = float("nan")   # deterministic divergence
                else:
                    if tr is not None:
                        tok = tr.begin("eval.dispatch", "step")
                    loss, n_err = step.evaluate(state, x, y, w)
                    if tr is not None:
                        tr.end(tok)
                        step_tok = tr.begin("step", "step")
                    # fused-mode confusion accumulation (the granular
                    # graph's evaluator fills it per minibatch; without
                    # this the confusion plot would silently skip).
                    # Accumulated as LAZY DEVICE adds like loss/err; the
                    # host sync stays at the class-pass boundary.
                    cs = getattr(ev, "confusion_split", None)
                    if (cs is not None and b.minibatch_class == cs
                            and getattr(self, "plotters", None)
                            and getattr(ev, "compute_confusion", True)
                            and not _root.common.get("plotting_disabled",
                                                     False)
                            and getattr(step, "confusion", None)
                            is not None):
                        m = step.confusion(state, x, y, ev.n_classes, w)
                        if m is not None:
                            acc_conf = (m if acc_conf is None
                                        else acc_conf + m)
                # step losses are weighted MEANS over the minibatch; scale
                # by the batch's valid-row weight so the class-pass total
                # is the EXACT weighted mean (a wrapped final minibatch
                # with few valid rows must not count as a full one)
                bw = float(b.w_host.sum())
                wl = loss * bw
                acc_loss = wl if acc_loss is None else acc_loss + wl
                acc_w += bw
                acc_err = n_err if acc_err is None else acc_err + n_err
                step_idx += 1
                mh.steps.inc()
                if b.minibatch_class == TRAIN:
                    mh.examples.inc(bw)
                    ep_examples += bw
                now = _time.perf_counter()
                mh.step_seconds.observe(now - t_iter)
                t_iter = now
                if b.last_minibatch:
                    # Decision's improvement/stop logic only reads totals
                    # at the class-pass boundary; feeding the accumulated
                    # value here (zeros in between) preserves its
                    # semantics. This float() is THE driver-side device
                    # sync — timed so the feed's stats decompose blocked
                    # time into loader vs device.
                    t_sync = _time.perf_counter()
                    ev.loss = float(acc_loss) / max(acc_w, 1.0)
                    if tr is not None and step_tok is not None:
                        tr.end(step_tok)   # the float() drained the
                        step_tok = None    # device: the window is over
                    mh.loss.set(ev.loss)
                    if nonfinite_guard and not np.isfinite(ev.loss):
                        # raised BEFORE dec.run()/the snapshot branch: a
                        # poisoned state must never be snapshotted. The
                        # check rides the boundary's existing host sync,
                        # so the guard costs no extra device round-trips.
                        from veles_tpu.resilience import NonFiniteLossError
                        raise NonFiniteLossError(
                            f"non-finite loss {ev.loss!r} at epoch "
                            f"{dec.epoch_number} (class "
                            f"{int(b.minibatch_class)} pass)")
                    ev.n_err = (int(acc_err) if self.loss == "softmax"
                                else float(acc_err))
                    if acc_conf is not None:
                        ev.confusion_matrix.map_write()
                        # class-pass-boundary sync by design: confusion
                        # accumulated as lazy device adds above, pulled
                        # host-side ONCE per pass, not per batch
                        # velint: disable=sync-feed
                        ev.confusion_matrix.mem += np.asarray(
                            acc_conf).astype(ev.confusion_matrix.mem.dtype)
                    t_done = _time.perf_counter()
                    feed.note_device_sync(t_done - t_sync)
                    if tr is not None:
                        tr.add_span("device_sync", "step", t_sync,
                                    t_done)
                    acc_loss = acc_err = acc_conf = None
                    acc_w = 0.0
                else:
                    ev.loss = 0.0
                    ev.n_err = 0
                if b.epoch_ended:
                    # BEFORE dec.run(): the Decision's epoch hooks write
                    # the heartbeat, which carries these counters to the
                    # supervisor's exit report
                    self.feed_stats = feed.stats()
                    # the one registry mirrors the feed's counters (the
                    # feed stays the producer) and the epoch-boundary
                    # rates; a JSONL sink (if installed) gets one line
                    # per epoch for offline analysis
                    _tmetrics.mirror_feed(self.feed_stats)
                    t_ep = _time.perf_counter()
                    if ep_examples and t_ep > t_epoch:
                        mh.examples_per_s.set(
                            ep_examples / (t_ep - t_epoch))
                    ep_examples, t_epoch = 0.0, t_ep
                if tr is not None:
                    tok = tr.begin("decision", "bookkeeping")
                dec.run()
                if tr is not None:
                    tr.end(tok)
                if b.epoch_ended:
                    mh.epoch.set(dec.epoch_number)
                    _tmetrics.flush_installed(
                        extra={"source": "driver",
                               "epoch": int(dec.epoch_number)})
                if getattr(self, "plotters", None) \
                        and b.epoch_ended \
                        and not _root.common.get("plotting_disabled",
                                                 False):
                    # weight plots need the CURRENT fused params in the
                    # unit Arrays, not the init-time values
                    from veles_tpu.plotting_units import Weights2D
                    if any(isinstance(p, Weights2D)
                           for p in self.plotters):
                        step.write_back(state)
                    self._fire_plotters()   # same per-epoch plot set as
                    # the granular graph's plot_driver
                # fused mode bypasses the pulse graph, so the snapshot
                # gating is applied here by hand: same improved-gated
                # behavior as granular mode (run_fused's contract)
                if self.snapshotter is not None and bool(dec.improved):
                    if tr is not None:
                        tok = tr.begin("snapshot", "bookkeeping")
                    step.write_back(state)
                    self.snapshotter.run()
                    if tr is not None:
                        tr.end(tok)
                # NOW produce batch k+1 and issue its async put: the
                # step dispatched above is still executing on device,
                # so the H2D transfer hides under it — and the snapshot
                # (if any) already pickled the pristine loader cursor
                if not bool(dec.complete):
                    if tr is not None:
                        tok = tr.begin("feed.prefetch", "feed")
                    feed.prefetch()
                    if tr is not None:
                        tr.end(tok)
        finally:
            if tr is not None and step_tok is not None:
                tr.end(step_tok)
            prof.finalize()
            feed.stop()
            self.feed_stats = feed.stats()
            _tmetrics.mirror_feed(self.feed_stats)
            loader.on_device = prev_on_device
            if wire is not None and hasattr(loader, "set_emit") \
                    and prev_emit is not None:
                loader.set_emit(prev_emit)
                loader._emit_pristine = None
            if hasattr(loader, "local_rows_fn"):
                loader.local_rows_fn = prev_rows_fn
            step.write_back(state)
            self.fused_state = state
            self._stop_units()   # release loader prefetch threads etc.
