"""Standalone activation units (forward + backward pairs).

Parity: reference `veles/znicz/activation.py` — `ActivationTanh`,
`ActivationRELU` (softplus flavor), `ActivationStrictRELU`,
`ActivationSigmoid`, `ActivationLog` (asinh) as separate graph units,
used when an activation is not fused into an All2All/Conv layer
(SURVEY.md §2.8).

TPU-first: each is a trivially-jitted elementwise fn; XLA fuses it into
whatever producer/consumer surrounds it, so the standalone-unit granularity
costs nothing in the fused train step.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import numpy as np

from veles_tpu.ops import reference as ref
from veles_tpu.ops import xla as ox
from veles_tpu.znicz.nn_units import Forward, GradientDescentBase, register_gd


class ActivationForward(Forward):
    """y = act(x), shape-preserving, no parameters."""

    activation = "linear"

    def param_arrays(self):
        return {}

    def initialize(self, device=None, **kwargs: Any):
        if not self.input:
            return False
        if not self.output or self.output.shape != self.input.shape:
            self.output.reset(np.zeros(self.input.shape, np.float32))
        return super().initialize(device=device, **kwargs)

    def xla_init(self):
        self._fn = self.jit(partial(ox.act_forward, self.activation))
        return None

    def fused_apply(self, params, x, *, key=None, train=True):
        return ox.act_forward(self.activation, x)

    def numpy_run(self) -> None:
        self.output.mem = ref.act_forward(self.activation, self.input.mem)

    def xla_run(self) -> None:
        self.output.set_devmem(self._fn(self.input.devmem(self.device)))


class ActivationTanh(ActivationForward):
    activation = "tanh"


class ActivationRELU(ActivationForward):
    activation = "relu"


class ActivationStrictRELU(ActivationForward):
    activation = "strictrelu"


class ActivationSigmoid(ActivationForward):
    activation = "sigmoid"


class ActivationLog(ActivationForward):
    activation = "log"


@register_gd(ActivationForward)
class ActivationBackward(GradientDescentBase):
    """err_input = act'(y)·err_output. The derivative is expressed from the
    forward OUTPUT (reference memory model: pre-activations not retained);
    the log flavor additionally needs the input, which stays linked."""

    def __init__(self, workflow=None, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.activation = "linear"

    def link_forward(self, fwd):
        self.activation = fwd.activation
        self.link_attrs(fwd, "input", "output")
        return self

    def initialize(self, device=None, **kwargs: Any):
        if not self.err_output or not self.input:
            return False
        if not self.err_input or self.err_input.shape != self.input.shape:
            self.err_input.reset(np.zeros(self.input.shape, np.float32))
        return super().initialize(device=device, **kwargs)

    def xla_init(self):
        act = self.activation

        def step(y, err_y, x):
            return ox.act_backward(act, y, err_y, x)

        self._fn = self.jit(step)
        return None

    def numpy_run(self) -> None:
        self.err_input.mem = ref.act_backward(
            self.activation, self.output.mem, self.err_output.mem,
            self.input.mem)

    def xla_run(self) -> None:
        d = self.device
        self.err_input.set_devmem(
            self._fn(self.output.devmem(d), self.err_output.devmem(d),
                     self.input.devmem(d)))


# -- layer-type registration --------------------------------------------------
from veles_tpu.znicz import standard_workflow as _sw  # noqa: E402

_sw.LAYER_TYPES.update({
    "activation_tanh": ActivationTanh,
    "activation_relu": ActivationRELU,
    "activation_strictrelu": ActivationStrictRELU,
    "activation_sigmoid": ActivationSigmoid,
    "activation_log": ActivationLog,
})
