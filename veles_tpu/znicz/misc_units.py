"""Miscellaneous service units.

Parity: reference misc znicz units (SURVEY.md §2.8 [L]):
`image_saver.py` (dump misclassified samples), `accumulator.py` (collect a
linked value over time), `weights_zerofilling.py` (mask/zero chosen weight
entries each step), `multi_hist.py` (histogram of a linked tensor).
"""

from __future__ import annotations

import os
from typing import Any, List, Optional

import numpy as np

from veles_tpu.units import Unit


class Accumulator(Unit):
    """Appends the linked `input` value each firing (the reference used it
    to gather per-minibatch metrics for plotters)."""

    def __init__(self, workflow=None, limit: int = 0, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.limit = limit
        self.values: List[Any] = []
        self.input = None  # usually a data link

    def run(self) -> None:
        v = self.input
        if v is None:
            return
        self.values.append(np.copy(v) if isinstance(v, np.ndarray)
                           else v)
        if self.limit and len(self.values) > self.limit:
            self.values.pop(0)

    def reset(self) -> None:
        self.values.clear()


class MultiHistogram(Unit):
    """Histogram of a linked Array (weights/activations) each firing."""

    def __init__(self, workflow=None, n_bins: int = 20, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.n_bins = n_bins
        self.input = None          # Array data link
        self.hist = None
        self.bin_edges = None

    def run(self) -> None:
        if self.input is None or not self.input:
            return
        self.hist, self.bin_edges = np.histogram(
            self.input.mem.ravel(), bins=self.n_bins)


class ZeroFiller(Unit):
    """Zeroes weight entries selected by a boolean mask after each update
    (parity: weights_zerofilling — used to enforce sparsity patterns /
    frozen connections)."""

    def __init__(self, workflow=None, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.weights = None  # Array data link (to a forward unit's weights)
        self.mask: Optional[np.ndarray] = None

    def run(self) -> None:
        if self.weights is None or not self.weights or self.mask is None:
            return
        self.weights.map_write()
        self.weights.mem[self.mask] = 0.0


class ImageSaver(Unit):
    """Dumps misclassified samples as PNGs named
    `<label>_as_<pred>_<i>.png` (parity: image_saver.py). Links: `input`
    (minibatch data Array), `labels` (Array), `max_idx` (Array from
    All2AllSoftmax)."""

    def __init__(self, workflow=None, directory: str = "misclassified",
                 limit: int = 64, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.directory = directory
        self.limit = limit
        self.saved = 0
        self.input = None
        self.labels = None
        self.max_idx = None

    def run(self) -> None:
        if any(a is None or not a
               for a in (self.input, self.labels, self.max_idx)):
            return
        os.makedirs(self.directory, exist_ok=True)
        x = self.input.mem
        y = self.labels.mem
        pred = self.max_idx.mem
        for i in np.nonzero(pred != y)[0]:
            if self.saved >= self.limit:
                return
            img = x[i].squeeze()
            lo, hi = float(img.min()), float(img.max())
            arr = ((img - lo) / max(hi - lo, 1e-9) * 255).astype(np.uint8)
            path = os.path.join(
                self.directory,
                f"{int(y[i])}_as_{int(pred[i])}_{self.saved}.png")
            try:
                from PIL import Image
                if arr.ndim == 1:  # flat features: save as a row strip
                    arr = arr[None, :]
                Image.fromarray(arr).save(path)
            except ImportError:
                np.save(path + ".npy", arr)
            self.saved += 1
