"""Evaluator units: loss + error derivative + metrics.

Parity: reference `veles/znicz/evaluator.py` — `EvaluatorSoftmax`
(cross-entropy over All2AllSoftmax probabilities, n_err count, confusion
matrix, max-error tracking) and `EvaluatorMSE`.

TPU-first: the metric math runs jitted on device; only the scalar metrics
the Decision unit consumes (n_err, loss) cross to host, once per minibatch
in granular mode (the fused train step keeps even those on device across a
whole epoch — see standard_workflow.py).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from veles_tpu.accelerated_units import XLAUnit
from veles_tpu.memory import Array
from veles_tpu.ops import reference as ref
from veles_tpu.ops import xla as ox


class EvaluatorBase(XLAUnit):
    def __init__(self, workflow=None, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.input = Array()        # network output (probs for softmax)
        self.err_output = Array()   # derivative handed to the GD chain
        self.loss = 0.0
        #: error metric the Decision consumes (count for softmax, the
        #: loss itself for MSE). Present from construction: the Decision
        #: links it at wiring time, and eager link_attrs validation
        #: (units.LinkError) rightly rejects a source attribute that
        #: only appears at first run()
        self.n_err = 0.0


class EvaluatorSoftmax(EvaluatorBase):
    """Consumes probabilities + integer labels; emits err wrt logits
    (probs − onehot, batch-mean-scaled), n_err, loss, confusion matrix."""

    def __init__(self, workflow=None, n_classes: int = 10,
                 compute_confusion: bool = True, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.n_classes = n_classes
        self.compute_confusion = compute_confusion
        self.labels = Array()
        #: (N,) sample weights — StandardWorkflow aliases the Loader's
        #: minibatch_valid pad mask here so wrapped final minibatches
        #: yield EXACT epoch metrics (zero-weight rows drop out); an
        #: unlinked evaluator defaults to all-ones (legacy behavior)
        self.sample_weights = Array()
        self.n_err = 0
        self.confusion_matrix = Array(
            np.zeros((n_classes, n_classes), np.int64))
        #: None — accumulate confusion over every minibatch (legacy);
        #: a class index (0/1/2) — only that split's minibatches count
        #: (requires `minibatch_class` linked from the loader). The
        #: plot_config wiring sets VALIDATION here so the confusion plot
        #: is the reference's per-epoch validation matrix.
        self.confusion_split = None
        self.minibatch_class = None

    def initialize(self, device=None, **kwargs: Any):
        if not self.input:
            return False
        if not self.err_output or self.err_output.shape != self.input.shape:
            self.err_output.reset(np.zeros(self.input.shape, np.float32))
        if not self.sample_weights:
            self.sample_weights.reset(
                np.ones(self.input.shape[0], np.float32))
        # per-token LM heads flatten (N, S) rows to N·S while the Loader
        # mask stays per-sample (N,): repeat each sample weight S times
        n, nw = self.input.shape[0], self.sample_weights.shape[0]
        if n != nw and n % nw:
            raise ValueError(f"sample_weights ({nw}) incompatible with "
                             f"evaluator rows ({n})")
        self._w_repeat = n // nw
        return super().initialize(device=device, **kwargs)

    def xla_init(self):
        import jax.numpy as jnp
        r = self._w_repeat
        self._fn = self.jit(
            lambda p, l, w: ox.softmax_ce(
                p, l, self.n_classes,
                weights=jnp.repeat(w, r) if r > 1 else w))
        return None

    def numpy_run(self) -> None:
        w = self.sample_weights.mem
        if self._w_repeat > 1:
            w = np.repeat(w, self._w_repeat)
        loss, err, n_err, conf = ref.softmax_ce(
            self.input.mem, self.labels.mem, self.n_classes, weights=w)
        self.loss = loss
        self.err_output.mem = err
        self.n_err = n_err
        if self._accumulate_confusion():
            self.confusion_matrix.map_write()
            self.confusion_matrix.mem += conf

    def xla_run(self) -> None:
        d = self.device
        loss, err, n_err, conf = self._fn(self.input.devmem(d),
                                          self.labels.devmem(d),
                                          self.sample_weights.devmem(d))
        self.err_output.set_devmem(err)
        # scalars cross to host here: the Decision unit is host-side logic
        self.loss = float(loss)
        self.n_err = int(n_err)
        if self._accumulate_confusion():
            self.confusion_matrix.map_write()
            # the CxC pull rides the scalar sync two lines up (loss/
            # n_err already crossed to host): no extra pipeline stall
            # velint: disable=hot-sync
            self.confusion_matrix.mem += np.asarray(conf)

    def _accumulate_confusion(self) -> bool:
        if not self.compute_confusion:
            return False
        split = getattr(self, "confusion_split", None)
        return split is None or self.minibatch_class == split

    def reset_metrics(self) -> None:
        self.confusion_matrix.reset(
            np.zeros((self.n_classes, self.n_classes), np.int64))


class EvaluatorMSE(EvaluatorBase):
    """Mean-squared-error evaluator (autoencoders, regression)."""

    def __init__(self, workflow=None, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.target = Array()
        self.sample_weights = Array()   # see EvaluatorSoftmax

    def initialize(self, device=None, **kwargs: Any):
        if not self.input:
            return False
        if not self.err_output or self.err_output.shape != self.input.shape:
            self.err_output.reset(np.zeros(self.input.shape, np.float32))
        if not self.sample_weights:
            self.sample_weights.reset(
                np.ones(self.input.shape[0], np.float32))
        return super().initialize(device=device, **kwargs)

    def xla_init(self):
        self._fn = self.jit(lambda y, t, w: ox.mse(y, t, weights=w))
        return None

    def numpy_run(self) -> None:
        loss, err = ref.mse(self.input.mem, self.target.mem,
                            weights=self.sample_weights.mem)
        self.loss = loss
        self.err_output.mem = err
        self.n_err = loss  # Decision tracks MSE as the "error" metric

    def xla_run(self) -> None:
        d = self.device
        loss, err = self._fn(self.input.devmem(d), self.target.devmem(d),
                             self.sample_weights.devmem(d))
        self.err_output.set_devmem(err)
        self.loss = float(loss)
        self.n_err = self.loss
