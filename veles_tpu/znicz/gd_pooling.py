"""Gradient units for pooling layers.

Parity: reference `veles/znicz/gd_pooling.py` — `GDMaxPooling` (scatter via
the offsets stored by the forward), `GDMaxAbsPooling`, `GDAvgPooling`
(uniform spread), plus the stochastic-pooling backward (SURVEY.md §2.8).

TPU-first: max/maxabs/stochastic backwards scatter err at the flat winner
offsets their forward recorded (`ox.pool_scatter` — one code shape for all
three); the avg backward is `jax.vjp` of the forward reduce_window. Both
replace the reference's hand-written scatter kernels.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np

from veles_tpu.ops import reference as ref
from veles_tpu.ops import xla as ox
from veles_tpu.znicz import pooling
from veles_tpu.znicz.nn_units import GradientDescentBase, register_gd


class GDPoolingBase(GradientDescentBase):
    """No trainable parameters: only err routing. Captures the twin's
    geometry in link_forward."""

    def __init__(self, workflow=None, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.ksize = (2, 2)
        self.stride = (2, 2)

    def link_forward(self, fwd):
        self.ksize = fwd.ksize
        self.stride = fwd.stride
        self.link_attrs(fwd, "input", "output")
        if hasattr(fwd, "input_offset"):
            self.link_attrs(fwd, "input_offset")
        return self

    def initialize(self, device=None, **kwargs: Any):
        if not self.err_output or not self.input:
            return False
        if not self.err_input or self.err_input.shape != self.input.shape:
            self.err_input.reset(np.zeros(self.input.shape, np.float32))
        return super().initialize(device=device, **kwargs)


class GDScatterPoolingBase(GDPoolingBase):
    """Shared backward for pooling flavors whose forward records flat
    winner offsets (max/maxabs/stochastic): err scatters to the winners;
    sentinel offsets (input.size — dead stochastic windows) drop."""

    def xla_init(self):
        shape = tuple(self.input.shape)
        self._fn = self.jit(lambda err_y, idx: ox.pool_scatter(
            err_y, idx, shape))
        return None

    def numpy_run(self) -> None:
        self.err_input.mem = ref.stochastic_pool_backward(
            self.err_output.mem, self.input_offset.mem, self.input.shape)

    def xla_run(self) -> None:
        d = self.device
        self.err_input.set_devmem(
            self._fn(self.err_output.devmem(d), self.input_offset.devmem(d)))


@register_gd(pooling.MaxPooling)
class GDMaxPooling(GDScatterPoolingBase):
    pass


@register_gd(pooling.MaxAbsPooling)
class GDMaxAbsPooling(GDScatterPoolingBase):
    pass


@register_gd(pooling.StochasticPooling)
class GDStochasticPooling(GDScatterPoolingBase):
    pass


@register_gd(pooling.AvgPooling)
class GDAvgPooling(GDPoolingBase):
    def xla_init(self):
        ksize, stride = self.ksize, self.stride

        def step(x, err_y):
            _, vjp = jax.vjp(
                lambda v: ox.avgpool_forward(v, ksize, stride), x)
            (err_x,) = vjp(err_y)
            return err_x

        self._fn = self.jit(step)
        return None

    def numpy_run(self) -> None:
        self.err_input.mem = ref.avgpool_backward(
            self.err_output.mem, self.input.shape, self.ksize, self.stride)

    def xla_run(self) -> None:
        d = self.device
        self.err_input.set_devmem(
            self._fn(self.input.devmem(d), self.err_output.devmem(d)))
