"""Fully-connected forward units.

Parity: reference `veles/znicz/all2all.py` — `All2All` (linear),
`All2AllTanh` (scaled LeCun tanh), `All2AllRELU` (softplus-style RELU),
`All2AllStrictRELU`, `All2AllSigmoid`, `All2AllSoftmax` (linear + fused
max-subtracted softmax; named in BASELINE.json:4).

TPU-first: the matmul + bias + activation is one jitted XLA function
(ops.xla.all2all_forward) hitting the MXU; the reference's BLOCK_SIZE-tuned
OpenCL/CUDA matmul kernels have no analog here by design.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence, Union

import jax
import numpy as np

from veles_tpu.memory import Array
from veles_tpu.ops import reference as ref
from veles_tpu.ops import xla as ox
from veles_tpu.znicz.nn_units import Forward


class All2All(Forward):
    """y = act(x·W + b); W: (fan_in, units)."""

    activation = "linear"

    def __init__(self, workflow=None,
                 output_sample_shape: Union[int, Sequence[int]] = 10,
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        if isinstance(output_sample_shape, int):
            output_sample_shape = (output_sample_shape,)
        self.output_sample_shape = tuple(output_sample_shape)

    @property
    def n_output(self) -> int:
        return int(np.prod(self.output_sample_shape))

    def initialize(self, device=None, **kwargs: Any):
        if not self.input:
            return False  # deferred until the upstream unit allocates
        fan_in = int(np.prod(self.input.shape[1:]))
        self.init_params((fan_in, self.n_output), fan_in)
        n = self.input.shape[0]
        if not self.output or self.output.shape[0] != n:
            self.output.reset(np.zeros((n,) + self.output_sample_shape,
                                       np.float32))
        return super().initialize(device=device, **kwargs)

    def xla_init(self):
        self._fn = self.jit(partial(ox.all2all_forward,
                                    activation=self.activation))
        return None

    def fused_apply(self, params, x, *, key=None, train=True):
        y = ox.all2all_forward(x, params["weights"], params["bias"],
                               self.activation)
        return y.reshape((-1,) + self.output_sample_shape)

    def numpy_run(self) -> None:
        self.output.mem = ref.all2all_forward(
            self.input.mem, self.weights.mem, self.bias.mem,
            self.activation).reshape((-1,) + self.output_sample_shape)

    def xla_run(self) -> None:
        d = self.device
        y = self._fn(self.input.devmem(d), self.weights.devmem(d),
                     self.bias.devmem(d))
        self.output.set_devmem(y.reshape((-1,) + self.output_sample_shape))


class All2AllTanh(All2All):
    activation = "tanh"


class All2AllRELU(All2All):
    activation = "relu"


class All2AllStrictRELU(All2All):
    activation = "strictrelu"


class All2AllSigmoid(All2All):
    activation = "sigmoid"


class All2AllSoftmax(All2All):
    """Linear layer fused with max-subtracted softmax; `output` holds
    probabilities and `max_idx` the per-sample argmax (the reference kernel
    emitted it for the evaluator)."""

    activation = "linear"

    def __init__(self, workflow=None, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.max_idx = Array()

    def xla_init(self):
        def fwd(x, w, b):
            probs = ox.all2all_softmax_forward(x, w, b)
            return probs, probs.argmax(axis=-1)

        self._fn = self.jit(fwd)
        return None

    def numpy_run(self) -> None:
        x2 = self.input.mem.reshape(len(self.input), -1)
        probs = ref.softmax(x2 @ self.weights.mem + self.bias.mem)
        self.output.mem = probs
        self.max_idx.mem = probs.argmax(axis=1)

    def xla_run(self) -> None:
        d = self.device
        probs, idx = self._fn(self.input.devmem(d), self.weights.devmem(d),
                              self.bias.devmem(d))
        self.output.set_devmem(probs)
        self.max_idx.set_devmem(idx)

    #: the fused train step takes logits and uses log-softmax CE directly
    #: (numerically identical gradient to the granular probs path).
    fused_emits_logits = True

    def fused_apply(self, params, x, *, key=None, train=True):
        return ox.all2all_forward(x, params["weights"], params["bias"])
