"""Gradient units for fully-connected layers.

Parity: reference `veles/znicz/gd.py` — `GradientDescent` (linear twin),
`GDTanh`, `GDRELU`, `GDStrictRELU`, `GDSigmoid`, `GDSoftmax` (the softmax
twin receives err wrt LOGITS from EvaluatorSoftmax — probs−onehot — so its
activation derivative is identity, exactly the reference convention).

TPU-first: backward + momentum/decay weight update is ONE jitted function
per unit; XLA fuses the two matmuls (dW, err_input) with the update
arithmetic. Velocity buffers live on device across steps.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import numpy as np

from veles_tpu.ops import reference as ref
from veles_tpu.ops import xla as ox
from veles_tpu.ops.optim import SGDConfig, sgd_update
from veles_tpu.znicz import all2all
from veles_tpu.znicz.nn_units import GradientDescentBase, register_gd


@register_gd(all2all.All2All)
class GradientDescent(GradientDescentBase):
    """Backward for All2All-family layers. `activation` mirrors the forward
    twin and drives the output-expressed derivative (ops.reference
    act_backward semantics)."""

    activation = "linear"

    def initialize(self, device=None, **kwargs: Any):
        if not self.err_output or not self.weights:
            return False
        self._ensure_velocity()
        if not self.err_input or self.err_input.shape != self.input.shape:
            self.err_input.reset(np.zeros(self.input.shape,
                                          np.float32))
        return super().initialize(device=device, **kwargs)

    def xla_init(self):
        act = self.activation
        cfg = SGDConfig(lr=self.learning_rate,
                        momentum=self.gradient_moment,
                        weight_decay=self.weights_decay,
                        l1_decay=self.l1_decay,
                        lr_bias_mult=self.learning_rate_bias)

        def step(x, w, b, y, err_y, vw, vb, lr_scale):
            x2 = x.reshape(x.shape[0], -1)
            pre = ox.act_backward(act, y, err_y)
            pre2 = pre.reshape(pre.shape[0], -1)
            grads = {"w": x2.T @ pre2, "b": pre2.sum(axis=0)}
            err_x = (pre2 @ w.T).reshape(x.shape)
            new_p, new_v = sgd_update({"w": w, "b": b}, grads,
                                      {"w": vw, "b": vb}, cfg, lr_scale)
            return (err_x, new_p["w"], new_p["b"], new_v["w"], new_v["b"])

        self._fn = self.jit(step, donate_argnums=(5, 6))
        return None

    def numpy_run(self) -> None:
        y2 = self.output.mem.reshape(len(self.output), -1)
        ey2 = self.err_output.mem.reshape(len(self.err_output), -1)
        err_x, dw, db = ref.all2all_backward(
            self.input.mem, self.weights.mem, y2, ey2, self.activation)
        w, vw = self._sgd_host(self.weights.mem, dw, self.vel_w.mem, False)
        b, vb = self._sgd_host(self.bias.mem, db, self.vel_b.mem, True)
        self.err_input.mem = err_x
        self.weights.mem = w
        self.bias.mem = b
        self.vel_w.mem = vw
        self.vel_b.mem = vb

    def xla_run(self) -> None:
        d = self.device
        y2 = self.output.devmem(d).reshape(len(self.output), -1)
        ey2 = self.err_output.devmem(d).reshape(len(self.err_output), -1)
        err_x, w, b, vw, vb = self._fn(
            self.input.devmem(d), self.weights.devmem(d),
            self.bias.devmem(d), y2, ey2,
            self.vel_w.devmem(d), self.vel_b.devmem(d),
            jnp.float32(self.lr_scale))
        self.err_input.set_devmem(err_x)
        self.weights.set_devmem(w)
        self.bias.set_devmem(b)
        self.vel_w.set_devmem(vw)
        self.vel_b.set_devmem(vb)


@register_gd(all2all.All2AllTanh)
class GDTanh(GradientDescent):
    activation = "tanh"


@register_gd(all2all.All2AllRELU)
class GDRELU(GradientDescent):
    activation = "relu"


@register_gd(all2all.All2AllStrictRELU)
class GDStrictRELU(GradientDescent):
    activation = "strictrelu"


@register_gd(all2all.All2AllSigmoid)
class GDSigmoid(GradientDescent):
    activation = "sigmoid"


@register_gd(all2all.All2AllSoftmax)
class GDSoftmax(GradientDescent):
    """err_output from EvaluatorSoftmax is already wrt logits
    (probs − onehot), so the derivative pass-through is identity."""

    activation = "linear"
