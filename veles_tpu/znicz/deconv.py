"""Deconv (transposed convolution) forward unit.

Parity: reference `veles/znicz/deconv.py` (`Deconv`) — the adjoint of Conv
wrt its input, used by autoencoder decoders (SURVEY.md §2.8 "Autoencoder
units"). Like the reference, Deconv carries no bias, and its weights are
usually SHARED with the encoder's Conv twin via a data link
(`deconv.link_conv(conv)`), so the AE is tied-weight by default.

TPU-first: one `jax.linear_transpose` of the forward conv — XLA lowers it
to a single fractionally-strided convolution on the MXU (ops.xla
.deconv2d_forward); no hand-written col2im kernel.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Tuple

import numpy as np

from veles_tpu.ops import reference as ref
from veles_tpu.ops import xla as ox
from veles_tpu.znicz.nn_units import Forward


class Deconv(Forward):
    """y = deconv2d(x, W); x: (N,OH,OW,n_kernels), W: (ky,kx,C,n_kernels),
    y: (N,H,W,C). `n_channels` sets C when weights are owned (not linked
    from a Conv twin); `out_hw` pins the ambiguous strided output size."""

    def __init__(self, workflow=None, n_kernels: int = 16,
                 kx: int = 3, ky: int = 3,
                 stride: Tuple[int, int] = (1, 1),
                 padding: Tuple[int, int] = (0, 0),
                 n_channels: Optional[int] = None,
                 out_hw: Optional[Tuple[int, int]] = None,
                 **kwargs: Any) -> None:
        kwargs.setdefault("include_bias", False)
        super().__init__(workflow, **kwargs)
        self.n_kernels = n_kernels
        self.kx = kx
        self.ky = ky
        self.stride = tuple(stride)
        self.padding = tuple(padding)
        self.n_channels = n_channels
        self.out_hw = tuple(out_hw) if out_hw is not None else None

    def link_conv(self, conv) -> "Deconv":
        """Tie weights to the encoder Conv twin and take geometry from it
        (the reference AE wiring: Deconv reuses Conv's weights)."""
        self.link_attrs(conv, "weights")
        self.n_kernels = conv.n_kernels
        self.kx, self.ky = conv.kx, conv.ky
        self.stride, self.padding = conv.stride, conv.padding
        return self

    def output_hw(self) -> Tuple[int, int]:
        if self.out_hw is not None:
            return self.out_hw
        _, oh, ow, _ = self.input.shape
        sy, sx = self.stride
        ph, pw = self.padding
        return ((oh - 1) * sy + self.ky - 2 * ph,
                (ow - 1) * sx + self.kx - 2 * pw)

    def initialize(self, device=None, **kwargs: Any):
        if not self.input:
            return False
        n, oh, ow, oc = self.input.shape
        assert oc == self.n_kernels, (oc, self.n_kernels)
        if not self.weights:
            if self.n_channels is None:
                return False  # waiting for a linked Conv twin's weights
            fan_in = self.kx * self.ky * self.n_channels
            self.init_params(
                (self.ky, self.kx, self.n_channels, self.n_kernels), fan_in)
        c = self.weights.shape[2]
        h, w = self.output_hw()
        if not self.output or self.output.shape != (n, h, w, c):
            self.output.reset(np.zeros((n, h, w, c), np.float32))
        return super().initialize(device=device, **kwargs)

    def param_arrays(self):
        # weights may be TIED to the encoder conv (link_conv); the fused
        # step must not treat them as a second independent parameter
        if "weights" in self._linked_attrs:
            return {}
        return {"weights": self.weights}

    def xla_init(self):
        self._fn = self.jit(partial(
            ox.deconv2d_forward, stride=self.stride, padding=self.padding,
            out_hw=self.output_hw()))
        return None

    def fused_apply(self, params, x, *, key=None, train=True):
        w = params.get("weights")
        if w is None:  # tied weights: read the conv twin's live array
            import jax.numpy as jnp
            w = jnp.asarray(self.weights.mem)
        return ox.deconv2d_forward(x, w, self.stride, self.padding,
                                   self.output_hw())

    def numpy_run(self) -> None:
        self.output.mem = ref.deconv2d_forward(
            self.input.mem, self.weights.mem, self.stride, self.padding,
            self.output_hw())

    def xla_run(self) -> None:
        d = self.device
        self.output.set_devmem(self._fn(self.input.devmem(d),
                                        self.weights.devmem(d)))


from veles_tpu.znicz import standard_workflow as _sw  # noqa: E402

_sw.LAYER_TYPES.update({"deconv": Deconv})
