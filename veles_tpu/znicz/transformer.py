"""Sequence-preserving (time-distributed) layers for transformer LMs.

Not in the reference (SURVEY.md §5.7: the 2015 codebase has no attention
and its only sequence model host-unrolled an LSTM) — these units exist so
the long-context path (MultiHeadAttention + ring/Ulysses sequence
parallelism, znicz/attention.py) is reachable from a real TRAINING
workflow, not just ops-level tests.

House pattern: Forward twin + vjp-driven GD twin; `fused_apply` keeps the
(N, S, D) sequence structure so FusedTrainStep's "seq" mode can shard S
over the mesh "seq" axis. Granular mode flattens at the softmax head to
(N·S, V) so the standard EvaluatorSoftmax/Decision stack consumes
per-token predictions exactly like the char-LSTM convention
(loader/text.py).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from veles_tpu.memory import Array
from veles_tpu.ops import xla as ox
from veles_tpu.znicz.nn_units import (Forward, GradientDescentVJP,
                                      register_gd)


class SeqLinear(Forward):
    """Position-wise linear: x (N, S, Din) -> act(x @ W + b) (N, S, Dout),
    optionally adding a learned positional embedding (pos_embed=True —
    the embedding layer of a transformer LM when fed one-hot tokens).

    Under the fused "seq" mode the sequence dim is sharded; the pos table
    is replicated and each shard slices its own rows at
    axis_index * S_local (`seq_axis_name` is set by FusedTrainStep)."""

    def __init__(self, workflow=None, output_features: int = 64,
                 activation: str = "linear", pos_embed: bool = False,
                 max_seq: int = 0, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.output_features = output_features
        self.activation = activation
        self.pos_embed = pos_embed
        self.max_seq = max_seq
        self.pos = Array()
        #: set by FusedTrainStep in "seq" mode; None = sequence is local
        self.seq_axis_name = None

    def param_arrays(self) -> Dict[str, Array]:
        out = {"weights": self.weights, "bias": self.bias}
        if self.pos_embed:
            out["pos"] = self.pos
        return out

    def initialize(self, device=None, **kwargs: Any):
        if not self.input:
            return False
        n, s, din = self.input.shape
        dout = self.output_features
        self.init_params((din, dout), fan_in=din)
        if self.pos_embed:
            smax = self.max_seq or s
            if smax < s:
                # dynamic_slice CLAMPS out-of-range starts — an undersized
                # table would silently feed wrong/duplicated position rows
                raise ValueError(
                    f"pos_embed table max_seq={smax} shorter than the "
                    f"input sequence length {s}")
            if not self.pos:
                std = self.weights_stddev or self.default_stddev(din)
                self.pos.reset(self._fill((smax, dout),
                                          self.weights_filling, std))
        if not self.output or self.output.shape != (n, s, dout):
            self.output.reset(np.zeros((n, s, dout), np.float32))
        return super().initialize(device=device, **kwargs)

    def _apply(self, params, x, seq_axis_name=None):
        """seq_axis_name is passed EXPLICITLY by fused_apply (from the
        unit attr FusedTrainStep sets at trace time); the granular
        numpy_run/xla_run paths and the VJP GD twin call with the default
        None, so they never execute lax.axis_index outside a shard_map."""
        y = x @ params["weights"] + params["bias"]
        if self.pos_embed:
            s_loc = x.shape[1]
            if seq_axis_name is not None:
                off = lax.axis_index(seq_axis_name) * s_loc
            else:
                off = 0
            rows = lax.dynamic_slice_in_dim(params["pos"], off, s_loc, 0)
            y = y + rows[None]
        return ox.act_forward(self.activation, y)

    def fused_apply(self, params, x, *, key=None, train=True):
        return self._apply(params, x, seq_axis_name=self.seq_axis_name)

    def xla_init(self):
        self._fn = self.jit(lambda x, p: self._apply(p, x))
        return None

    def numpy_run(self) -> None:
        params = {k: jnp.asarray(a.mem)
                  for k, a in self.param_arrays().items()}
        self.output.mem = np.asarray(self._apply(params, self.input.mem))

    def xla_run(self) -> None:
        dv = self.device
        params = {k: a.devmem(dv) for k, a in self.param_arrays().items()}
        self.output.set_devmem(self._fn(self.input.devmem(dv), params))


class SeqFFN(Forward):
    """Transformer FFN block with residual: y = x + W2·act(W1·x + b1) + b2.
    x (N, S, E) -> (N, S, E); hidden width `hidden`. The residual add is
    element-wise, so it composes with sequence sharding untouched."""

    def __init__(self, workflow=None, hidden: int = 128,
                 activation: str = "tanh", **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.hidden = hidden
        self.activation = activation
        self.w2 = Array()
        self.b2 = Array()
        #: mesh axis for megatron TP under shard_map ("seq" mode with a
        #: model axis): W1 column-sharded, W2 row-sharded, one psum here.
        #: Set by FusedTrainStep at trace time; None = params whole.
        self.model_axis_name = None

    def param_arrays(self) -> Dict[str, Array]:
        return {"weights": self.weights, "bias": self.bias,
                "w2": self.w2, "b2": self.b2}

    def initialize(self, device=None, **kwargs: Any):
        if not self.input:
            return False
        n, s, e = self.input.shape
        h = self.hidden
        self.init_params((e, h), fan_in=e)
        if not self.w2:
            std = self.weights_stddev or self.default_stddev(h)
            self.w2.reset(self._fill((h, e), self.weights_filling, std))
            self.b2.reset(np.zeros((e,), np.float32))
        if not self.output or self.output.shape != (n, s, e):
            self.output.reset(np.zeros((n, s, e), np.float32))
        return super().initialize(device=device, **kwargs)

    def tp_param_specs(self, model_axis: str, m: int):
        """Megatron pair for shard_map TP: W1/b1 column-sharded (local
        hidden H/m, zero comms), W2 row-sharded (one psum in _apply).
        None when the hidden width does not divide the model axis."""
        from jax.sharding import PartitionSpec as P
        if self.hidden % m:
            return None
        return {"weights": P(None, model_axis), "bias": P(model_axis),
                "w2": P(model_axis, None), "b2": P()}

    def _apply(self, params, x, model_axis=None):
        hmid = ox.act_forward(self.activation,
                              x @ params["weights"] + params["bias"])
        y = hmid @ params["w2"]
        if model_axis is not None:
            # row-parallel W2: partial products sum over the model axis.
            # Justified stray-collective: the psum is this unit's OWN
            # megatron contract (tp_param_specs shards w2's contraction
            # dim) — its gradient arrives through this psum's transpose,
            # which the step modules cannot place on the unit's behalf
            # velint: disable=stray-collective
            y = lax.psum(y, model_axis)
        return x + y + params["b2"]

    def fused_apply(self, params, x, *, key=None, train=True):
        return self._apply(params, x, model_axis=self.model_axis_name)

    def xla_init(self):
        self._fn = self.jit(lambda x, p: self._apply(p, x))
        return None

    def numpy_run(self) -> None:
        params = {k: jnp.asarray(a.mem)
                  for k, a in self.param_arrays().items()}
        self.output.mem = np.asarray(self._apply(params, self.input.mem))

    def xla_run(self) -> None:
        dv = self.device
        params = {k: a.devmem(dv) for k, a in self.param_arrays().items()}
        self.output.set_devmem(self._fn(self.input.devmem(dv), params))


class SeqSoftmax(SeqLinear):
    """Per-position softmax head: x (N, S, E) -> logits (N, S, V) in the
    fused path (log-softmax CE consumes logits; sequence structure kept
    for the "seq" sharding), probabilities flattened to (N·S, V) in the
    granular path so EvaluatorSoftmax sees the char-LSTM convention."""

    fused_emits_logits = True

    def initialize(self, device=None, **kwargs: Any):
        ok = super().initialize(device=device, **kwargs)
        if ok is False:
            return False
        n, s, _ = self.input.shape
        v = self.output_features
        if self.output.shape != (n * s, v):
            self.output.reset(np.zeros((n * s, v), np.float32))
        return ok

    def numpy_run(self) -> None:
        params = {k: jnp.asarray(a.mem)
                  for k, a in self.param_arrays().items()}
        logits = self._apply(params, self.input.mem)
        probs = jax.nn.softmax(logits, axis=-1)
        self.output.mem = np.asarray(probs).reshape(-1, probs.shape[-1])

    def xla_init(self):
        def fn(x, p):
            probs = jax.nn.softmax(self._apply(p, x), axis=-1)
            return probs.reshape(-1, probs.shape[-1])

        self._fn = self.jit(fn)
        return None

    def xla_run(self) -> None:
        dv = self.device
        params = {k: a.devmem(dv) for k, a in self.param_arrays().items()}
        self.output.set_devmem(self._fn(self.input.devmem(dv), params))


@register_gd(SeqLinear)
class GDSeqLinear(GradientDescentVJP):
    pass


@register_gd(SeqFFN)
class GDSeqFFN(GradientDescentVJP):
    pass


@register_gd(SeqSoftmax)
class GDSeqSoftmax(GradientDescentVJP):
    """err_output arrives flattened (N·S, V) from the evaluator (probs −
    onehot over logits, the same gradient as log-softmax CE); the
    backward model therefore composes softmax-CE's logit gradient: we
    differentiate the LOGITS (N, S, V), so the incoming error is exactly
    dL/dlogits reshaped to sequence form."""

    def _err_reshape(self):
        n, s, _ = self.input.shape
        return (n, s, -1)


from veles_tpu.znicz import standard_workflow as _sw  # noqa: E402

_sw.LAYER_TYPES.update({"seq_linear": SeqLinear, "seq_ffn": SeqFFN,
                        "seq_softmax": SeqSoftmax})
