"""Znicz: the neural-network engine — forward units, paired gradient units,
evaluators, and the decision (training-loop controller) unit.

Parity: reference `veles/znicz/` package (named in BASELINE.json:4). Every
forward unit class has a matching gradient unit registered via
`nn_units.MATCHED_GD` (the reference used a `MatchingObject` metaclass
registry — SURVEY.md §2.8).
"""
