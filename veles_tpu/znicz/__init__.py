"""Znicz: the neural-network engine — forward units, paired gradient units,
evaluators, and the decision (training-loop controller) unit.

Parity: reference `veles/znicz/` package (named in BASELINE.json:4). Every
forward unit class has a matching gradient unit registered via
`nn_units.MATCHED_GD` (the reference used a `MatchingObject` metaclass
registry — SURVEY.md §2.8).
"""

# Importing the op-unit modules registers their layer types and GD pairs
# (standard_workflow first: the others append to its LAYER_TYPES).
from veles_tpu.znicz import standard_workflow  # noqa: F401, E402
from veles_tpu.znicz import (  # noqa: F401, E402
    activation, all2all, attention, conv, cutter, deconv, depooling,
    dropout, gd, gd_conv, gd_deconv, gd_pooling, kohonen, lstm, moe,
    normalization, pooling, rbm_units)
