"""Depooling unit: the adjoint of max pooling, for autoencoder decoders.

Parity: reference `veles/znicz/depooling.py` (`Depooling`, SURVEY.md §2.8
"Autoencoder units") — scatters each pooled activation back to the position
its max-pooling twin recorded (`input_offset`), producing a sparse
upsampled map. The paired gradient is the gather at those offsets.

Wiring: `depool.link_pool(maxpool)` aliases the offsets and the unpooled
shape from the encoder's pooling twin.
"""

from __future__ import annotations

from typing import Any, Tuple

import numpy as np

from veles_tpu.ops import reference as ref
from veles_tpu.ops import xla as ox
from veles_tpu.znicz.nn_units import Forward, GradientDescentBase, register_gd


class Depooling(Forward):
    """y[idx] += x — idx from the encoder MaxPooling's `input_offset`."""

    def __init__(self, workflow=None, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.output_shape: Tuple[int, ...] = ()

    def link_pool(self, pool) -> "Depooling":
        """Take winner offsets and the target (unpooled) shape from the
        encoder pooling twin."""
        self.link_attrs(pool, "input_offset")
        self._pool = pool
        return self

    def param_arrays(self):
        return {}

    def initialize(self, device=None, **kwargs: Any):
        if not self.input:
            return False
        pool = getattr(self, "_pool", None)
        if pool is not None:
            if not pool.input:
                return False
            self.output_shape = tuple(pool.input.shape)
        if not self.output_shape:
            raise ValueError(
                f"{self.name}: link_pool() or output_shape required")
        if not self.output or self.output.shape != self.output_shape:
            self.output.reset(np.zeros(self.output_shape, np.float32))
        return super().initialize(device=device, **kwargs)

    def xla_init(self):
        shape = tuple(self.output_shape)
        self._fn = self.jit(lambda x, idx: ox.depool_forward(x, idx, shape))
        return None

    def numpy_run(self) -> None:
        self.output.mem = ref.depool_forward(
            self.input.mem, self.input_offset.mem, self.output_shape)

    def xla_run(self) -> None:
        d = self.device
        self.output.set_devmem(self._fn(self.input.devmem(d),
                                        self.input_offset.devmem(d)))

    def __getstate__(self):
        d = super().__getstate__()
        d.pop("_pool", None)  # re-linked by the owning workflow on restore
        return d


@register_gd(Depooling)
class GDDepooling(GradientDescentBase):
    """err_input = err_output gathered at the recorded offsets."""

    def link_forward(self, fwd) -> "GDDepooling":
        self.link_attrs(fwd, "input", "input_offset")
        return self

    def initialize(self, device=None, **kwargs: Any):
        if not self.err_output:
            return False
        if not self.err_input or self.err_input.shape != self.input.shape:
            self.err_input.reset(np.zeros(self.input.shape, np.float32))
        return super().initialize(device=device, **kwargs)

    def xla_init(self):
        self._fn = self.jit(ox.depool_backward)
        return None

    def numpy_run(self) -> None:
        self.err_input.mem = ref.depool_backward(
            self.err_output.mem, self.input_offset.mem)

    def xla_run(self) -> None:
        d = self.device
        self.err_input.set_devmem(self._fn(self.err_output.devmem(d),
                                           self.input_offset.devmem(d)))
