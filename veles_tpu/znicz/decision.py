"""Decision unit: the training-loop controller living INSIDE the graph.

Parity: reference `veles/znicz/decision.py` (`DecisionBase`/`DecisionGD`) —
consumes evaluator stats per minibatch class, detects epoch boundaries,
tracks the best validation error and an `improved` flag (gates the
Snapshotter), and raises `complete` on stop conditions: `max_epochs`
reached, or no validation improvement for `fail_iterations` epochs.
The `complete` Bool gates the workflow's loop-back Repeater link and the
EndPoint — the training loop is data, not driver code (SURVEY.md §0).

Host-only unit: epoch bookkeeping is control flow, not tensor math.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from veles_tpu.accelerated_units import AcceleratedUnit
from veles_tpu.loader.base import TEST, TRAIN, VALIDATION
from veles_tpu.mutable import Bool
from veles_tpu.resilience.hooks import fire_epoch


class DecisionBase(AcceleratedUnit):
    #: abort the run with NonFiniteLossError the moment the evaluator's
    #: loss goes NaN/inf (the granular arm of --nonfinite-guard; the
    #: Launcher maps the error to exit 81 and the Supervisor rolls back
    #: one snapshot). Class attribute so snapshots never pickle it: a
    #: restored run re-opts-in via its own CLI flags.
    nonfinite_guard = False

    def __getstate__(self):
        # the Launcher arms the guard by INSTANCE attribute; strip it
        # from snapshots so the class-attribute contract above holds (a
        # restored run must re-opt-in via its own CLI flags)
        st = super().__getstate__()
        st.pop("nonfinite_guard", None)
        return st

    def __init__(self, workflow=None, max_epochs: Optional[int] = None,
                 fail_iterations: int = 100, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.max_epochs = max_epochs
        self.fail_iterations = fail_iterations
        self.complete = Bool(False, name=f"{self.name}.complete")
        self.improved = Bool(False, name=f"{self.name}.improved")
        self.epoch_number = 0
        # linked from the loader at wiring time:
        #   minibatch_class, last_minibatch, class_lengths, epoch_ended


class DecisionEpochs(DecisionBase):
    """Unsupervised loop controller: counts epochs off the loader's
    last-minibatch flag and completes at `max_epochs` (parity: the
    reference's Kohonen/AE decisions that stop on epoch count, with no
    evaluator in the loop)."""

    def numpy_run(self) -> None:
        if not bool(self.last_minibatch):
            return
        if int(self.minibatch_class) == TRAIN:
            self.epoch_number += 1
            self.debug("epoch %d done", self.epoch_number)
            if (self.max_epochs is not None
                    and self.epoch_number >= self.max_epochs):
                self.complete <<= True
            # process-level epoch boundary: heartbeats + epoch-keyed
            # fault injection (resilience.hooks; no-op when empty)
            fire_epoch(self.epoch_number)


class DecisionGD(DecisionBase):
    """Supervised-training decision driven by an evaluator's n_err/loss."""

    def __init__(self, workflow=None, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        # linked from the evaluator at wiring time: n_err, loss
        self.epoch_n_err = [0.0, 0.0, 0.0]       # per class (test/valid/train)
        self.epoch_metrics = [None, None, None]  # last completed epoch's
        self.best_validation_err = None
        self.best_epoch = 0
        #: per-epoch error history (reference web dashboard's error
        #: curves; also consumed by publishing reports): one record per
        #: completed TRAIN pass, granular and fused modes alike
        self.history: list = []
        self._accum = [0.0, 0.0, 0.0]
        self._epochs_since_improvement = 0

    def numpy_run(self) -> None:
        cls = int(self.minibatch_class)
        if self.nonfinite_guard and not np.isfinite(float(self.loss)):
            # the loss is ALREADY a host float here (the evaluator syncs
            # its scalars per minibatch in granular mode), so the guard
            # costs zero extra device round-trips. Raised before any
            # accumulation/snapshot gating: a poisoned epoch must never
            # look "improved".
            from veles_tpu.resilience import NonFiniteLossError
            raise NonFiniteLossError(
                f"non-finite loss {float(self.loss)!r} at epoch "
                f"{self.epoch_number} (class {cls} minibatch, granular "
                "mode)")
        self._accum[cls] += float(self.n_err)
        self.improved <<= False
        if not bool(self.last_minibatch):
            return
        # end of this class's pass
        self.epoch_n_err[cls] = self._accum[cls]
        self._accum[cls] = 0.0
        if cls == VALIDATION or (cls == TRAIN and
                                 self.class_lengths[VALIDATION] == 0):
            err = self.epoch_n_err[cls]
            if (self.best_validation_err is None
                    or err < self.best_validation_err):
                self.best_validation_err = err
                self.best_epoch = self.epoch_number
                self.improved <<= True
                self._epochs_since_improvement = 0
            else:
                self._epochs_since_improvement += 1
        if cls == TRAIN:
            self.epoch_metrics = list(self.epoch_n_err)
            self.epoch_number += 1
            if not hasattr(self, "history"):
                # snapshot from before history existed: resume must not
                # crash, it just starts recording from here
                self.history = []
            self.history.append({
                "epoch": self.epoch_number,
                "train_err": float(self.epoch_n_err[TRAIN]),
                "valid_err": float(self.epoch_n_err[VALIDATION]),
                "test_err": float(self.epoch_n_err[TEST]),
                "best_err": (None if self.best_validation_err is None
                             else float(self.best_validation_err)),
            })
            self.info(
                "epoch %d: train_err=%g valid_err=%g test_err=%g best=%s",
                self.epoch_number, self.epoch_n_err[TRAIN],
                self.epoch_n_err[VALIDATION], self.epoch_n_err[TEST],
                self.best_validation_err)
            if ((self.max_epochs is not None
                 and self.epoch_number >= self.max_epochs)
                    or self._epochs_since_improvement
                    >= self.fail_iterations):
                self.complete <<= True
            # process-level epoch boundary: heartbeats + epoch-keyed
            # fault injection (resilience.hooks; no-op when empty)
            fire_epoch(self.epoch_number)
