"""Recurrent units: LSTM over time, char-LM building blocks.

Parity: the reference's char-LSTM workflow (config 5 in BASELINE.json:10,
"znicz rnn units") built the recurrence OUT OF all2all+activation units
with explicit per-timestep unrolling in the unit graph, time-stepped on
host (SURVEY.md §5.7).

TPU-first redesign: the whole sequence is ONE `lax.scan` inside jit
(ops.xla.lstm_scan) — XLA compiles the time loop, keeps h/c on-chip, and
batches the three gate matmuls per step onto the MXU; the backward is
`jax.vjp` through the scan (compiled BPTT) instead of a graph of per-step
gradient units. The numpy golden twin is a hand-derived BPTT
(ops.reference.lstm_backward) — the cross-backend equivalence test pins
them against each other.

Layout: input (N, T, D); output is FLATTENED to (N*T, H) so a standard
All2All(Softmax) projection + EvaluatorSoftmax consume per-timestep
predictions unchanged (labels arrive flat from the text loader).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from veles_tpu import prng
from veles_tpu.memory import Array
from veles_tpu.ops import reference as ref
from veles_tpu.ops import xla as ox
from veles_tpu.ops.optim import SGDConfig, sgd_update
from veles_tpu.znicz.nn_units import (Forward, GradientDescentBase,
                                      register_gd)


class LSTM(Forward):
    """Scan-compiled LSTM; params wx (D,4H), wh (H,4H), b (4H,)."""

    def __init__(self, workflow=None, n_units: int = 128,
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.n_units = n_units
        self.wx = Array()
        self.wh = Array()
        self.b = Array()

    def param_arrays(self) -> Dict[str, Array]:
        return {"wx": self.wx, "wh": self.wh, "b": self.b}

    def initialize(self, device=None, **kwargs: Any):
        if not self.input:
            return False
        n, t, d = self.input.shape
        h = self.n_units
        if not self.wx:
            std = self.weights_stddev or self.default_stddev(d)
            self.wx.reset(self._fill((d, 4 * h), self.weights_filling, std))
            std_h = self.weights_stddev or self.default_stddev(h)
            self.wh.reset(self._fill((h, 4 * h), self.weights_filling,
                                     std_h))
            b = np.zeros((4 * h,), np.float32)
            b[h:2 * h] = 1.0  # forget-gate bias init (standard practice)
            self.b.reset(b)
        if not self.output or self.output.shape != (n * t, h):
            self.output.reset(np.zeros((n * t, h), np.float32))
        return super().initialize(device=device, **kwargs)

    def _zeros_hc(self, n):
        h = self.n_units
        return np.zeros((n, h), np.float32), np.zeros((n, h), np.float32)

    def xla_init(self):
        def fwd(x, wx, wh, b):
            n, t, d = x.shape
            h0 = jnp.zeros((n, self.n_units), x.dtype)
            hs, _, _ = ox.lstm_scan(x.transpose(1, 0, 2), h0, h0, wx, wh, b)
            return hs.transpose(1, 0, 2).reshape(n * t, self.n_units)

        self._fn = self.jit(fwd)
        return None

    def fused_apply(self, params, x, *, key=None, train=True):
        n, t, d = x.shape
        h0 = jnp.zeros((n, self.n_units), x.dtype)
        hs, _, _ = ox.lstm_scan(x.transpose(1, 0, 2), h0, h0,
                                params["wx"], params["wh"], params["b"])
        return hs.transpose(1, 0, 2).reshape(n * t, self.n_units)

    def numpy_run(self) -> None:
        x = self.input.mem
        n, t, d = x.shape
        h0, c0 = self._zeros_hc(n)
        hs, cache = ref.lstm_forward(x.transpose(1, 0, 2), h0, c0,
                                     self.wx.mem, self.wh.mem, self.b.mem)
        self._cache = cache
        self.output.mem = hs.transpose(1, 0, 2).reshape(n * t, self.n_units)

    def xla_run(self) -> None:
        d = self.device
        self.output.set_devmem(self._fn(
            self.input.devmem(d), self.wx.devmem(d), self.wh.devmem(d),
            self.b.devmem(d)))

    def __getstate__(self):
        st = super().__getstate__()
        st.pop("_cache", None)  # per-step scratch, rebuilt each forward
        return st


@register_gd(LSTM)
class GDLSTM(GradientDescentBase):
    """BPTT + SGD update. XLA path: jax.vjp through the scan, fused with
    the momentum update; numpy path: the hand-derived golden BPTT."""

    def link_forward(self, fwd: LSTM) -> "GDLSTM":
        self.link_attrs(fwd, "wx", "wh", "b", "input", "output")
        self._fwd = fwd
        return self

    def initialize(self, device=None, **kwargs: Any):
        if not self.err_output or not self.wx:
            return False
        for vname, p in (("vel_wx", self.wx), ("vel_wh", self.wh),
                         ("vel_b", self.b)):
            v = getattr(self, vname, None)
            if v is None or not v:
                arr = Array()
                arr.reset(np.zeros(p.shape, p.dtype))
                setattr(self, vname, arr)
        if not self.err_input or self.err_input.shape != self.input.shape:
            self.err_input.reset(np.zeros(self.input.shape, np.float32))
        return super().initialize(device=device, **kwargs)

    def xla_init(self):
        n_units = self._fwd.n_units
        cfg = SGDConfig(lr=self.learning_rate,
                        momentum=self.gradient_moment,
                        weight_decay=self.weights_decay,
                        l1_decay=self.l1_decay)

        def step(x, wx, wh, b, err_y, vwx, vwh, vb, lr_scale):
            n, t, d = x.shape

            def fwd(params, xx):
                h0 = jnp.zeros((n, n_units), xx.dtype)
                hs, _, _ = ox.lstm_scan(xx.transpose(1, 0, 2), h0, h0,
                                        params["wx"], params["wh"],
                                        params["b"])
                return hs.transpose(1, 0, 2).reshape(n * t, n_units)

            params = {"wx": wx, "wh": wh, "b": b}
            _, vjp = jax.vjp(fwd, params, x)
            grads, err_x = vjp(err_y)
            new_p, new_v = sgd_update(
                params, grads, {"wx": vwx, "wh": vwh, "b": vb}, cfg,
                lr_scale)
            return (err_x, new_p["wx"], new_p["wh"], new_p["b"],
                    new_v["wx"], new_v["wh"], new_v["b"])

        self._fn = self.jit(step, donate_argnums=(5, 6, 7))
        return None

    def numpy_run(self) -> None:
        x = self.input.mem
        n, t, d = x.shape
        cache = getattr(self._fwd, "_cache", None)
        if cache is None:  # forward ran on the other backend: rebuild
            h0 = np.zeros((n, self._fwd.n_units), np.float32)
            _, cache = ref.lstm_forward(x.transpose(1, 0, 2), h0, h0,
                                        self.wx.mem, self.wh.mem,
                                        self.b.mem)
        dhs = self.err_output.mem.reshape(n, t, -1).transpose(1, 0, 2)
        dxs, dwx, dwh, db = ref.lstm_backward(
            x.transpose(1, 0, 2), self.wx.mem, self.wh.mem, dhs, cache)
        self.err_input.mem = dxs.transpose(1, 0, 2)
        for p, g, v in ((self.wx, dwx, self.vel_wx),
                        (self.wh, dwh, self.vel_wh),
                        (self.b, db, self.vel_b)):
            new_p, new_v = self._sgd_host(p.mem, g, v.mem, False)
            p.mem = new_p
            v.mem = new_v

    def xla_run(self) -> None:
        d = self.device
        out = self._fn(self.input.devmem(d), self.wx.devmem(d),
                       self.wh.devmem(d), self.b.devmem(d),
                       self.err_output.devmem(d), self.vel_wx.devmem(d),
                       self.vel_wh.devmem(d), self.vel_b.devmem(d),
                       jnp.float32(self.lr_scale))
        err_x, wx, wh, b, vwx, vwh, vb = out
        self.err_input.set_devmem(err_x)
        self.wx.set_devmem(wx)
        self.wh.set_devmem(wh)
        self.b.set_devmem(b)
        self.vel_wx.set_devmem(vwx)
        self.vel_wh.set_devmem(vwh)
        self.vel_b.set_devmem(vb)

    def __getstate__(self):
        st = super().__getstate__()
        st.pop("_fwd", None)  # re-linked on restore by the workflow
        return st


from veles_tpu.znicz import standard_workflow as _sw  # noqa: E402

_sw.LAYER_TYPES.update({"lstm": LSTM})
