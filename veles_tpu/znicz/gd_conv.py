"""Gradient units for convolutional layers.

Parity: reference `veles/znicz/gd_conv.py` — `GradientDescentConv`,
`GDTanhConv`, `GDRELUConv`, `GDStrictRELUConv` (SURVEY.md §2.8).

TPU-first: the backward is the exact adjoint of the forward conv, obtained
with `jax.vjp` over the linear convolution inside ONE jitted step fused
with the momentum/decay weight update — replacing the reference's three
hand-written kernels (err_input col2im, dW implicit-GEMM, weight update).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from veles_tpu.ops import reference as ref
from veles_tpu.ops import xla as ox
from veles_tpu.ops.optim import SGDConfig, sgd_update
from veles_tpu.znicz import conv
from veles_tpu.znicz.nn_units import GradientDescentBase, register_gd


@register_gd(conv.Conv)
class GradientDescentConv(GradientDescentBase):
    """Backward for the Conv family. Needs the twin's stride/padding, which
    `link_forward` captures along with the standard data links."""

    activation = "linear"

    def __init__(self, workflow=None, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.stride = (1, 1)
        self.padding = (0, 0)

    def link_forward(self, fwd):
        self.stride = fwd.stride
        self.padding = fwd.padding
        return super().link_forward(fwd)

    def initialize(self, device=None, **kwargs: Any):
        if not self.err_output or not self.weights:
            return False
        self._ensure_velocity()
        if not self.err_input or self.err_input.shape != self.input.shape:
            self.err_input.reset(np.zeros(self.input.shape, np.float32))
        return super().initialize(device=device, **kwargs)

    def xla_init(self):
        act = self.activation
        stride, padding = self.stride, self.padding
        cfg = SGDConfig(lr=self.learning_rate,
                        momentum=self.gradient_moment,
                        weight_decay=self.weights_decay,
                        l1_decay=self.l1_decay,
                        lr_bias_mult=self.learning_rate_bias)

        def lin(x, w, b):
            ph, pw = padding
            return lax.conv_general_dilated(
                x, w, window_strides=stride, padding=[(ph, ph), (pw, pw)],
                dimension_numbers=("NHWC", "HWIO", "NHWC")) + b

        def step(x, w, b, y, err_y, vw, vb, lr_scale):
            pre = ox.act_backward(act, y, err_y)
            _, vjp = jax.vjp(lin, x, w, b)
            err_x, dw, db = vjp(pre)
            new_p, new_v = sgd_update({"w": w, "b": b}, {"w": dw, "b": db},
                                      {"w": vw, "b": vb}, cfg, lr_scale)
            return (err_x, new_p["w"], new_p["b"], new_v["w"], new_v["b"])

        self._fn = self.jit(step, donate_argnums=(5, 6))
        return None

    def numpy_run(self) -> None:
        err_x, dw, db = ref.conv2d_backward(
            self.input.mem, self.weights.mem, self.output.mem,
            self.err_output.mem, self.stride, self.padding, self.activation)
        w, vw = self._sgd_host(self.weights.mem, dw, self.vel_w.mem, False)
        b, vb = self._sgd_host(self.bias.mem, db, self.vel_b.mem, True)
        self.err_input.mem = err_x
        self.weights.mem = w
        self.bias.mem = b
        self.vel_w.mem = vw
        self.vel_b.mem = vb

    def xla_run(self) -> None:
        d = self.device
        err_x, w, b, vw, vb = self._fn(
            self.input.devmem(d), self.weights.devmem(d),
            self.bias.devmem(d), self.output.devmem(d),
            self.err_output.devmem(d),
            self.vel_w.devmem(d), self.vel_b.devmem(d),
            jnp.float32(self.lr_scale))
        self.err_input.set_devmem(err_x)
        self.weights.set_devmem(w)
        self.bias.set_devmem(b)
        self.vel_w.set_devmem(vw)
        self.vel_b.set_devmem(vb)


@register_gd(conv.ConvTanh)
class GDTanhConv(GradientDescentConv):
    activation = "tanh"


@register_gd(conv.ConvRELU)
class GDRELUConv(GradientDescentConv):
    activation = "relu"


@register_gd(conv.ConvStrictRELU)
class GDStrictRELUConv(GradientDescentConv):
    activation = "strictrelu"


@register_gd(conv.ConvSigmoid)
class GDSigmoidConv(GradientDescentConv):
    activation = "sigmoid"
