"""veles_tpu — a TPU-native deep-learning workflow framework.

A from-scratch rebuild of the capabilities of the reference framework
(tfwu/veles, i.e. the Samsung VELES platform + Znicz NN engine): a
model/experiment is a *Workflow* — a graph of *Units* wired by control links
(`link_from`) and data links (`link_attrs`) — but the execution substrate is
JAX/XLA on TPU instead of hand-written OpenCL/CUDA kernels, and distributed
training is a synchronous ICI all-reduce inside a sharded, jit-compiled train
step instead of Twisted/ZeroMQ master–slave parameter averaging.

Layer map (mirrors SURVEY.md §1):
  L0 foundation      — config, logger, mutable (Bool/links), prng
  L1 device/memory   — backends (Device/XLADevice/NumpyDevice), memory (Array)
  L2 runtime         — units, workflow, accelerated_units, distributable
  L3 parallel        — mesh/sharding/collectives/ring-attention (parallel/)
  L4 services        — snapshotter, plotting, results
  L5 data            — loader/
  L6 NN engine       — znicz/ (ops in ops/, units in znicz/)
  L7 entry           — __main__, launcher, znicz/samples/

Reference parity citations use `veles/<path> (Symbol)` form: the reference
mount was empty at survey time (SURVEY.md §"Evidence & Provenance"), so no
file:line numbers exist to cite.
"""

__version__ = "0.1.0"

from veles_tpu.config import root, Config  # noqa: F401
from veles_tpu.mutable import Bool  # noqa: F401
