"""Workflow: a container of units that self-schedules a pulse-driven graph.

Parity: reference `veles/workflow.py` (`Workflow`, `StartPoint`, `EndPoint`,
`Repeater`) — `initialize()` walks all units (device injection, allocation,
retrying units whose data links are not ready yet); `run()` fires the start
point and pumps pulses until the end point runs or `stop()` is called; a
per-unit accumulated run-time table is reported at the end (the reference's
built-in profiler).

Scheduling note (TPU-first): the reference used a thread pool because OpenCL
kernel enqueues block; jax dispatch is asynchronous already, so a
single-threaded event loop is both sufficient and faster (no GIL churn). The
loop is deterministic: units fire in pulse-arrival order.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Optional

from veles_tpu.units import Container, TrivialUnit, Unit


class StartPoint(TrivialUnit):
    pass


class EndPoint(TrivialUnit):
    """Running the end point stops the owning workflow's pump."""

    def run(self) -> None:
        self.workflow.on_end_point()


class Repeater(TrivialUnit):
    """OR-gate merge unit used to close training loops (parity: reference
    `Repeater` in `veles/workflow.py`)."""

    or_gate = True


class Workflow(Container):
    """A Unit that contains units and runs them as a pulse-driven graph."""

    def __init__(self, workflow: Optional[Unit] = None, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.start_point = StartPoint(self)
        self.end_point = EndPoint(self)
        self.stopped = False
        self.device = None
        self._queue: deque = deque()
        self.run_total_time = 0.0

    # -- lifecycle -----------------------------------------------------------

    def initialize(self, device=None, **kwargs: Any) -> None:
        """Initialize all units. Units may return False to be retried after
        the others (mirrors the reference's deferred-initialization loop).

        `verify="error"|"warn"|"off"` (default "warn") runs the static
        graph verifier (analysis/graph.py) over the constructed graph
        first: "warn" logs every finding and continues, "error"
        additionally raises WorkflowVerifyError on error-severity
        findings, "off" skips the pass."""
        verify = kwargs.pop("verify", "warn")
        if verify not in ("off", "warn", "error"):
            raise ValueError(f"verify={verify!r}: expected "
                             "'error', 'warn' or 'off'")
        if verify != "off":
            from veles_tpu.analysis.graph import (WorkflowVerifyError,
                                                  verify_workflow)
            findings = verify_workflow(self)
            errs = []
            for f in findings:
                if f.severity == "error":
                    errs.append(f)
                    self.error("verify: %s", f.format())
                else:
                    self.warning("verify: %s", f.format())
            if errs and verify == "error":
                raise WorkflowVerifyError(errs)
        self.device = device
        super().initialize(**kwargs)
        pending = list(self.units)
        while pending:
            retry = []
            for unit in pending:
                if unit.initialize(device=device, **kwargs) is False:
                    retry.append(unit)
                else:
                    unit._initialized = True
            if len(retry) == len(pending):
                names = [u.name for u in retry]
                raise RuntimeError(
                    f"workflow initialization deadlock; unresolved: {names}")
            pending = retry

    def schedule(self, unit: Unit) -> None:
        self._queue.append(unit)

    def run(self) -> None:
        """Pump pulses from start_point until end_point or stop()."""
        self.stopped = False
        start = time.perf_counter()
        self._queue.clear()
        for unit in self.units:  # clear stale pulses from any previous run
            for src in unit._links_from:
                unit._links_from[src] = False
        self.schedule(self.start_point)
        try:
            while self._queue and not self.stopped:
                self._queue.popleft().fire()
        finally:
            # teardown must run even when a unit raised (Ctrl-C mid-run
            # used to leave prefetch/plotter threads alive): every unit's
            # stop() is invoked, failures logged, none masking the
            # original exception
            self.run_total_time += time.perf_counter() - start
            self._stop_units()

    def on_end_point(self) -> None:
        self.stopped = True

    def _stop_units(self) -> None:
        for unit in self.units:
            if unit is self:
                continue
            try:
                unit.stop()
            except Exception as e:   # noqa: BLE001 — teardown best-effort
                self.warning("stop() of %s failed: %s", unit.name, e)

    def stop(self) -> None:
        """Stop the pump loop AND release unit-owned background resources
        (prefetch pools, plotter renderer threads) — callable from any
        thread and idempotent."""
        self.stopped = True
        self._stop_units()

    # -- reporting -----------------------------------------------------------

    def print_stats(self) -> str:
        """Per-unit accumulated wall-time table (the reference's end-of-run
        profiler); returns the formatted table and logs it."""
        total = self.run_total_time
        rows = sorted((u for u in self.units if u.run_count),
                      key=lambda u: -u.run_time)
        lines = [f"{'unit':<32} {'runs':>8} {'time':>10} {'%':>6}"]
        for u in rows:
            pct = 100.0 * u.run_time / total if total > 0 else 0.0
            lines.append(
                f"{u.name:<32} {u.run_count:>8} {u.run_time:>9.3f}s {pct:>5.1f}%")
        lines.append(f"{'TOTAL':<32} {'':>8} {total:>9.3f}s")
        table = "\n".join(lines)
        self.info("run-time stats:\n%s", table)
        return table
