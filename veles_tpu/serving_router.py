"""Fleet front door for the serving tier (ISSUE 19, ROADMAP dir 3).

One host (or many) runs N independent :class:`~veles_tpu.serving
.InferenceServer` slot rings; this module makes them a *fleet*:

- **ReplicaBeacon** — each replica publishes a presence beacon on the
  mirror bus (`serve_replica_<rid>.json`, the PR-10 presence-beacon
  discipline pointed at serving): status up/draining/gone, the live
  `/healthz` capacity hint, the blue/green generation labels, and a
  monotonic seq so a torn read can never roll a replica's state
  backwards. Beacons are meta records (no ".pickle" in the name), so
  they are invisible to the snapshot plane.
- **RouterCore** — a PURE routing state machine (no threads, no
  sockets, no clock of its own: every method takes `now`). It owns the
  per-replica registry: capacity-weighted pick, per-replica
  Retry-After backpressure windows, a per-replica circuit breaker
  (closed → open after `fail_threshold` consecutive transport
  failures → half-open single probe → closed on success), a frugal
  p99 latency estimator that feeds request hedging, and drain
  discipline (a draining replica finishes its in-flight rounds but is
  never picked again — invariant 9, `mc-no-route-to-drained`, which
  `analysis/modelcheck.py` exhausts this class against directly).
- **ServingRouter** — the HTTP shell: discovers replicas from the bus
  (`Mirror.meta_names` — open membership, so join-mid-run needs no
  config push), proxies `POST /predict` with bounded
  retry-with-timeout (`resilience/backoff.py`), hedges to a second
  replica when the first exceeds the measured p99, fans `POST
  /rollback` out to every live replica, and aggregates the fleet view
  at `GET /fleet`. Every failure mode degrades to a
  shed-with-Retry-After — never a hung client.

Trust model: the router and the replicas share ONE token
(`X-Veles-Token`, `http_util.check_shared_token`): clients auth to the
router, the router re-presents the same token to replicas, and the
beacon bus is the same mirror the weight plane already trusts. The
router never reads request bodies beyond `max_body` and never forwards
anything but the verbatim client body — it holds no model state at
all, which is what makes it restartable at any moment.

Clock discipline: this module is inside velint's `raw-clock` scope —
no direct `time.*` calls; everything goes through an injected
:class:`~veles_tpu.resilience.clock.Clock` so the model checker and
the unit tests own time deterministically.
"""

from __future__ import annotations

import json
import math
import threading
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from veles_tpu.logger import Logger
from veles_tpu.resilience.backoff import backoff_delay
from veles_tpu.resilience.clock import Clock, SYSTEM_CLOCK

#: meta-record name prefix for serving-fleet presence beacons; the
#: suffix is the replica id. `Mirror.meta_names(BEACON_PREFIX)` is the
#: router's whole discovery protocol.
BEACON_PREFIX = "serve_replica_"

#: consecutive transport failures before a replica's circuit opens
FAIL_THRESHOLD = 3

#: seconds an open circuit waits before allowing the half-open probe
CIRCUIT_OPEN_S = 5.0

#: beacon silence after which a replica is presumed dead and evicted.
#: Deliberately MANY beacon intervals: a briefly-unreachable mirror
#: must not amputate a healthy fleet (the mirror-unreachable chaos
#: scenario) — during an outage no beacon refreshes, so the registry
#: coasts on last-known state until this TTL.
BEACON_TTL_S = 20.0

#: floor for the hedge trigger: below this a hedge costs more than it
#: saves (connection + dispatch overhead)
HEDGE_FLOOR_S = 0.05

#: Retry-After the router tells clients when NO replica can take the
#: request right now and no replica published a tighter hint
DEFAULT_RETRY_AFTER_S = 1.0


def beacon_name(rid: str) -> str:
    """Meta-record name for replica `rid`'s beacon. `rid` is
    constrained to filename-safe characters because it becomes part of
    a mirror meta name (DirMirror: a file under the mirror root)."""
    if not rid or not all(c.isalnum() or c in "._-" for c in rid):
        raise ValueError(f"replica id must be [A-Za-z0-9._-]+: {rid!r}")
    return f"{BEACON_PREFIX}{rid}.json"


class ReplicaState:
    """Router-side view of one replica. Mutated only by RouterCore
    (which is itself guarded by the ServingRouter's lock)."""

    __slots__ = ("rid", "url", "capacity", "status", "seq", "last_seen",
                 "not_before", "fails", "circuit", "open_until",
                 "inflight", "ewma_s", "p99_s", "n_ok", "n_fail",
                 "generation", "gen_age_s")

    def __init__(self, rid: str, url: str, now: float) -> None:
        self.rid = rid
        self.url = url
        self.capacity = 1.0
        self.status = "up"            # up | draining
        self.seq = -1
        self.last_seen = now
        self.not_before = 0.0         # Retry-After backpressure window
        self.fails = 0                # consecutive transport failures
        self.circuit = "closed"       # closed | open | half_open
        self.open_until = 0.0
        self.inflight = 0             # router-tracked, not replica's
        self.ewma_s = 0.0             # mean dispatch latency EWMA
        self.p99_s = 0.0              # frugal p99 estimate (hedging)
        self.n_ok = 0
        self.n_fail = 0
        self.generation = None        # live digest from the beacon
        self.gen_age_s = None

    def view(self, now: float) -> Dict[str, Any]:
        return {"rid": self.rid, "url": self.url,
                "status": self.status, "capacity": self.capacity,
                "circuit": self.circuit, "inflight": self.inflight,
                "fails": self.fails, "n_ok": self.n_ok,
                "n_fail": self.n_fail,
                "silent_for_s": round(max(0.0, now - self.last_seen), 3),
                "backpressure_s":
                    round(max(0.0, self.not_before - now), 3),
                "ewma_s": round(self.ewma_s, 6),
                "p99_s": round(self.p99_s, 6),
                "generation": self.generation,
                "generation_age_s": self.gen_age_s}


class RouterCore:
    """Pure fleet-routing state machine. Single-threaded by contract:
    the HTTP shell serializes access under its lock; the model checker
    calls it directly. No clock — callers pass `now` (monotonic
    seconds) so a VirtualClock can own time."""

    def __init__(self, fail_threshold: int = FAIL_THRESHOLD,
                 open_s: float = CIRCUIT_OPEN_S,
                 beacon_ttl_s: float = BEACON_TTL_S) -> None:
        self.replicas: Dict[str, ReplicaState] = {}
        self.fail_threshold = max(1, int(fail_threshold))
        self.open_s = float(open_s)
        self.beacon_ttl_s = float(beacon_ttl_s)
        self._rr = 0                  # rotation among weight-ties
        #: rid -> last seq seen before TTL eviction. A crashed
        #: replica's beacon file stays on the mirror; without this the
        #: next poll would re-create the corpse with a fresh last_seen
        #: and it would flap in and out of the registry forever. Only
        #: a seq ADVANCE past the tombstone (the replica actually came
        #: back) clears it.
        self._tombstones: Dict[str, int] = {}

    # -- registry (beacon plane) ------------------------------------------

    def observe_beacon(self, rec: Dict[str, Any], now: float
                       ) -> Optional[str]:
        """Apply one beacon record; returns the rid on a state-bearing
        update, None for malformed/stale records. A `seq` below the
        last seen one is a torn/stale read and is ignored — a replica's
        lifecycle (up → draining → gone) never rolls backwards."""
        rid = rec.get("rid")
        url = rec.get("url")
        status = rec.get("status")
        if not isinstance(rid, str) or not isinstance(url, str) \
                or status not in ("up", "draining", "gone"):
            return None
        try:
            seq = int(rec.get("seq", 0))
        except (TypeError, ValueError):
            return None
        dead_seq = self._tombstones.get(rid)
        if dead_seq is not None:
            if seq <= dead_seq:
                return None   # the evicted corpse's file, re-listed
            del self._tombstones[rid]
        st = self.replicas.get(rid)
        if st is not None and seq < st.seq:
            return None
        if status == "gone":
            self.replicas.pop(rid, None)
            return rid
        if st is None:
            st = self.replicas[rid] = ReplicaState(rid, url, now)
        elif seq > st.seq:
            # liveness = the beacon ADVANCED. A crashed replica's last
            # record stays on the mirror forever; re-reading that same
            # seq must not count as a heartbeat or the TTL eviction
            # below would never fire.
            st.last_seen = now
        st.url = url
        st.seq = seq
        st.status = status
        try:
            st.capacity = max(1.0, float(rec.get("capacity", 1.0)))
        except (TypeError, ValueError):
            st.capacity = 1.0
        gen = rec.get("generation")
        if isinstance(gen, dict):
            st.generation = gen.get("digest")
            st.gen_age_s = gen.get("serving_for_s")
        return rid

    def evict_silent(self, now: float) -> List[str]:
        """Drop replicas whose beacon went silent past the TTL (crashed
        without a 'gone' beacon). Returns the evicted rids. The evicted
        seq is tombstoned so the beacon file the corpse left on the
        mirror cannot re-register it (found by the pass-8 fleet
        scenario: without the tombstone, eviction and re-discovery
        alternate every TTL)."""
        dead = [rid for rid, st in self.replicas.items()
                if now - st.last_seen > self.beacon_ttl_s]
        for rid in dead:
            self._tombstones[rid] = self.replicas[rid].seq
            del self.replicas[rid]
        return dead

    # -- pick -------------------------------------------------------------

    def _eligible(self, st: ReplicaState, now: float) -> bool:
        if st.status != "up":          # invariant 9: never route to a
            return False               # draining/deregistered replica
        if st.not_before > now:        # replica told us to back off
            return False
        if st.circuit == "open":
            if now < st.open_until:
                return False
            st.circuit = "half_open"   # readmission probe window
        if st.circuit == "half_open" and st.inflight > 0:
            return False               # exactly one probe at a time
        return True

    def pick(self, now: float, exclude: Tuple[str, ...] = ()
             ) -> Optional[str]:
        """Best replica to dispatch to right now, or None when the
        fleet has no capacity (caller sheds with Retry-After). Weight
        is `capacity / (1 + router-tracked inflight)` — the live
        /healthz capacity hint discounted by what we already sent
        there; weight ties rotate round-robin (a counter, so the
        choice stays deterministic and the model checker can replay
        schedules) — without the rotation a sequential client would
        pin the lexicographically-first replica forever."""
        cands: List[Tuple[float, str]] = []
        for rid in sorted(self.replicas):
            if rid in exclude:
                continue
            st = self.replicas[rid]
            if not self._eligible(st, now):
                continue
            cands.append((st.capacity / (1.0 + st.inflight), rid))
        if not cands:
            return None
        best_w = max(w for w, _ in cands)
        ties = [rid for w, rid in cands if w >= best_w - 1e-12]
        rid = ties[self._rr % len(ties)]
        self._rr += 1
        return rid

    def min_retry_after(self, now: float) -> float:
        """Shed hint when pick() returned None: the soonest any
        replica's backpressure window reopens, clamped to the default
        when nothing tighter is known."""
        waits = [st.not_before - now for st in self.replicas.values()
                 if st.status == "up" and st.not_before > now]
        if waits:
            return max(0.05, min(min(waits), DEFAULT_RETRY_AFTER_S * 30))
        return DEFAULT_RETRY_AFTER_S

    # -- dispatch outcomes ------------------------------------------------

    def note_dispatch(self, rid: str) -> None:
        st = self.replicas.get(rid)
        if st is not None:
            st.inflight += 1

    def note_ok(self, rid: str, latency_s: float) -> None:
        """Successful dispatch: closes the circuit (a half-open probe
        that succeeds readmits the replica), clears the failure streak,
        and feeds the latency estimators."""
        st = self.replicas.get(rid)
        if st is None:
            return
        st.inflight = max(0, st.inflight - 1)
        st.fails = 0
        st.circuit = "closed"
        st.n_ok += 1
        x = max(0.0, float(latency_s))
        st.ewma_s = x if st.ewma_s == 0.0 \
            else 0.8 * st.ewma_s + 0.2 * x
        # frugal p99: step up 5% of the sample when exceeded, down
        # 5%/99 otherwise — equilibrium where ~1% of samples exceed
        if st.p99_s == 0.0:
            st.p99_s = x
        elif x > st.p99_s:
            st.p99_s += 0.05 * x
        else:
            st.p99_s = max(0.0, st.p99_s - (0.05 / 99.0) * x)

    def note_fail(self, rid: str, now: float) -> None:
        """Transport failure (connect refused / timeout / 5xx without
        backpressure semantics). `fail_threshold` consecutive ones —
        or ANY failure of a half-open probe — open the circuit."""
        st = self.replicas.get(rid)
        if st is None:
            return
        st.inflight = max(0, st.inflight - 1)
        st.fails += 1
        st.n_fail += 1
        if st.circuit == "half_open" or st.fails >= self.fail_threshold:
            st.circuit = "open"
            st.open_until = now + self.open_s
            st.fails = 0

    def note_shed(self, rid: str, retry_after_s: float, now: float
                  ) -> None:
        """503 + Retry-After from the replica: backpressure, NOT a
        failure — the replica is alive and told us when to come back.
        Does not touch the circuit or the failure streak."""
        st = self.replicas.get(rid)
        if st is None:
            return
        st.inflight = max(0, st.inflight - 1)
        st.fails = 0
        st.not_before = max(st.not_before,
                            now + max(0.0, float(retry_after_s)))

    # -- views ------------------------------------------------------------

    def hedge_after_s(self, rid: str) -> Optional[float]:
        """Seconds to wait on `rid` before hedging to a second replica:
        the measured p99, floored — None until enough signal exists."""
        st = self.replicas.get(rid)
        if st is None or st.n_ok < 10 or st.p99_s <= 0.0:
            return None
        return max(HEDGE_FLOOR_S, st.p99_s)

    def live(self) -> List[str]:
        """rids the control plane should fan admin verbs out to —
        everything registered, up or draining (a draining replica
        still serves its in-flight generation)."""
        return sorted(self.replicas)

    def routable(self, now: float) -> int:
        return sum(1 for st in self.replicas.values()
                   if st.status == "up")

    def fleet_capacity(self) -> float:
        return sum(st.capacity for st in self.replicas.values()
                   if st.status == "up")

    def snapshot(self, now: float) -> Dict[str, Any]:
        return {"replicas": [self.replicas[r].view(now)
                             for r in sorted(self.replicas)],
                "routable": self.routable(now),
                "fleet_capacity": self.fleet_capacity()}


class ReplicaBeacon(Logger):
    """Presence beacon for ONE serving replica: publishes
    `serve_replica_<rid>.json` on the mirror bus every `interval_s`,
    carrying the replica's live /healthz capacity hint and generation
    labels. Lifecycle: start() beats 'up'; drain() flips the published
    status to 'draining' (the router stops picking it while in-flight
    work finishes); stop() publishes 'gone' best-effort and stops the
    beat thread. A replica that dies without stop() goes silent and is
    TTL-evicted by the router instead."""

    def __init__(self, mirror, rid: str, url: str,
                 health: Optional[Callable[[], Dict[str, Any]]] = None,
                 capacity: Optional[float] = None,
                 interval_s: float = 2.0,
                 clock: Clock = SYSTEM_CLOCK) -> None:
        self.mirror = mirror
        self.rid = rid
        self.url = url
        self.name = beacon_name(rid)
        self._health = health
        self._capacity = capacity
        self.interval_s = max(0.2, float(interval_s))
        self._clock = clock
        self._status = "up"
        self._seq = 0
        self._lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def record(self) -> Dict[str, Any]:
        with self._lock:
            self._seq += 1
            # the dict is built UNDER the lock so status and seq are
            # one consistent observation (seq gates staleness on the
            # router side — a torn pair could roll a drain backwards)
            rec: Dict[str, Any] = {"rid": self.rid, "url": self.url,
                                   "status": self._status,
                                   "seq": self._seq,
                                   "ts": self._clock.time()}
        health = None
        if self._health is not None:
            try:
                health = self._health()
            except Exception as e:  # beacon must outlive a sick server
                self.debug("beacon health probe failed: %s", e)
        if health is not None:
            if health.get("status") == "draining" \
                    and rec["status"] == "up":
                rec["status"] = "draining"
            rec["generation"] = {
                "digest": (health.get("generation") or {}).get("digest"),
                "serving_for_s":
                    (health.get("generation") or {}).get("serving_for_s")}
            rec["inflight"] = health.get("inflight")
            rec["retry_after_s"] = health.get("retry_after_s")
            if self._capacity is None:
                rec["capacity"] = float(health.get("queue_limit") or 1)
        if self._capacity is not None:
            rec["capacity"] = float(self._capacity)
        return rec

    def publish(self) -> bool:
        try:
            return bool(self.mirror.put_meta(self.name, self.record()))
        except Exception as e:      # unreachable mirror: beat again later
            self.debug("beacon publish failed: %s", e)
            return False

    def _beat_loop(self) -> None:
        while not self._stop_evt.wait(self.interval_s):
            self.publish()

    def start(self) -> "ReplicaBeacon":
        self.publish()
        self._thread = threading.Thread(target=self._beat_loop,
                                        daemon=True,
                                        name=f"beacon-{self.rid}")
        self._thread.start()
        return self

    def drain(self) -> None:
        """Announce graceful deregistration: the router stops routing
        here while the replica finishes in-flight rounds."""
        with self._lock:
            self._status = "draining"
        self.publish()

    def silence(self) -> None:
        """Stop beating WITHOUT the 'gone' goodbye — the crash
        simulation hook (chaos/loadtest drivers): the beacon file stays
        on the mirror with a frozen seq, and the router must degrade
        via circuit + TTL eviction, never via a polite deregistration
        the dead process could not have sent."""
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def stop(self) -> None:
        with self._lock:
            self._status = "gone"
        self._stop_evt.set()
        self.publish()              # best-effort goodbye
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


class _Shed(RuntimeError):
    """Replica answered 503 + Retry-After (backpressure)."""

    def __init__(self, retry_after_s: float, body: bytes) -> None:
        super().__init__("replica shed")
        self.retry_after_s = retry_after_s
        self.body = body


class _ReplicaError(RuntimeError):
    """Transport-level dispatch failure (retryable elsewhere)."""


class ServingRouter(Logger):
    """Health-routing HTTP front door over a beacon-discovered replica
    fleet. Endpoints:

    - ``POST /predict``  — token + bounded body; capacity-weighted
      dispatch with bounded retry/backoff, hedging, circuit breaking;
      degrades to 503 + Retry-After when the fleet has no capacity.
    - ``POST /rollback`` — fans out to every live replica; 200 when
      all applied, 409 with per-replica outcomes otherwise.
    - ``GET /healthz``   — router liveness + fleet summary (unauthed,
      like the replica healthz: balancers probe it).
    - ``GET /fleet``     — full per-replica registry view
      (token-guarded: it leaks fleet internals).
    - ``GET /metrics``   — Prometheus exposition (token-guarded).
    """

    def __init__(self, mirror, host: str = "127.0.0.1", port: int = 0,
                 token: Optional[str] = None, poll_s: float = 1.0,
                 max_body: int = 1 << 20, attempts: int = 3,
                 dispatch_timeout_s: float = 10.0,
                 total_timeout_s: float = 15.0,
                 backoff_base: float = 0.05, backoff_cap: float = 0.5,
                 hedge: bool = True, core: Optional[RouterCore] = None,
                 clock: Clock = SYSTEM_CLOCK) -> None:
        self.mirror = mirror
        self.host = host
        self.port = int(port)
        self.token = token
        self.poll_s = max(0.05, float(poll_s))
        self.max_body = int(max_body)
        self.attempts = max(1, int(attempts))
        self.dispatch_timeout_s = float(dispatch_timeout_s)
        self.total_timeout_s = float(total_timeout_s)
        self.backoff_base = float(backoff_base)
        self.backoff_cap = float(backoff_cap)
        self.hedge = bool(hedge)
        self._clock = clock
        self._core = core if core is not None else RouterCore()
        self._lock = threading.Lock()       # guards _core
        self._stop_evt = threading.Event()
        self._poller: Optional[threading.Thread] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._pool = ThreadPoolExecutor(max_workers=64,
                                        thread_name_prefix="router")
        from veles_tpu.telemetry import metrics as _tmetrics
        _reg = _tmetrics.default_registry()
        req = _reg.counter("veles_router_requests_total",
                           "client requests by terminal outcome",
                           labelnames=("outcome",))
        self._m_req = {o: req.labels(outcome=o)
                       for o in ("ok", "shed", "error", "bad")}
        self._f_dispatch = _reg.counter(
            "veles_router_dispatch_total",
            "per-replica dispatch attempts by outcome",
            labelnames=("replica", "outcome"))
        self._m_hedges = _reg.counter(
            "veles_router_hedges_total",
            "hedged dispatches (first replica exceeded its p99)")
        self._m_retries = _reg.counter(
            "veles_router_retries_total",
            "dispatch retries after a replica failure or shed")
        self._m_live = _reg.gauge("veles_router_replicas_live",
                                  "replicas currently routable")
        self._m_capacity = _reg.gauge(
            "veles_router_fleet_capacity",
            "summed capacity hint across routable replicas")
        self._m_latency = _reg.histogram(
            "veles_router_latency_seconds",
            "end-to-end /predict latency through the router",
            buckets=_tmetrics.LATENCY_BUCKETS)

    # -- beacon plane -----------------------------------------------------

    def poll_once(self) -> None:
        """One discovery sweep: list beacons, apply each, evict the
        TTL-silent. A mirror outage yields an empty listing and no
        fresh records — the registry then COASTS on last-known state
        until the generous TTL, which is the mirror-unreachable
        degradation contract (requests keep routing; nothing is
        amputated by a listing hiccup)."""
        try:
            names = self.mirror.meta_names(BEACON_PREFIX)
        except Exception as e:
            self.debug("beacon listing failed: %s", e)
            names = []
        recs = []
        for name in names:
            try:
                rec = self.mirror.get_meta(name)
            except Exception:
                rec = None
            if isinstance(rec, dict):
                recs.append(rec)
        now = self._clock.monotonic()
        with self._lock:
            for rec in recs:
                self._core.observe_beacon(rec, now)
            evicted = self._core.evict_silent(now)
            self._m_live.set(float(self._core.routable(now)))
            self._m_capacity.set(self._core.fleet_capacity())
        for rid in evicted:
            self.warning("replica %s evicted: beacon silent > %.0fs",
                         rid, self._core.beacon_ttl_s)

    def _poll_loop(self) -> None:
        while not self._stop_evt.wait(self.poll_s):
            self.poll_once()

    # -- dispatch plane ---------------------------------------------------

    def _dispatch_child(self, rid: str, outcome: str):
        # Family.labels() caches children under the family's own lock —
        # no router-side cache needed (this is not a unit hot path)
        return self._f_dispatch.labels(replica=rid, outcome=outcome)

    def _post_replica(self, url: str, path: str, body: bytes,
                      timeout: float) -> Tuple[int, Dict[str, str],
                                               bytes]:
        """Raw POST to one replica; raises OSError-family on transport
        failure. Returns (status, lowered-headers, body)."""
        import http.client
        from urllib.parse import urlsplit
        parts = urlsplit(url)
        conn = http.client.HTTPConnection(parts.hostname,
                                          parts.port or 80,
                                          timeout=max(0.05, timeout))
        headers = {"Content-Type": "application/json",
                   "Content-Length": str(len(body))}
        if self.token:
            headers["X-Veles-Token"] = self.token
        try:
            conn.request("POST", path, body, headers)
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, {k.lower(): v for k, v in
                                 resp.getheaders()}, data
        finally:
            conn.close()

    def _dispatch_one(self, rid: str, url: str, body: bytes,
                      timeout: float) -> Tuple[int, bytes]:
        """One /predict dispatch to one replica, with the outcome fed
        back into the core. Returns (status, body) for responses the
        client should see verbatim (200 and 4xx); raises `_Shed` on
        replica backpressure and `_ReplicaError` on transport/5xx."""
        t0 = self._clock.monotonic()
        try:
            status, headers, data = self._post_replica(
                url, "/predict", body, timeout)
        except Exception as e:
            with self._lock:
                self._core.note_fail(rid, self._clock.monotonic())
            self._dispatch_child(rid, "fail").inc()
            raise _ReplicaError(f"{rid}: {e}") from e
        latency = self._clock.monotonic() - t0
        if status == 200:
            with self._lock:
                self._core.note_ok(rid, latency)
            self._dispatch_child(rid, "ok").inc()
            return status, data
        if status == 503:
            ra = headers.get("retry-after")
            try:
                ra_s = max(0.05, float(ra)) if ra is not None \
                    else DEFAULT_RETRY_AFTER_S
            except ValueError:
                ra_s = DEFAULT_RETRY_AFTER_S
            with self._lock:
                self._core.note_shed(rid, ra_s,
                                     self._clock.monotonic())
            self._dispatch_child(rid, "shed").inc()
            raise _Shed(ra_s, data)
        if 400 <= status < 500:
            # the CLIENT's fault — don't punish the replica, don't
            # retry elsewhere (every replica would say the same)
            with self._lock:
                self._core.note_ok(rid, latency)
            self._dispatch_child(rid, "client_error").inc()
            return status, data
        with self._lock:
            self._core.note_fail(rid, self._clock.monotonic())
        self._dispatch_child(rid, "fail").inc()
        raise _ReplicaError(f"{rid}: replica answered {status}")

    def _dispatch_hedged(self, rid: str, url: str, body: bytes,
                         deadline: float) -> Tuple[int, bytes]:
        """Dispatch to `rid`; when it exceeds its measured p99 and a
        second replica is eligible, hedge ONE duplicate there and take
        whichever answers first. The loser's outcome still lands in
        the core via its own `_dispatch_one` bookkeeping."""
        now = self._clock.monotonic()
        budget = max(0.05, min(self.dispatch_timeout_s, deadline - now))
        primary = self._pool.submit(self._dispatch_one, rid, url,
                                    body, budget)
        hedge_after = None
        if self.hedge:
            with self._lock:
                hedge_after = self._core.hedge_after_s(rid)
        if hedge_after is None or hedge_after >= budget:
            return primary.result()
        done, _ = wait([primary], timeout=hedge_after)
        if done:
            return primary.result()
        with self._lock:
            hedge_rid = self._core.pick(self._clock.monotonic(),
                                        exclude=(rid,))
            hedge_url = (self._core.replicas[hedge_rid].url
                         if hedge_rid is not None else None)
            if hedge_rid is not None:
                self._core.note_dispatch(hedge_rid)
        if hedge_rid is None:
            return primary.result()
        self._m_hedges.inc()
        self._dispatch_child(hedge_rid, "hedge").inc()
        second = self._pool.submit(self._dispatch_one, hedge_rid,
                                   hedge_url, body, budget)
        pending = {primary, second}
        last_exc: Optional[BaseException] = None
        while pending:
            remaining = deadline - self._clock.monotonic()
            done, pending = wait(pending, timeout=max(0.05, remaining),
                                 return_when=FIRST_COMPLETED)
            if not done:        # total budget exhausted
                break
            for fut in done:
                try:
                    return fut.result()
                except BaseException as e:  # noqa: BLE001 — loser may
                    last_exc = e            # still win below
        if last_exc is not None:
            raise last_exc
        raise _ReplicaError(f"{rid}: dispatch exceeded total budget")

    def handle_predict(self, body: bytes
                       ) -> Tuple[int, Dict[str, Any],
                                  Optional[Dict[str, str]]]:
        """Route one client /predict. Returns (status, payload,
        extra-headers). Bounded: at most `attempts` replica dispatches
        inside `total_timeout_s`, jittered backoff between transport
        failures; every no-capacity exit is a shed with Retry-After."""
        t0 = self._clock.monotonic()
        deadline = t0 + self.total_timeout_s
        shed_hint: Optional[float] = None
        last_err = "no replica available"
        failed: Tuple[str, ...] = ()
        for attempt in range(self.attempts):
            now = self._clock.monotonic()
            if now >= deadline:
                break
            with self._lock:
                rid = self._core.pick(now, exclude=failed)
                url = (self._core.replicas[rid].url
                       if rid is not None else None)
                if rid is not None:
                    self._core.note_dispatch(rid)
            if rid is None:
                break
            if attempt:
                self._m_retries.inc()
            try:
                status, data = self._dispatch_hedged(rid, url, body,
                                                     deadline)
            except _Shed as e:
                shed_hint = e.retry_after_s if shed_hint is None \
                    else min(shed_hint, e.retry_after_s)
                continue        # replica backpressure: try another NOW
            except _ReplicaError as e:
                last_err = str(e)
                failed = failed + (rid,)
                delay = backoff_delay(attempt, base=self.backoff_base,
                                      cap=self.backoff_cap)
                if self._clock.monotonic() + delay < deadline:
                    self._clock.sleep(delay)
                continue
            try:
                payload = json.loads(data) if data else {}
            except ValueError:
                payload = {"raw": data.decode("utf-8", "replace")[:300]}
            if status == 200:
                self._m_req["ok"].inc()
                self._m_latency.observe(self._clock.monotonic() - t0)
                return 200, payload, None
            self._m_req["bad"].inc()
            return status, payload, None
        with self._lock:
            fleet_hint = self._core.min_retry_after(
                self._clock.monotonic())
        ra = shed_hint if shed_hint is not None else fleet_hint
        if shed_hint is None and failed:
            # transport failures, not backpressure: still a bounded
            # shed (the client retries; the fleet may heal meanwhile)
            self._m_req["error"].inc()
            return 503, {"error": f"fleet dispatch failed: {last_err}"
                                  [:300],
                         "retry_after_s": round(ra, 3)}, \
                {"Retry-After": str(max(1, int(math.ceil(ra))))}
        self._m_req["shed"].inc()
        return 503, {"error": "fleet at capacity",
                     "retry_after_s": round(ra, 3)}, \
            {"Retry-After": str(max(1, int(math.ceil(ra))))}

    # -- admin plane ------------------------------------------------------

    def rollback_fleet(self) -> Tuple[int, Dict[str, Any]]:
        """Fan POST /rollback out to every live replica (up AND
        draining — a draining replica still serves its in-flight
        generation and must roll with the fleet). 200 when every
        replica applied; 409 with per-replica outcomes otherwise."""
        with self._lock:
            targets = [(rid, self._core.replicas[rid].url)
                       for rid in self._core.live()]
        outcomes: Dict[str, Any] = {}
        ok = True
        for rid, url in targets:
            try:
                status, _, data = self._post_replica(
                    url, "/rollback", b"", self.dispatch_timeout_s)
                try:
                    payload = json.loads(data) if data else {}
                except ValueError:
                    payload = {}
                if status == 200:
                    outcomes[rid] = {
                        "applied": True,
                        "generation":
                            (payload.get("generation") or {}).get(
                                "digest")}
                else:
                    ok = False
                    outcomes[rid] = {"applied": False,
                                     "error": payload.get(
                                         "error", f"status {status}"),
                                     "reason": payload.get("reason")}
            except Exception as e:
                ok = False
                outcomes[rid] = {"applied": False,
                                 "error": str(e)[:300]}
        if not targets:
            ok = False
        return (200 if ok else 409), {"fleet": True,
                                      "replicas": outcomes}

    def health(self) -> Dict[str, Any]:
        now = self._clock.monotonic()
        with self._lock:
            snap = self._core.snapshot(now)
        return {"status": "ok", "role": "router",
                "routable": snap["routable"],
                "replicas": len(snap["replicas"]),
                "fleet_capacity": snap["fleet_capacity"]}

    def fleet(self) -> Dict[str, Any]:
        now = self._clock.monotonic()
        with self._lock:
            return self._core.snapshot(now)

    # -- http lifecycle ---------------------------------------------------

    def start(self) -> "ServingRouter":
        router = self
        token = self.token
        from veles_tpu.http_util import check_shared_token

        class Handler(BaseHTTPRequestHandler):
            # same keep-alive discipline as the replica handler:
            # HTTP/1.1, Content-Length on every response, reject paths
            # close the connection because the body is still unread
            protocol_version = "HTTP/1.1"

            def _send(self, code: int, payload: Dict[str, Any],
                      headers: Optional[Dict[str, str]] = None) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self) -> None:  # noqa: N802
                if self.path.startswith("/healthz"):
                    self._send(200, router.health())
                elif self.path.startswith("/fleet"):
                    if not check_shared_token(self, token):
                        return
                    self._send(200, router.fleet())
                elif self.path.startswith("/metrics"):
                    if not check_shared_token(self, token):
                        return
                    from veles_tpu.telemetry import metrics as tmetrics
                    body = tmetrics.default_registry() \
                        .exposition().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     tmetrics.CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._send(404, {"error": "unknown endpoint"})

            def do_POST(self) -> None:  # noqa: N802
                negotiated = self.close_connection
                self.close_connection = True
                # the endpoint contract every control plane wires:
                # shared token first, bound the body BEFORE reading it
                if not check_shared_token(self, token):
                    return
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                except ValueError:
                    self._send(400, {"error": "bad Content-Length"})
                    return
                if not 0 <= n <= router.max_body:
                    self._send(413 if n > router.max_body else 400,
                               {"error":
                                f"body must be 0..{router.max_body}"
                                " bytes"})
                    return
                self.close_connection = negotiated
                body = self.rfile.read(n)
                if self.path.startswith("/rollback"):
                    code, payload = router.rollback_fleet()
                    self._send(code, payload)
                    return
                if not self.path.startswith("/predict"):
                    self._send(404, {"error": "unknown endpoint"})
                    return
                code, payload, headers = router.handle_predict(body)
                self._send(code, payload, headers)

            def log_message(self, *args: Any) -> None:
                pass

        self.poll_once()            # warm registry before first request
        self._stop_evt.clear()
        self._poller = threading.Thread(target=self._poll_loop,
                                        daemon=True, name="router-poll")
        self._poller.start()
        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          Handler)
        self.port = self._httpd.server_address[1]
        # poll_interval bounds how long shutdown() blocks waiting for
        # the accept loop to notice the flag
        self._thread = threading.Thread(
            target=lambda: self._httpd.serve_forever(poll_interval=0.05),
            daemon=True, name="router-http")
        self._thread.start()
        self.info("router on http://%s:%d (POST /predict|/rollback, "
                  "GET /healthz|/fleet|/metrics)", self.host, self.port)
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        if self._poller is not None:
            self._poller.join(timeout=5)
            self._poller = None
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        self._thread = None
        self._pool.shutdown(wait=False)
