"""Concrete plotting units.

Parity: reference `veles/plotting_units.py` + `veles/znicz/
nn_plotting_units.py` (SURVEY.md §2.5) — `AccumulatingPlotter` (error
curves over epochs), `MatrixPlotter` (confusion matrix), `Weights2D`
(first-layer filter tiles), `KohonenHits` (SOM activation histogram).
Each reads its source unit through data links, exactly like the
reference's wiring.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from veles_tpu.plotter import Plotter


class AccumulatingPlotter(Plotter):
    """Appends a scalar each firing and redraws the curve. Link `input`
    to e.g. the decision's epoch metric; fire it once per epoch."""

    def __init__(self, workflow=None, plot_name: str = "metric",
                 label: str = "train", **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.plot_name = plot_name
        self.label = label
        self.values: List[float] = []
        self.input = 0.0  # usually a data link

    def make_spec(self) -> Optional[Dict[str, Any]]:
        v = self.input
        if v is None:
            return None
        self.values.append(float(v))
        return {"name": self.plot_name, "kind": "lines",
                "title": self.plot_name,
                "series": {self.label: list(self.values)},
                "ylabel": self.plot_name}


class MatrixPlotter(Plotter):
    """Renders a matrix heatmap (confusion matrix from EvaluatorSoftmax)."""

    def __init__(self, workflow=None, plot_name: str = "confusion",
                 **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.plot_name = plot_name
        self.input = None  # link to evaluator.confusion_matrix (Array)

    def make_spec(self) -> Optional[Dict[str, Any]]:
        if self.input is None or not self.input:
            return None
        return {"name": self.plot_name, "kind": "matrix",
                "title": self.plot_name,
                "data": np.asarray(self.input.mem).tolist()}


class Weights2D(Plotter):
    """First-layer filter visualization: tiles each kernel as an image.
    Link `input` to a Conv/All2All unit's weights Array."""

    def __init__(self, workflow=None, plot_name: str = "weights",
                 limit: int = 64, **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.plot_name = plot_name
        self.limit = limit
        self.input = None

    def make_spec(self) -> Optional[Dict[str, Any]]:
        if self.input is None or not self.input:
            return None
        w = np.asarray(self.input.mem)
        if w.ndim == 4:  # (ky, kx, C, K) conv kernels -> K tiles
            tiles = [w[:, :, :, k].mean(axis=2)
                     for k in range(min(w.shape[3], self.limit))]
        else:  # (fan_in, units) FC weights: square-ish reshape per unit
            side = int(np.sqrt(w.shape[0]))
            tiles = [w[:side * side, k].reshape(side, side)
                     for k in range(min(w.shape[1], self.limit))]
        return {"name": self.plot_name, "kind": "images",
                "title": self.plot_name,
                "data": [t.tolist() for t in tiles]}


class KohonenHits(Plotter):
    """SOM winner-count map (reference znicz KohonenHits). Link `input` to
    KohonenForward.hits and set `shape` to the SOM grid."""

    def __init__(self, workflow=None, plot_name: str = "kohonen_hits",
                 shape=(8, 8), **kwargs: Any) -> None:
        super().__init__(workflow, **kwargs)
        self.plot_name = plot_name
        self.shape = tuple(shape)
        self.input = None

    def make_spec(self) -> Optional[Dict[str, Any]]:
        if self.input is None or not self.input:
            return None
        hits = np.asarray(self.input.mem).reshape(self.shape)
        return {"name": self.plot_name, "kind": "matrix",
                "title": self.plot_name, "data": hits.tolist()}
