"""Blue/green weight-generation ledger for the serving tier.

The hot-swap state machine (ISSUE 16) used to live as five loose
attributes on `InferenceServer`; this extracts it into ONE import-light
object so (a) every transition — boot, commit, rollback — is a single
method call whose atomicity is a checkable property rather than a code
comment, and (b) the protocol model checker (`analysis/modelcheck.py`)
can drive the REAL generation/rollback/pinning logic without jax or a
device in sight.

The ledger pairs the generation LABEL with the live params handle (an
opaque token: device arrays in production, anything hashable in the
checker), so "swap commits are atomic between ring rounds" reduces to:
any `(params, label)` pair read together matches a pair some single
`commit`/`rollback`/`boot` call published together.

NOT thread-safe by itself: the owner provides the mutual exclusion
(`InferenceServer` calls every mutator under its `_cv`; the model
checker is single-threaded by construction).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Set, Tuple

from veles_tpu.resilience.clock import SYSTEM_CLOCK, Clock


class GenerationLedger:
    """Blue/green generations: the LIVE (label, params) pair, one
    PREVIOUS pair kept resident as the rollback target, the swap
    counter, and the rolled-back digest pins the WeightWatcher honors."""

    def __init__(self, clock: Optional[Clock] = None) -> None:
        self._clock = clock or SYSTEM_CLOCK
        #: the live generation label: {"digest", "since", "source"}
        self.generation: Dict[str, Any] = {
            "digest": "boot", "since": self._clock.time(),
            "source": "boot"}
        self.prev_gen: Optional[Dict[str, Any]] = None
        #: the live params handle — read lock-free (one attribute load)
        #: by the dispatch loop once per ring round
        self.params: Any = None
        self.prev_params: Any = None
        self.n_swaps = 0
        #: digests explicitly rolled back FROM: the WeightWatcher skips
        #: these, so a rollback pins serving until a NEW digest is
        #: pushed (without this the watcher would re-apply the bad
        #: generation one poll after the operator rolled it back)
        self.rolled_back: Set[str] = set()

    def boot(self, digest: str, params: Any,
             source: str = "boot") -> Dict[str, Any]:
        """Publish the startup generation (no previous: rollback from
        boot is `no_previous` by definition)."""
        self.params = params
        self.generation = {"digest": digest,
                           "since": self._clock.time(),
                           "source": source}
        return dict(self.generation)

    def commit(self, digest: str, source: str,
               params: Any) -> Dict[str, Any]:
        """Commit a validated candidate as the live generation — the
        outgoing pair becomes the rollback target. ONE call publishes
        label and params together; callers must not split it."""
        self.prev_params = self.params
        self.prev_gen = dict(self.generation)
        self.params = params
        self.generation = {"digest": digest,
                           "since": self._clock.time(),
                           "source": source}
        self.n_swaps += 1
        return dict(self.generation)

    def rollback(self) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """Swap live and previous pairs and PIN the outgoing digest so
        the watcher never re-applies it. Returns (restored label,
        outgoing label); raises LookupError when nothing is resident."""
        if self.prev_params is None:
            raise LookupError("no previous generation is resident")
        self.params, self.prev_params = self.prev_params, self.params
        outgoing = dict(self.generation)
        restored = dict(self.prev_gen or {})
        self.generation = {"digest": restored.get("digest", "boot"),
                           "since": self._clock.time(),
                           "source": "rollback"}
        self.prev_gen = outgoing
        self.rolled_back.add(str(outgoing["digest"]))
        self.n_swaps += 1
        return dict(self.generation), outgoing

    def snapshot(self) -> Dict[str, Any]:
        """A copy of the live label (never the internal dict)."""
        return dict(self.generation)
