"""Concurrency analysis (pass 4): shared-state races and lock order.

VELES's workflow engine is thread-heavy by heritage — DeviceFeed rides a
PrefetchingLoader thread pool, Supervisor/ClusterMember run heartbeat
loops, task_queue leases work to threaded workers, the telemetry tracer
appends from every thread, and five stdlib HTTP planes serve on
`ThreadingHTTPServer` daemon threads. Every review pass since PR 4 has
hand-caught the same concurrency bug classes; this pass mechanizes them
as a whole-program AST analysis (no execution, no jax — importable by
the velint CLI):

- `shared-write-no-lock` (error): build a THREAD-ROOT graph per class —
  `Thread(target=self.m)` / `threading.Timer(..., self.m)` targets,
  `executor.submit(self.m)` callees, nested `BaseHTTPRequestHandler`
  `do_*` methods (mapped to the outer class through the `outer = self`
  closure idiom), plus the implicit "main" root (public methods the
  owning thread calls) — and compute per-root attribute read/write
  sets with lock-context propagation. A mutable attribute written from
  one root and read/written from another (or written from a
  self-concurrent root: handler/pool entries run on many threads at
  once) with an EMPTY common lock guard is flagged.
- `lock-order-cycle` (error): a global lock-acquisition-order graph —
  an edge A -> B whenever B is acquired while A is held (nested `with`
  blocks, propagated through intra-class helper calls) — with Tarjan
  SCC detection. Any cycle (including a self-loop: re-acquiring a
  non-reentrant `Lock` you already hold) is a potential deadlock.
- `wait-holding-lock` (error): `x.wait(...)` on a condition/event while
  holding a DIFFERENT lock — the waiter blocks every other thread that
  needs that lock, including the one that would have signalled.

Guard-inference model (documented in docs/ANALYSIS.md, tested in
tests/test_concurrency_analysis.py):

- A lock is an attribute assigned `threading.Lock()/RLock()/Condition()/
  Semaphore()` anywhere in the class, or whose name looks lock-ish
  (`lock`, `mutex`, `cond`, `cv`, `sem`). `with self.X:` (including
  through a closure alias `lk = self._lock`) puts X in the held set;
  helper methods called under the `with` inherit it — so a helper that
  only ever runs under one lock is correctly treated as guarded.
- Setup happens-before: accesses in `__init__`/`__setstate__`/
  `__getstate__`/`initialize`/`load_data` (and in private methods
  called ONLY from those), plus accesses lexically BEFORE the first
  thread-creation/start in a thread-creating method, precede
  concurrency and are exempt.
- Flag publication: attributes whose every post-setup write is a bare
  `True`/`False`/`None` constant (stop flags, tombstones) are exempt —
  a single GIL-atomic reference store.
- Thread-safe types: attributes holding `Lock`/`Event`/`Condition`/
  `Semaphore`/`Queue`/`SimpleQueue`/`Barrier` objects are exempt (their
  methods carry their own synchronization).

Known blind spots (by design — static, per-class):
- cross-OBJECT lock nesting (a method holding its lock calling into
  another object that locks) is not tracked; `Condition.wait()`
  releasing its lock inside a `with` is not modeled;
- attributes reached via `getattr(self, "name")`, dict aliases mutated
  through a second alias hop, and monkey-patched methods are invisible;
- only MODULE-TOP-LEVEL classes are analyzed (plus nested
  `BaseHTTPRequestHandler` handlers, which map to their outer class):
  a thread-owning class defined inside a factory function or another
  class body is skipped;
- happens-before edges other than the setup heuristics above (e.g. a
  write after `join()`) are not proven — suppress with justification
  (`# velint: disable=shared-write-no-lock`) when the ordering is real.

Findings are `lint.LintFinding` records so they ride `tools/velint.py
--ci` (same ratchet baseline, same `# velint: disable=` suppressions);
`lock_order_edges_source`/`lock_order_edges_paths` expose the static
order graph for the runtime witness fixture in tier-1.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from veles_tpu.analysis.lint import (LintFinding, _attr_chain,
                                     _suppressed, read_py_files)

RULES: Dict[str, str] = {
    "shared-write-no-lock": "attribute written from one thread root and "
                            "accessed from another with no common lock "
                            "guard",
    "lock-order-cycle": "locks acquired in inconsistent nested order "
                        "(potential deadlock; Tarjan cycle over the "
                        "acquisition-order graph)",
    "wait-holding-lock": ".wait() on a condition/event while holding a "
                         "different lock (blocks the signaller)",
}

_LOCK_NAME_RE = re.compile(r"lock|mutex|mtx|(^|_)cond|(^|_)cv($|_|\d)|sem",
                           re.IGNORECASE)
_LOCK_CTORS = ("Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore")
#: ctors whose instances synchronize internally — attrs holding one are
#: exempt from the race rule
_SAFE_CTORS = _LOCK_CTORS + ("Event", "Queue", "SimpleQueue", "LifoQueue",
                             "PriorityQueue", "Barrier", "local")
#: method names that MUTATE their receiver (container write)
_MUTATORS = frozenset((
    "append", "appendleft", "extend", "extendleft", "insert", "remove",
    "discard", "pop", "popleft", "popitem", "clear", "update", "add",
    "setdefault", "sort", "reverse", "rotate"))
#: methods that run before any thread exists (the framework contract:
#: construct/unpickle/initialize happen on the owning thread, before
#: produce pools / servers are started)
_SETUP_METHODS = frozenset(("__init__", "__new__", "__setstate__",
                            "__getstate__", "__del__", "initialize",
                            "load_data"))
_THREAD_CTOR_LEAVES = ("Thread", "Timer")
_HANDLER_BASE = "BaseHTTPRequestHandler"

#: env marker: a local name aliasing the enclosing instance (`outer =
#: self`, `srv = self`)
_SELF = ("self",)


# == project model ============================================================

@dataclass
class ClassModel:
    name: str
    path: str
    node: ast.ClassDef
    bases: List[str]
    methods: Dict[str, ast.FunctionDef] = field(default_factory=dict)
    #: nested BaseHTTPRequestHandler classes declared inside a method:
    #: (handler ClassDef, alias env of the enclosing method)
    handlers: List[Tuple[ast.ClassDef, Dict[str, object]]] = \
        field(default_factory=list)


@dataclass
class Project:
    #: top-level classes: name -> [ClassModel] (collisions kept)
    by_name: Dict[str, List[ClassModel]] = field(default_factory=dict)
    classes: List[ClassModel] = field(default_factory=list)
    #: path -> source lines (suppression checks)
    lines: Dict[str, List[str]] = field(default_factory=dict)


def _base_names(node: ast.ClassDef) -> List[str]:
    out = []
    for b in node.bases:
        chain = _attr_chain(b)
        if chain:
            out.append(chain.rsplit(".", 1)[-1])
    return out


def _method_env(fn: ast.AST) -> Dict[str, object]:
    """Closure aliases a nested handler class captures from its
    enclosing method: `outer = self` -> _SELF, `workers = self.workers`
    -> ("attr", "workers")."""
    env: Dict[str, object] = {}
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        t = node.targets[0]
        if not isinstance(t, ast.Name):
            continue
        v = node.value
        if isinstance(v, ast.Name) and v.id == "self":
            env[t.id] = _SELF
        elif isinstance(v, ast.Attribute) \
                and isinstance(v.value, ast.Name) and v.value.id == "self":
            env[t.id] = ("attr", v.attr)
    return env


def collect_project(files: Dict[str, str]) -> Project:
    """Parse `files` (path -> source) into the class table the passes
    share. Files that fail to parse are skipped (velint reports the
    syntax error separately)."""
    proj = Project()
    for path, source in sorted(files.items()):
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError:
            continue
        proj.lines[path] = source.splitlines()
        for node in tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            cm = ClassModel(node.name, path, node, _base_names(node))
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    cm.methods[item.name] = item
                    for sub in ast.walk(item):
                        if isinstance(sub, ast.ClassDef) \
                                and _HANDLER_BASE in _base_names(sub):
                            cm.handlers.append((sub, _method_env(item)))
            proj.by_name.setdefault(cm.name, []).append(cm)
            proj.classes.append(cm)
    return proj


def method_chains(cm: ClassModel, proj: Project,
                  _seen: Optional[Set[int]] = None
                  ) -> Dict[str, List[Tuple[ast.FunctionDef, str]]]:
    """Flattened method table (name -> [(funcdef, defining path), ...]
    base-first): bases left-to-right (same-module preferred on name
    collisions), subclass definitions appended last — a linear MRO
    approximation good enough for this codebase's hierarchies. The last
    entry is the effective method; the one before it is what that
    method's `super().m()` reaches."""
    if _seen is None:
        _seen = set()
    if id(cm) in _seen:
        return {}
    _seen.add(id(cm))
    out: Dict[str, List[Tuple[ast.FunctionDef, str]]] = {}
    for bname in cm.bases:
        cands = proj.by_name.get(bname) or []
        if not cands:
            continue
        base = next((c for c in cands if c.path == cm.path), cands[0])
        for name, chain in method_chains(base, proj, _seen).items():
            out.setdefault(name, []).extend(
                e for e in chain if e not in out.get(name, []))
    for name, fn in cm.methods.items():
        out.setdefault(name, []).append((fn, cm.path))
    return out


def flat_methods(cm: ClassModel, proj: Project
                 ) -> Dict[str, Tuple[ast.FunctionDef, str]]:
    """The effective (post-override) method table."""
    return {name: chain[-1]
            for name, chain in method_chains(cm, proj).items()}


# == per-class analysis =======================================================

@dataclass
class _Access:
    attr: str
    kind: str                 # "read" | "write"
    root: str
    locks: frozenset
    path: str
    line: int
    constant: bool = False    # write of a bare True/False/None
    setup: bool = False


@dataclass
class _Root:
    rid: str
    fn: ast.AST
    path: str
    env: Dict[str, object]
    self_name: Optional[str]   # None inside handler methods
    handler: Optional[ast.ClassDef] = None
    concurrent: bool = False   # runs on many threads at once


def _first_arg(fn) -> Optional[str]:
    for dec in getattr(fn, "decorator_list", ()):
        if _attr_chain(dec).rsplit(".", 1)[-1] == "staticmethod":
            return None
    args = fn.args.args
    return args[0].arg if args else None


def _is_const_flag(value: ast.AST) -> bool:
    return isinstance(value, ast.Constant) \
        and value.value in (True, False, None)


class _ClassAnalysis:
    """One flattened class: roots, accesses, lock edges, waits."""

    def __init__(self, cm: ClassModel, proj: Project) -> None:
        self.cm = cm
        self.proj = proj
        self.method_chain = method_chains(cm, proj)
        self.methods = {n: c[-1] for n, c in self.method_chain.items()}
        self.handler_methods: Dict[int, Dict[str, ast.FunctionDef]] = {
            id(h): {m.name: m for m in h.body
                    if isinstance(m, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))}
            for h, _env in cm.handlers}
        self.lock_attrs: Dict[str, str] = {}    # attr -> ctor leaf
        self.safe_attrs: Set[str] = set()
        self._infer_attr_types()
        self.spawn_line: Dict[int, int] = {}    # id(fn) -> first spawn
        self.roots: List[_Root] = self._find_roots()
        self.accesses: Dict[str, List[_Access]] = {}
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        self.waits: List[Tuple[str, frozenset, str, int]] = []
        self.root_concurrent: Dict[str, bool] = {
            r.rid: r.concurrent for r in self.roots}

    # -- attribute typing -----------------------------------------------------

    def _infer_attr_types(self) -> None:
        for _name, (fn, _path) in self.methods.items():
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign) \
                        or len(node.targets) != 1:
                    continue
                t = node.targets[0]
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == _first_arg(fn)):
                    continue
                if isinstance(node.value, ast.Call):
                    leaf = _attr_chain(node.value.func).rsplit(".", 1)[-1]
                    if leaf in _LOCK_CTORS:
                        self.lock_attrs[t.attr] = leaf
                        self.safe_attrs.add(t.attr)
                    elif leaf in _SAFE_CTORS:
                        self.safe_attrs.add(t.attr)

    # -- root discovery -------------------------------------------------------

    def _find_roots(self) -> List[_Root]:
        roots: List[_Root] = []
        entry_methods: Set[str] = set()
        for name, (fn, path) in self.methods.items():
            locals_ = {n.name: n for n in ast.walk(fn)
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))
                       and n is not fn}
            self_name = _first_arg(fn)
            env = _method_env(fn)
            ctor_lines: List[int] = []
            start_lines: List[int] = []
            for node, in_loop in _walk_with_loops(fn):
                if not isinstance(node, ast.Call):
                    continue
                leaf = _attr_chain(node.func).rsplit(".", 1)[-1]
                if leaf in ("start", "start_thread"):
                    start_lines.append(node.lineno)
                target = None
                if leaf in _THREAD_CTOR_LEAVES:
                    for kw in node.keywords:
                        if kw.arg in ("target", "function"):
                            target = kw.value
                    if target is None and leaf == "Timer" \
                            and len(node.args) >= 2:
                        target = node.args[1]
                    ctor_lines.append(node.lineno)
                elif leaf == "submit" and node.args:
                    target = node.args[0]
                    ctor_lines.append(node.lineno)
                    start_lines.append(node.lineno)
                else:
                    continue
                concurrent = in_loop or leaf == "submit"
                if isinstance(target, ast.Attribute) \
                        and isinstance(target.value, ast.Name) \
                        and (target.value.id == self_name
                             or env.get(target.value.id) is _SELF) \
                        and target.attr in self.methods:
                    mfn, mpath = self.methods[target.attr]
                    entry_methods.add(target.attr)
                    roots.append(_Root(
                        f"thread:{target.attr}", mfn, mpath,
                        _method_env(mfn), _first_arg(mfn),
                        concurrent=concurrent))
                elif isinstance(target, ast.Name) \
                        and target.id in locals_:
                    roots.append(_Root(
                        f"thread:{target.id}", locals_[target.id], path,
                        env, self_name, concurrent=concurrent))
            if ctor_lines:
                # concurrency begins at the first `.start()`/`submit`,
                # not at the Thread ctor: writes before the spawn are
                # single-threaded publication and exempt
                self.spawn_line[id(fn)] = min(start_lines or ctor_lines)
        for hcls, henv in self.cm.handlers:
            for m in self.handler_methods[id(hcls)].values():
                if m.name.startswith("do_"):
                    roots.append(_Root(
                        f"handler:{hcls.name}.{m.name}", m, self.cm.path,
                        henv, None, handler=hcls, concurrent=True))
        if roots:
            for name, (fn, path) in self.methods.items():
                if name in _SETUP_METHODS or name in entry_methods \
                        or name.startswith("_"):
                    # private helpers are NOT independent entries: they
                    # contribute through their callers' lock context
                    # (a helper that only runs under one lock is thus
                    # correctly treated as guarded); externally-invoked
                    # privates are a documented blind spot
                    continue
                roots.append(_Root("main", fn, path, _method_env(fn),
                                   _first_arg(fn)))
        return roots

    # -- the walker -----------------------------------------------------------

    def run(self, races: bool = True) -> None:
        """Visit every root (races + edges); for classes WITHOUT thread
        roots, still walk every method for the lock-order graph."""
        if self.roots:
            for root in self.roots:
                self._walk_root(root)
        else:
            for name, (fn, path) in self.methods.items():
                root = _Root("main", fn, path, _method_env(fn),
                             _first_arg(fn))
                self._root = root
                self._seen: Set[Tuple[int, frozenset]] = set()
                self._record = False
                self._enter_fn(fn, path, name, frozenset())
            for hcls, henv in self.cm.handlers:
                for m in self.handler_methods[id(hcls)].values():
                    root = _Root(f"handler:{hcls.name}.{m.name}", m,
                                 self.cm.path, henv, None, handler=hcls)
                    self._root = root
                    self._seen = set()
                    self._record = False
                    self._enter_fn(m, self.cm.path, m.name, frozenset())

    def _walk_root(self, root: _Root) -> None:
        self._root = root
        self._seen = set()
        self._record = True
        name = getattr(root.fn, "name", root.rid)
        self._enter_fn(root.fn, root.path, name, frozenset())

    def _enter_fn(self, fn, path: str, mname: str,
                  locks: frozenset) -> None:
        key = (id(fn), locks)
        if key in self._seen:
            return
        self._seen.add(key)
        in_handler = (self._root.handler is not None
                      and fn in self.handler_methods.get(
                          id(self._root.handler), {}).values())
        if fn is self._root.fn:
            # root entry: closures inherit the enclosing method's
            # self/env; handler entries see only the closure aliases
            self_name, env = self._root.self_name, self._root.env
        elif in_handler:
            self_name, env = None, self._root.env
        else:
            self_name, env = _first_arg(fn), _method_env(fn)
        ctx = {
            "fn": fn, "path": path, "mname": mname,
            "self": self_name, "env": env,
            "setup": mname in _SETUP_METHODS,
            "spawn": self.spawn_line.get(id(fn)),
        }
        self._stmts(fn.body, locks, ctx)

    # resolution ---------------------------------------------------------------

    def _chain(self, node, ctx) -> Optional[str]:
        """Attr chain relative to the OUTER instance ('' -> None)."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        if node.id == ctx["self"] and ctx["self"] is not None:
            return ".".join(reversed(parts)) if parts else None
        al = ctx["env"].get(node.id)
        if al is _SELF:
            return ".".join(reversed(parts)) if parts else None
        if isinstance(al, tuple) and al[0] == "attr":
            return ".".join([al[1]] + list(reversed(parts)))
        return None

    def _as_lock(self, expr, ctx) -> Optional[str]:
        chain = self._chain(expr, ctx)
        if not chain:
            return None
        head = chain.split(".", 1)[0]
        if head in self.lock_attrs or _LOCK_NAME_RE.search(chain):
            return chain
        return None

    # recording ----------------------------------------------------------------

    def _rec(self, attr_chain: str, kind: str, node, locks, ctx,
             constant: bool = False) -> None:
        if not self._record:
            return
        attr = attr_chain.split(".", 1)[0]
        if attr in self.safe_attrs:
            return
        setup = ctx["setup"] or (ctx["spawn"] is not None
                                 and node.lineno < ctx["spawn"])
        self.accesses.setdefault(attr, []).append(_Access(
            attr, kind, self._root.rid, locks, ctx["path"],
            node.lineno, constant, setup))

    def _edge(self, held: frozenset, acquired: str, node, ctx) -> None:
        me = f"{self.cm.name}.{acquired}"
        for h in held:
            self.edges.setdefault(
                (f"{self.cm.name}.{h}", me),
                (ctx["path"], node.lineno))

    # statements ---------------------------------------------------------------

    def _stmts(self, body, locks: frozenset, ctx) -> None:
        for s in body:
            self._stmt(s, locks, ctx)

    def _stmt(self, s, locks: frozenset, ctx) -> None:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            return                      # closures/nested: roots or skip
        if isinstance(s, (ast.With, ast.AsyncWith)):
            inner = locks
            for item in s.items:
                guard = self._as_lock(item.context_expr, ctx)
                self._expr(item.context_expr, inner, ctx)
                if guard is not None:
                    self._edge(inner, guard, item.context_expr, ctx)
                    inner = inner | {guard}
            self._stmts(s.body, inner, ctx)
            return
        if isinstance(s, (ast.If, ast.While)):
            self._expr(s.test, locks, ctx)
            self._stmts(s.body, locks, ctx)
            self._stmts(s.orelse, locks, ctx)
            return
        if isinstance(s, (ast.For, ast.AsyncFor)):
            self._target(s.target, locks, ctx)
            self._expr(s.iter, locks, ctx)
            self._stmts(s.body, locks, ctx)
            self._stmts(s.orelse, locks, ctx)
            return
        if isinstance(s, ast.Try):
            self._stmts(s.body, locks, ctx)
            for h in s.handlers:
                self._stmts(h.body, locks, ctx)
            self._stmts(s.orelse, locks, ctx)
            self._stmts(s.finalbody, locks, ctx)
            return
        if isinstance(s, ast.Assign):
            for t in s.targets:
                self._target(t, locks, ctx, value=s.value)
            self._expr(s.value, locks, ctx)
            return
        if isinstance(s, ast.AnnAssign):
            if s.value is not None:
                self._target(s.target, locks, ctx, value=s.value)
                self._expr(s.value, locks, ctx)
            return
        if isinstance(s, ast.AugAssign):
            chain = self._chain(s.target, ctx)
            if chain:
                self._rec(chain, "read", s, locks, ctx)
                self._rec(chain, "write", s, locks, ctx)
            self._expr(s.value, locks, ctx)
            return
        if isinstance(s, ast.Delete):
            for t in s.targets:
                self._target(t, locks, ctx)
            return
        for child in ast.iter_child_nodes(s):
            if isinstance(child, ast.expr):
                self._expr(child, locks, ctx)
            elif isinstance(child, ast.stmt):
                self._stmt(child, locks, ctx)

    def _target(self, t, locks, ctx, value=None) -> None:
        """A store/delete target: attribute -> write; subscript on an
        attribute -> container write."""
        if isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                self._target(el, locks, ctx, value=None)
            return
        if isinstance(t, ast.Subscript):
            chain = self._chain(t.value, ctx)
            if chain:
                self._rec(chain, "write", t, locks, ctx)
            else:
                self._expr(t.value, locks, ctx)
            self._expr(t.slice, locks, ctx)
            return
        if isinstance(t, ast.Name):
            # a store to a local name — even one aliasing an attribute
            # (`tr = self._tr`) — re-binds the LOCAL, not the attribute
            return
        chain = self._chain(t, ctx)
        if chain:
            self._rec(chain, "write", t, locks, ctx,
                      constant=value is not None
                      and _is_const_flag(value))

    def _expr(self, e, locks: frozenset, ctx) -> None:
        if e is None:
            return
        if isinstance(e, ast.Call):
            self._call(e, locks, ctx)
            return
        if isinstance(e, ast.Attribute):
            chain = self._chain(e, ctx)
            if chain:
                self._rec(chain, "read", e, locks, ctx)
                return
            self._expr(e.value, locks, ctx)
            return
        if isinstance(e, ast.Name):
            al = ctx["env"].get(e.id)
            if isinstance(al, tuple) and al[0] == "attr" \
                    and al[1] not in self.methods:
                self._rec(al[1], "read", e, locks, ctx)
            return
        if isinstance(e, (ast.Lambda,)):
            return                      # deferred body: blind spot
        for child in ast.iter_child_nodes(e):
            if isinstance(child, ast.expr):
                self._expr(child, locks, ctx)

    def _call(self, node: ast.Call, locks: frozenset, ctx) -> None:
        fnode = node.func
        leaf = fnode.attr if isinstance(fnode, ast.Attribute) else (
            fnode.id if isinstance(fnode, ast.Name) else "")
        # handler-internal helper: self.m() where self is the HANDLER
        if self._root.handler is not None \
                and isinstance(fnode, ast.Attribute) \
                and isinstance(fnode.value, ast.Name) \
                and fnode.value.id == "self":
            hm = self.handler_methods.get(id(self._root.handler), {})
            if fnode.attr in hm:
                for a in list(node.args) + [k.value for k in
                                            node.keywords]:
                    self._expr(a, locks, ctx)
                self._enter_fn(hm[fnode.attr], ctx["path"],
                               fnode.attr, locks)
                return
        # super().m(...): the definition the final override shadows
        # (linear-MRO approximation — one super hop, which is all this
        # codebase uses)
        if isinstance(fnode, ast.Attribute) \
                and isinstance(fnode.value, ast.Call) \
                and isinstance(fnode.value.func, ast.Name) \
                and fnode.value.func.id == "super":
            mchain = self.method_chain.get(fnode.attr) or []
            if len(mchain) >= 2:
                for a in list(node.args) + [k.value for k in
                                            node.keywords]:
                    self._expr(a, locks, ctx)
                mfn, mpath = mchain[-2]
                self._enter_fn(mfn, mpath, fnode.attr, locks)
                return
        chain = self._chain(fnode, ctx)
        if chain is not None and "." not in chain \
                and chain in self.methods:
            # intra-class call: propagate the held-lock context
            for a in list(node.args) + [k.value for k in node.keywords]:
                self._expr(a, locks, ctx)
            mfn, mpath = self.methods[chain]
            self._enter_fn(mfn, mpath, chain, locks)
            return
        # aliased bound method (`clean = self._clean_beat`)
        if isinstance(fnode, ast.Name):
            al = ctx["env"].get(fnode.id)
            if isinstance(al, tuple) and al[0] == "attr" \
                    and al[1] in self.methods:
                for a in list(node.args) + [k.value for k in
                                            node.keywords]:
                    self._expr(a, locks, ctx)
                mfn, mpath = self.methods[al[1]]
                self._enter_fn(mfn, mpath, al[1], locks)
                return
        if isinstance(fnode, ast.Attribute):
            recv = self._chain(fnode.value, ctx)
            if recv:
                if leaf == "wait":
                    # recorded regardless of thread roots: waiting
                    # under someone else's lock is a hazard for
                    # whichever thread ends up calling this
                    others = frozenset(
                        h for h in locks if h != recv
                        and h.split(".", 1)[0] != recv.split(".", 1)[0])
                    if others:
                        self.waits.append((recv, others, ctx["path"],
                                           node.lineno))
                self._rec(recv, "write" if leaf in _MUTATORS
                          else "read", node, locks, ctx)
            else:
                self._expr(fnode.value, locks, ctx)
        for a in list(node.args) + [k.value for k in node.keywords]:
            self._expr(a, locks, ctx)

    # verdicts -----------------------------------------------------------------

    def race_findings(self) -> List[LintFinding]:
        out: List[LintFinding] = []
        if not self.roots:
            return out
        for attr, recs in sorted(self.accesses.items()):
            accs = [a for a in recs if not a.setup and not (
                a.kind == "write" and a.constant)]
            writes = [a for a in accs if a.kind == "write"]
            if not writes:
                continue
            conflict = None
            for w in writes:
                other = next((a for a in accs
                              if a.root != w.root), None)
                if other is not None:
                    conflict = (w, other)
                    break
                if self.root_concurrent.get(w.root):
                    other = next(
                        (a for a in accs
                         if a is not w and a.root == w.root), None)
                    if other is not None:
                        conflict = (w, other)
                        break
            if conflict is None:
                continue
            common = frozenset.intersection(
                *(a.locks for a in accs)) if accs else frozenset()
            if common:
                continue
            anchor = min(
                (a for a in writes if not a.locks),
                key=lambda a: (a.path, a.line),
                default=min(writes, key=lambda a: (a.path, a.line)))
            w, other = conflict
            locks_seen = sorted({lk for a in accs for lk in a.locks})
            out.append(LintFinding(
                anchor.path, anchor.line, 0, "shared-write-no-lock",
                f"{self.cm.name}.{attr} is written from {w.root} "
                f"({os.path.basename(w.path)}:{w.line}) and "
                f"{other.kind} from {other.root} "
                f"({os.path.basename(other.path)}:{other.line}) with "
                f"no common lock guard"
                + (f" (locks seen: {', '.join(locks_seen)})"
                   if locks_seen else "")
                + " — guard every access with one lock, or prove the "
                  "happens-before and suppress with justification"))
        return out

    def wait_findings(self) -> List[LintFinding]:
        out = []
        seen: Set[Tuple] = set()
        for recv, others, path, line in self.waits:
            if (recv, path, line) in seen:
                continue        # multiple roots visit one site
            seen.add((recv, path, line))
            out.append(LintFinding(
                path, line, 0, "wait-holding-lock",
                f"{self.cm.name}: .wait() on {recv} while holding "
                f"{', '.join(sorted(others))} — the waiter blocks "
                "every thread needing that lock, including the one "
                "that would signal; release it before waiting"))
        return out


def _walk_with_loops(fn) -> Iterable[Tuple[ast.AST, bool]]:
    """(node, inside_a_loop_of_fn) pairs, skipping nested defs for the
    loop flag purpose is irrelevant — used only for root discovery."""
    def go(node, in_loop):
        yield node, in_loop
        enter = in_loop or isinstance(node, (ast.For, ast.While,
                                             ast.AsyncFor))
        for child in ast.iter_child_nodes(node):
            yield from go(child, enter)
    yield from go(fn, False)


# == lock-order graph =========================================================

def _tarjan_cycles(edges: Dict[Tuple[str, str], Tuple[str, int]]
                   ) -> List[List[str]]:
    """SCCs of size > 1, plus self-loop nodes, over the order graph."""
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    counter = [0]
    sccs: List[List[str]] = []

    def strongconnect(v: str) -> None:
        work = [(v, iter(sorted(graph[v])))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(sorted(graph[w]))))
                    advanced = True
                    break
                elif w in on:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1:
                    sccs.append(sorted(scc))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    for (a, b) in edges:
        if a == b:
            sccs.append([a])
    return sccs


def _cycle_findings(edges: Dict[Tuple[str, str], Tuple[str, int]],
                    reentrant: Set[str]) -> List[LintFinding]:
    out: List[LintFinding] = []
    for scc in _tarjan_cycles(edges):
        if len(scc) == 1:
            node = scc[0]
            if node in reentrant:
                continue
            path, line = edges[(node, node)]
            out.append(LintFinding(
                path, line, 0, "lock-order-cycle",
                f"{node} is acquired while already held — a "
                "non-reentrant Lock self-deadlocks on nested "
                "acquisition (use RLock, or restructure so the outer "
                "scope passes control down without re-locking)"))
            continue
        cyc_edges = sorted((k, v) for k, v in edges.items()
                           if k[0] in scc and k[1] in scc)
        (a, b), (path, line) = cyc_edges[0]
        order = " -> ".join(scc + [scc[0]])
        sites = "; ".join(f"{x}->{y} at {os.path.basename(p)}:{ln}"
                          for (x, y), (p, ln) in cyc_edges)
        out.append(LintFinding(
            path, line, 0, "lock-order-cycle",
            f"inconsistent lock acquisition order {order}: two threads "
            f"taking opposite edges deadlock ({sites}) — pick ONE "
            "global order and acquire in it everywhere"))
    return out


# == entry points =============================================================

def _analyze_project(proj: Project) -> List[LintFinding]:
    findings: List[LintFinding] = []
    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
    reentrant: Set[str] = set()
    for cm in proj.classes:
        ana = _ClassAnalysis(cm, proj)
        ana.run()
        for attr, ctor in ana.lock_attrs.items():
            if ctor == "RLock":
                reentrant.add(f"{cm.name}.{attr}")
        for k, v in ana.edges.items():
            edges.setdefault(k, v)
        findings += ana.race_findings()
        findings += ana.wait_findings()
    findings += _cycle_findings(edges, reentrant)
    # dedupe (two subclasses flattening one base anchor identically)
    seen: Set[Tuple] = set()
    unique: List[LintFinding] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule,
                                             f.message)):
        key = (f.path, f.line, f.rule,
               f.message.split(" is ", 1)[-1] if f.rule ==
               "shared-write-no-lock" else f.message)
        if key in seen:
            continue
        seen.add(key)
        unique.append(f)
    out = []
    for f in unique:
        lines = proj.lines.get(f.path)
        if lines is not None and _suppressed(f, lines):
            continue
        out.append(f)
    return out


def analyze_files(files: Dict[str, str]) -> List[LintFinding]:
    """Run the concurrency pass over `files` (path -> source)."""
    return _analyze_project(collect_project(files))


def analyze_source(source: str,
                   path: str = "<module>") -> List[LintFinding]:
    """Single-module convenience (fixtures/tests)."""
    return analyze_files({path: source})


def analyze_paths(paths: Sequence[str],
                  root: Optional[str] = None) -> List[LintFinding]:
    """Whole-program pass over every .py under `paths`; reported paths
    are relative to `root` (baseline stability), like lint_paths."""
    findings = analyze_files(read_py_files(paths))
    if root:
        for f in findings:
            f.path = os.path.relpath(f.path, root)
    return findings


def lock_order_edges_source(source: str, path: str = "<module>"
                            ) -> Set[Tuple[str, str]]:
    """The static acquisition-order edges (ClassName.lock pairs) — the
    runtime witness fixture cross-validates observed acquisition order
    against this graph."""
    proj = collect_project({path: source})
    edges: Set[Tuple[str, str]] = set()
    for cm in proj.classes:
        ana = _ClassAnalysis(cm, proj)
        ana.run()
        edges |= set(ana.edges)
    return edges


def lock_order_edges_paths(paths: Sequence[str]) -> Set[Tuple[str, str]]:
    proj = collect_project(read_py_files(paths))
    edges: Set[Tuple[str, str]] = set()
    for cm in proj.classes:
        ana = _ClassAnalysis(cm, proj)
        ana.run()
        edges |= set(ana.edges)
    return edges
