"""Finding: the shared record every analysis pass emits.

One type for all three passes (graph verifier, jaxpr auditor, velint) so
the CLI (`--verify-workflow`), the bench record, the supervisor exit
report and the tests consume a single shape. Import-light on purpose: the
supervisor embeds findings in its exit report and must not pull jax in.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass
from typing import Dict, Iterable, List

SEV_ERROR = "error"
SEV_WARN = "warn"


@dataclass
class Finding:
    """One analyzer finding.

    - `rule`: stable kebab-case rule id (docs/ANALYSIS.md catalogue);
    - `severity`: "error" (broken build / wrong numerics) or "warn"
      (suspicious but possibly intentional);
    - `unit`: what the finding is about — a unit repr for graph findings,
      an op/primitive for jaxpr findings, `path:line` for lint;
    - `site`: the precise link/trace site, when one exists.
    """

    rule: str
    severity: str
    unit: str
    message: str
    site: str = ""

    def as_dict(self) -> Dict[str, str]:
        return asdict(self)

    def format(self) -> str:
        tag = "E" if self.severity == SEV_ERROR else "W"
        loc = f" [{self.site}]" if self.site else ""
        return f"{tag} {self.rule}: {self.unit}: {self.message}{loc}"


def errors(findings: Iterable[Finding]) -> List[Finding]:
    return [f for f in findings if f.severity == SEV_ERROR]


def summarize(findings: Iterable[Finding]) -> Dict[str, object]:
    """Compact embeddable summary (bench records, supervisor reports)."""
    findings = list(findings)
    n_err = len(errors(findings))
    return {"errors": n_err,
            "warnings": len(findings) - n_err,
            "findings": [f.as_dict() for f in findings]}
