"""Pass 8 — protocol model checker: exhaustive bounded-interleaving
exploration of the election / membership / hot-swap / fleet-routing
planes.

The chaos matrix (tools/chaos.py) kills real processes and checks that
ONE schedule recovers; the concurrency pass (pass 5) reasons statically
about locks. This pass closes the gap between them: it runs the REAL
protocol logic — `ClusterCoordinator`/`ClusterMember` election and
membership from resilience/cluster.py, the quorum pick, and the
`WeightWatcher` + `GenerationLedger` hot-swap/rollback plane — inside a
simulated world (in-memory mirror, virtual clock, synchronous message
scheduler) and explores MANY schedules: every "which agent acts next"
choice and every injected fault (dropped beat, stale route, torn meta
read, lost beacon, crash before/after the coordinator announcement) is
a branch point in a deterministic choice tree walked DFS up to a depth
and schedule budget.

What is real and what is simulated
----------------------------------
Real (imported, unmodified): `handle_beat`/`handle_join`, the dead
sweep, gather mode, `_membership_bump`/`_initiate_restart` and
`quorum_snapshot`, member `step()` (fencing, failover, isolation
fail-stop), `_seek_coordinator`/`_try_adopt`/`_promote`,
`_publish_beacon`/`_live_hosts`, `WeightWatcher.poll_once` (scan,
pinning, deterministic-refusal memory), `GenerationLedger`
(commit/rollback/pinning) and the serving-fleet `RouterCore`
(beacon registry, capacity pick, circuit breaker, drain discipline —
serving_router.py is clock-clean and takes `now` parameters exactly so
this pass can drive it). Simulated (via the seams those classes
expose — `_mirror`, `_bind_http`, `_bind_coordinator`, `_post`,
`_spawn`, `_children_status`, `_local_snapshots`, `_resolve_snapshot`,
`_obtain`, the injected `Clock`): processes, files, sockets and time.

The invariant ledger (checked after every action)
-------------------------------------------------
1. mc-term-fence           a member's observed term never decreases,
                           and no member acts on a directive from a
                           term below the one it had already seen.
2. mc-single-coordinator   at most one LIVE bound coordinator per term.
3. mc-generation-rollback  member generations never decrease, and the
                           epoch of successive restart picks never
                           regresses (the PR-10 no-rollback contract).
4. mc-single-writer        at most one host spawns its children as the
                           snapshot WRITER per generation.
5. mc-verified-pick        a quorum pick names a snapshot with at least
                           one sidecar-verified copy somewhere.
6. mc-atomic-commit        every (params, label) pair a ring round
                           reads was published by ONE ledger call.
7. mc-rollback-pin         a digest that was rolled back FROM is never
                           watcher-re-applied.
8. mc-floor-failstop       a fleet below the floor fail-stops at
                           quiescence instead of wedging or running.
9. mc-no-route-to-drained  once the router has OBSERVED a replica's
                           draining/deregistration beacon, no routed
                           request lands on that replica (ISSUE 19's
                           drain protocol; a drain the router never
                           saw — lost beacon, torn read — is out of
                           scope by construction).

Determinism and reduction
-------------------------
A schedule is the sequence of (label, index) choices; replaying the
same schedule against the same scenario and seed reproduces the run
bit-for-bit (`random.seed` per run pins the backoff jitter; the
VirtualClock owns time). Exploration is stateless replay-from-root DFS
with state-fingerprint convergence pruning (two schedules reaching an
identical world state explore a pending action only once) and a fault
BUDGET: at most `max_faults` injected faults per schedule, so the tree
stays exhaustive *within k concurrent infrastructure faults* rather
than astronomically wide. Counterexamples serialize as replayable JSON
schedules (`replay()` re-runs one and returns the violation).

Known blind spots are catalogued in docs/ANALYSIS.md (pass 8): depth/
fault bounds, name-level (not digest-level) pick verification, and the
3-fault torn-read + stale-beacon claim-overwrite coincidence.
"""

from __future__ import annotations

import hashlib
import json
import logging
import random
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from veles_tpu.analysis.findings import SEV_ERROR, Finding
from veles_tpu.resilience.clock import VirtualClock
from veles_tpu.resilience.cluster import (COORD_META, ClusterCoordinator,
                                          ClusterMember)
from veles_tpu.serving_gen import GenerationLedger
from veles_tpu.serving_router import BEACON_PREFIX, RouterCore, beacon_name
from veles_tpu.serving_watch import WeightWatcher

__all__ = ["MUTANTS", "SCENARIOS", "ExploreResult", "Violation",
           "check_tree", "explore", "findings_from", "quick_check",
           "replay"]


class AgentCrashed(BaseException):
    """A crash-point fault fired inside an agent's action. BaseException
    on purpose: the production code's broad `except Exception` nets
    (best-effort mirror I/O, beacon publishes) must not swallow a
    simulated host death."""

    def __init__(self, host_id: str) -> None:
        super().__init__(f"host {host_id} crashed")
        self.host_id = host_id


class Violation(Exception):
    """One invariant violation; aborts the run that produced it."""

    def __init__(self, rule: str, invariant: int, message: str) -> None:
        super().__init__(message)
        self.rule = rule
        self.invariant = invariant
        self.message = message
        self.events: List[Dict[str, Any]] = []


class Scheduler:
    """The choice tree's cursor: replays a recorded prefix, then takes
    default (index 0) choices while RECORDING every point's label and
    arity, so the explorer can enumerate siblings. Fault points stop
    advertising alternatives once the per-run fault budget is spent."""

    def __init__(self, prefix: Sequence[Tuple[str, int]] = (),
                 max_faults: int = 2) -> None:
        self.prefix = list(prefix)
        self.pos = 0
        self.max_faults = max_faults
        self.faults_used = 0
        self.quiescing = False
        self.diverged = False
        #: (label, index, advertised_arity, option_label, fingerprint)
        self.trace: List[tuple] = []

    def choose(self, label: str, options: Sequence[str],
               fault: bool = False, fp: Optional[str] = None) -> int:
        n = len(options)
        if self.quiescing:
            # deterministic cooldown: no new branch points, take the
            # fault-free default so quiescence converges
            return 0
        if self.pos < len(self.prefix):
            plabel, pidx = self.prefix[self.pos]
            if plabel != label:
                self.diverged = True
            idx = pidx if 0 <= pidx < n else 0
            arity = n
        else:
            idx = 0
            arity = n if (not fault
                          or self.faults_used < self.max_faults) else 1
        if fault and idx > 0:
            self.faults_used += 1
        self.trace.append((label, idx, arity, options[idx], fp))
        self.pos += 1
        return idx


class SimMirror:
    """In-memory mirror store implementing the meta/entries subset the
    protocol uses, with scheduler-controlled faults at exactly the
    points the real DirMirror can fail: the COORD_META write (crash
    before/after — kill-before-announce / kill-after-announce), the
    beacon write (lost — a delayed beacon that stays stale) and every
    meta read (torn — the hardened `DirMirror.get_meta` degrades a torn
    record to None after its bounded re-reads)."""

    spec = "sim://"

    def __init__(self, world: "SimWorld") -> None:
        self.world = world
        self.metas: Dict[str, Dict[str, Any]] = {}

    def put_meta(self, name: str, record: Dict[str, Any]) -> bool:
        actor = self.world.current_host()
        if name == COORD_META:
            pick = self.world.choice(
                f"announce:{actor}",
                ("ok", "crash-before-write", "crash-after-write"),
                fault=True)
            if pick == 1:
                raise AgentCrashed(actor)
            self.metas[name] = dict(record)
            if pick == 2:
                raise AgentCrashed(actor)
            return True
        pick = self.world.choice(f"beacon:{actor}", ("ok", "lost"),
                                 fault=True)
        if pick == 0:
            self.metas[name] = dict(record)
        return True

    def get_meta(self, name: str) -> Optional[Dict[str, Any]]:
        rec = self.metas.get(name)
        if rec is None:
            return None       # absence is deterministic: no branch
        pick = self.world.choice(
            f"meta-read:{self.world.current_host()}", ("ok", "torn"),
            fault=True)
        if pick == 1:
            return None
        return dict(rec)

    def meta_names(self, prefix: str = "") -> List[str]:
        """Beacon discovery listing (serving_router contract): empty on
        an unreachable mirror — the `unlistable` fault models exactly
        that outage, and the router must coast on last-known state."""
        pick = self.world.choice(
            f"meta-list:{self.world.current_host()}",
            ("ok", "unlistable"), fault=True)
        if pick == 1:
            return []
        return sorted(n for n in self.metas if n.startswith(prefix))

    def entries(self) -> List[Dict[str, Any]]:
        return [{"name": n, "digest": s["claimed"], "mtime": s["mtime"]}
                for n, s in sorted(self.world.mirror_snaps.items())]

    def fetch(self, name: str, dest: str) -> Optional[str]:
        rec = self.world.mirror_snaps.get(name)
        if rec is None or rec["claimed"] != rec["true"]:
            return None       # fetch re-verifies the bytes
        return name


class SimCoordinator(ClusterCoordinator):
    """The real coordinator bound into the simulated world: no HTTP
    (peers reach `handle_beat` synchronously through the world's
    router), the world's mirror, and a pick-event hook so the invariant
    ledger observes every restart/membership decision."""

    def __init__(self, world: "SimWorld", *args, **kwargs) -> None:
        self.world = world
        super().__init__(*args, **kwargs)

    def _bind_http(self):
        return None

    def _mirror(self):
        return self.world.mirror

    def _initiate_restart(self, reason, nonfinite=False):
        super()._initiate_restart(reason, nonfinite=nonfinite)
        if self.action == "run":
            self.snapshot = self.world.mutate_pick(self.snapshot)
            self.world.record_pick(self)

    def _membership_bump(self, reason, admit=None, evict=None):
        super()._membership_bump(reason, admit=admit, evict=evict)
        if self.action == "run":
            self.snapshot = self.world.mutate_pick(self.snapshot)
            self.world.record_pick(self)


class NoFloorStopCoordinator(SimCoordinator):
    """Seeded mutant (invariant 8): the membership-bump floor guard is
    gone, so a coordinator promoted over a sub-floor live view resumes
    the job instead of fail-stopping."""

    def _membership_bump(self, reason, admit=None, evict=None):
        keep = self.floor
        self.floor = 1
        try:
            super()._membership_bump(reason, admit=admit, evict=evict)
        finally:
            self.floor = keep


class SimMember(ClusterMember):
    """The real member agent over simulated children / mirror /
    transport. Only the process- and I/O-facing seams are overridden;
    the beat loop, fencing, failover, election and promotion logic is
    the shipped code."""

    def __init__(self, world: "SimWorld", **kwargs) -> None:
        self.world = world
        self.sim_child: Optional[str] = None   # running|failed|done|dead
        self.sim_epoch = -1
        self.sim_local: Dict[str, Dict[str, Any]] = {}
        self._mc_rx: Optional[Tuple[int, int]] = None
        super().__init__([["true"]], clock=world.clock, mirror="sim://",
                         **kwargs)

    # -- simulated child set --------------------------------------------------

    def _sim_writer(self) -> bool:
        # the real `_spawn` env contract: the host homed to its own
        # embedded coordinator drops the VELES_SNAPSHOT_DRY_RUN pin, a
        # host whose embedded coordinator was deposed re-pins, and a
        # coordinator-less host keeps whatever its launch env says
        if self._is_writer():
            return True
        if self.coordinator is not None:
            return False
        return "VELES_SNAPSHOT_DRY_RUN" not in self.env

    def _spawn(self, run_dir, snapshot):
        self._respawns += 1
        self._procs = [object()]          # truthy: step() probes status
        self.sim_child = "running"
        self.sim_epoch = (self.world.snap_epochs.get(snapshot, 0)
                          if snapshot else 0)
        self.world.record_spawn(self, snapshot, self._sim_writer())

    def _children_status(self):
        if self.sim_child == "failed":
            return "failed", [1]
        if self.sim_child == "done":
            return "done", [0]
        if self.sim_child == "dead":
            return "failed", [-15]
        return "running", [None]

    def _kill_children(self):
        if self.sim_child == "running":
            self.sim_child = "dead"

    def _child_payload(self):
        return {"epoch": self.sim_epoch}

    def _plan(self):
        return None

    # -- simulated snapshot store ---------------------------------------------

    def _local_snapshots(self):
        out = []
        for name, s in sorted(self.sim_local.items()):
            if s["claimed"] != s["true"]:
                continue      # the sidecar re-hash fails: no vote
            out.append({"name": name, "digest": s["claimed"],
                        "mtime": s["mtime"]})
        return out

    def _resolve_snapshot(self, name):
        if name:
            loc = self.sim_local.get(name)
            if loc is not None and loc["claimed"] == loc["true"]:
                return name
            rec = self.world.mirror_snaps.get(name)
            if rec is not None and rec["claimed"] == rec["true"]:
                self.sim_local[name] = dict(rec)   # mirror restore
                return name
            if rec is not None:
                self._bad_mirror.add(name)   # fetch re-verify failed
        best = None
        for n, s in sorted(self.sim_local.items()):
            if s["claimed"] == s["true"] \
                    and (best is None or s["mtime"] > best[1]):
                best = (n, s["mtime"])
        if best is None:
            for n, rec in sorted(self.world.mirror_snaps.items()):
                if n in self._bad_mirror \
                        or rec["claimed"] != rec["true"]:
                    continue
                if best is None or rec["mtime"] > best[1]:
                    best = (n, rec["mtime"])
        return best[0] if best else None

    # -- simulated transport / control plane ----------------------------------

    def _mirror(self):
        return self.world.mirror

    def _post(self, path, report):
        return self.world.deliver(self, path, report)

    def _beat(self, status, codes):
        self._mc_rx = None
        d = super()._beat(status, codes)
        if d is not None:
            self._mc_rx = (int(d.get("term", 0) or 0), self.term)
        return d

    def _join_cluster(self, status, codes):
        self._mc_rx = None
        d = super()._join_cluster(status, codes)
        if d is not None:
            self._mc_rx = (int(d.get("term", 0) or 0), self.term)
        return d

    def _bind_coordinator(self, term, members):
        coord = self.world.coord_cls(
            self.world, self.floor, host=self.advertise,
            port=self.world.next_port(), token=None,
            dead_after=self.dead_after, max_restarts=self.max_restarts,
            members=members, mirror="sim://", term=term,
            coord_id=self.host_id, advertise=self.advertise,
            gather=True, clock=self._clock,
            join_grace=self.dead_after * 2)
        coord.start()
        self.world.register_coordinator(coord)
        return coord

    def _finish(self, code, outcome, dead_hosts=None):
        self.world.record_finish(self, code, outcome)
        if self.coordinator is not None:
            self.coordinator.stop()
        return code


# -- seeded member mutants ----------------------------------------------------

class NoFenceMember(SimMember):
    """Seeded mutant (invariant 1): the directive term fence is gone —
    the member treats a stale coordinator's directive as current (the
    term is rewritten up before step() compares it; the original term
    stays on the ledger wire so the violation is observable)."""

    def _beat(self, status, codes):
        d = super()._beat(status, codes)
        if d is not None:
            d = dict(d)
            d["term"] = max(int(d.get("term", 0) or 0), self.term)
        return d


class DoubleCoordinatorMember(SimMember):
    """Seeded mutant (invariant 2): the election plane rots — deaf to
    announcements, a solipsist liveness view, and a term counter that
    saturates at 2 — so two hosts can each bind a coordinator at the
    SAME term."""

    def _try_adopt(self, ann):
        return False

    def _live_hosts(self, mirror):
        return [self.host_id]

    def _bind_coordinator(self, term, members):
        return super()._bind_coordinator(min(term, 2), members)


class AllWritersMember(SimMember):
    """Seeded mutant (invariant 4): the single-writer dry-run pin is
    dropped — every host spawns its children as the snapshot writer."""

    def _sim_writer(self):
        return True


class UnverifiedVotesMember(SimMember):
    """Seeded mutant (invariant 5): local snapshot reports skip the
    sidecar re-hash, so a rotted local copy votes its CLAIMED digest
    into the quorum."""

    def _local_snapshots(self):
        return [{"name": name, "digest": s["claimed"],
                 "mtime": s["mtime"]}
                for name, s in sorted(self.sim_local.items())]


class NoBeaconTermMember(SimMember):
    """Regression mutant (invariant 2): reverts the beacon-term claim
    fence this checker's partition scenario motivated — the claim
    target ignores terms carried on peer beacons, so a candidate whose
    announcement reads are lossy re-claims a term that is already
    live-bound."""

    def _live_hosts(self, mirror):
        live = super()._live_hosts(mirror)
        self._beacon_term = 0
        return live


class NoWriterRepinMember(SimMember):
    """Regression mutant (invariant 4): reverts the writer re-pin —
    any host embedding a coordinator object spawns as the snapshot
    writer, even after re-homing to a successor control plane."""

    def _sim_writer(self):
        return (self.coordinator is not None
                or "VELES_SNAPSHOT_DRY_RUN" not in self.env)


class HostAgent:
    """One schedulable host: the member plus its crash/exit state."""

    def __init__(self, member: SimMember) -> None:
        self.member = member
        self.exit_code: Optional[int] = None
        self.crashed = False
        self.steps = 0
        self.prev_term = member.term
        self.prev_gen = member.generation

    @property
    def live(self) -> bool:
        return not self.crashed and self.exit_code is None


class SimWorld:
    """Base world: scheduler plumbing, the router (synchronous
    transport), the event/invariant ledger and the explore loop's
    run/quiesce/final hooks. Scenario builders subclass or configure."""

    scenario = "base"

    def __init__(self, sched: Scheduler, mutant: Optional[str] = None
                 ) -> None:
        self.sched = sched
        self.mutant = mutant
        self.clock = VirtualClock()
        self.mirror = SimMirror(self)
        #: ground truth snapshot stores: name -> {claimed, true, mtime}
        self.mirror_snaps: Dict[str, Dict[str, Any]] = {}
        self.snap_epochs: Dict[str, int] = {}
        self.agents: Dict[str, HostAgent] = {}
        self.router: Dict[Tuple[str, int], SimCoordinator] = {}
        self.events: List[Dict[str, Any]] = []
        self.writer_by_gen: Dict[int, str] = {}
        self.max_picked_epoch = -1
        self.used: set = set()
        self.floor = 1
        self.stale_route = False
        #: True while a scenario builds its PREBUILT start state: every
        #: choice takes the fault-free default and records nothing —
        #: faults belong to scheduled actions, not to world seeding
        self.seeding = False
        self._actor: List[str] = ["boot"]
        self._ports = iter(range(9000, 9900))
        self.coord_cls: Callable = (
            NoFloorStopCoordinator if mutant == "no_floor_stop"
            else SimCoordinator)
        self.member_cls: Callable = {
            "no_term_fence": NoFenceMember,
            "double_coordinator": DoubleCoordinatorMember,
            "all_writers": AllWritersMember,
            "unverified_votes": UnverifiedVotesMember,
            "no_beacon_term": NoBeaconTermMember,
            "no_writer_repin": NoWriterRepinMember,
        }.get(mutant or "", SimMember)

    # -- plumbing -------------------------------------------------------------

    def choice(self, label: str, options: Sequence[str],
               fault: bool = False, fp: Optional[str] = None) -> int:
        if self.seeding:
            return 0
        return self.sched.choose(label, options, fault=fault, fp=fp)

    def current_host(self) -> str:
        return self._actor[-1]

    def next_port(self) -> int:
        return next(self._ports)

    def register_coordinator(self, coord: SimCoordinator) -> None:
        self.router[(coord.advertise or coord.host, coord.port)] = coord
        self.events.append({"ev": "bind", "coord": coord.coord_id,
                            "term": coord.term,
                            "generation": coord.generation})

    def deregister_host(self, host_id: str) -> None:
        for addr in [a for a, c in self.router.items()
                     if c.coord_id == host_id]:
            del self.router[addr]

    def kill_host(self, host_id: str) -> None:
        agent = self.agents.get(host_id)
        if agent is not None:
            agent.crashed = True
        self.deregister_host(host_id)
        self.events.append({"ev": "crash", "host": host_id})

    def add_snap(self, name: str, epoch: int, mtime: float,
                 rotted: bool = False, on_mirror: bool = True,
                 hosts: Sequence[str] = ()) -> None:
        digest = f"d-{name}"
        rec = {"claimed": digest,
               "true": digest if not rotted else f"rot-{name}",
               "mtime": mtime}
        self.snap_epochs[name] = epoch
        if on_mirror:
            self.mirror_snaps[name] = dict(rec)
        for hid in hosts:
            self.agents[hid].member.sim_local[name] = dict(rec)

    # -- transport ------------------------------------------------------------

    def deliver(self, member: SimMember, path: str,
                report: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        coord = self.router.get((member.coord_host, member.coord_port))
        if coord is None:
            return None       # connection refused: deterministic
        options = ["deliver", "drop"]
        stale = None
        if self.stale_route:
            stale = [c for c in set(self.router.values())
                     if c.term < coord.term]
            if stale:
                options.append("stale-route")
        pick = self.choice(f"net:{member.host_id}", tuple(options),
                           fault=True)
        if options[pick] == "drop":
            return None
        if options[pick] == "stale-route":
            # a stale VIP/DNS entry routes the beat to a deposed
            # incumbent and returns ITS directive — exactly what the
            # member-side term fence exists to reject
            coord = min(stale, key=lambda c: (c.term, c.coord_id))
        self._actor.append(coord.coord_id)
        try:
            handle = (coord.handle_join if path == "/join"
                      else coord.handle_beat)
            return handle(dict(report))
        except AgentCrashed as c:
            self.kill_host(c.host_id)
            return None       # the connection died mid-request
        finally:
            self._actor.pop()

    # -- the invariant ledger -------------------------------------------------

    def _verified_copy_exists(self, name: str) -> bool:
        rec = self.mirror_snaps.get(name)
        if rec is not None and rec["claimed"] == rec["true"]:
            return True
        for agent in self.agents.values():
            s = agent.member.sim_local.get(name)
            if s is not None and s["claimed"] == s["true"]:
                return True
        return False

    def record_pick(self, coord: SimCoordinator) -> None:
        name = coord.snapshot
        epoch = self.snap_epochs.get(name) if name else None
        self.events.append({"ev": "pick", "coord": coord.coord_id,
                            "term": coord.term,
                            "generation": coord.generation,
                            "snapshot": name, "epoch": epoch})
        if name is None:
            return   # scratch pick: nothing to verify (blind spot:
            # a scratch pick after progress is quorum-sanctioned)
        if not self._verified_copy_exists(name):
            raise Violation(
                "mc-verified-pick", 5,
                f"coordinator {coord.coord_id} (term {coord.term}) "
                f"picked {name} for generation {coord.generation} but "
                f"no sidecar-verified copy of it exists anywhere")
        if epoch is not None:
            if epoch < self.max_picked_epoch:
                raise Violation(
                    "mc-generation-rollback", 3,
                    f"restart pick {name} (epoch {epoch}) regresses "
                    f"past an earlier pick at epoch "
                    f"{self.max_picked_epoch}")
            self.max_picked_epoch = epoch

    def record_spawn(self, member: SimMember, snapshot: Optional[str],
                     writer: bool) -> None:
        self.events.append({"ev": "spawn", "host": member.host_id,
                            "generation": member.generation,
                            "term": member.term, "snapshot": snapshot,
                            "writer": writer,
                            "epoch": member.sim_epoch})
        rx = member._mc_rx
        if rx is not None and rx[0] and rx[0] < rx[1]:
            raise Violation(
                "mc-term-fence", 1,
                f"host {member.host_id} spawned generation "
                f"{member.generation} on a directive from stale term "
                f"{rx[0]} (the member had already seen term {rx[1]})")
        if writer:
            prev = self.writer_by_gen.get(member.generation)
            if prev is not None and prev != member.host_id:
                raise Violation(
                    "mc-single-writer", 4,
                    f"hosts {prev} and {member.host_id} both spawned "
                    f"as the snapshot writer for generation "
                    f"{member.generation}")
            self.writer_by_gen[member.generation] = member.host_id

    def record_finish(self, member: SimMember, code: int,
                      outcome: str) -> None:
        self.events.append({"ev": "finish", "host": member.host_id,
                            "code": code, "term": member.term,
                            "outcome": outcome[:80]})
        rx = member._mc_rx
        if rx is not None and rx[0] and rx[0] < rx[1]:
            raise Violation(
                "mc-term-fence", 1,
                f"host {member.host_id} exited ({code}) on a terminal "
                f"directive from stale term {rx[0]} (the member had "
                f"already seen term {rx[1]})")

    def check_state(self) -> None:
        for agent in self.agents.values():
            m = agent.member
            if m.term < agent.prev_term:
                raise Violation(
                    "mc-term-fence", 1,
                    f"host {m.host_id} observed term went backwards: "
                    f"{agent.prev_term} -> {m.term}")
            if m.generation < agent.prev_gen:
                raise Violation(
                    "mc-generation-rollback", 3,
                    f"host {m.host_id} generation went backwards: "
                    f"{agent.prev_gen} -> {m.generation}")
            agent.prev_term, agent.prev_gen = m.term, m.generation
        by_term: Dict[int, set] = {}
        for coord in set(self.router.values()):
            by_term.setdefault(coord.term, set()).add(coord.coord_id)
        for term, ids in by_term.items():
            if len(ids) > 1:
                raise Violation(
                    "mc-single-coordinator", 2,
                    f"two live coordinators bound at term {term}: "
                    f"hosts {sorted(ids)}")

    # -- scenario hooks -------------------------------------------------------

    def start(self) -> None:
        pass

    def enabled_actions(self) -> List[Tuple[str, Callable[[], None]]]:
        raise NotImplementedError

    def fingerprint(self) -> str:
        raise NotImplementedError

    def run(self, max_actions: int) -> None:
        self._actor = [next(iter(self.agents), "boot")]
        try:
            self.start()
        except AgentCrashed as c:
            self.kill_host(c.host_id)
        self.check_state()
        for _ in range(max_actions):
            acts = self.enabled_actions()
            if not acts:
                break
            idx = self.choice("act", tuple(a[0] for a in acts),
                              fp=self.fingerprint())
            self.perform(acts[idx])
            self.check_state()
        self.quiesce()
        self.check_final()

    def perform(self, act: Tuple[str, Callable[[], None]]) -> None:
        self.events.append({"ev": "act", "act": act[0]})
        act[1]()

    def step_agent(self, agent: HostAgent) -> None:
        agent.steps += 1
        self._actor.append(agent.member.host_id)
        try:
            code = agent.member.step("sim")
            if code is not None:
                agent.exit_code = code
                self.deregister_host(agent.member.host_id)
        except AgentCrashed as c:
            self.kill_host(c.host_id)
        finally:
            self._actor.pop()

    def quiesce(self, rounds: int = 60) -> None:
        """Bounded cooldown past every protocol timeout: all agents
        step under a fault-free deterministic scheduler, so fail-stop
        paths (isolation, below-floor sweeps, drains) get to finish."""
        self.sched.quiescing = True
        for _ in range(rounds):
            live = [a for a in self.agents.values() if a.live]
            if not live:
                break
            for agent in live:
                if agent.live:
                    self.step_agent(agent)
            self.check_state()

    def check_final(self) -> None:
        alive = [a for a in self.agents.values() if not a.crashed]
        if len(alive) < self.floor:
            for a in alive:
                if a.exit_code is None:
                    raise Violation(
                        "mc-floor-failstop", 8,
                        f"only {len(alive)} host(s) survive (< floor "
                        f"{self.floor}) but host "
                        f"{a.member.host_id} is still running at "
                        f"quiescence instead of fail-stopping")


class ClusterWorld(SimWorld):
    """The election / membership / partition planes: N member hosts
    (host 0 embeds the boot coordinator), a shared SimMirror, and
    schedulable crash / child-failure / training actions."""

    def __init__(self, sched: Scheduler, mutant: Optional[str],
                 *, hosts: int = 3, floor: int = 3, join_host: bool =
                 False, beat_s: float = 1.0, dead_after: float = 6.0,
                 coord_timeout: float = 24.0, trains: int = 2,
                 crashes: Sequence[str] = (), fails: Sequence[str] = ()
                 ) -> None:
        super().__init__(sched, mutant)
        self.floor = floor
        self.beat_s = beat_s
        self.dead_after = dead_after
        self.coord_timeout = coord_timeout
        self.trains_left = trains
        self.crashable = list(crashes)
        self.failable = list(fails)
        self.boot_port = self.next_port()
        self.boot_coord = self.coord_cls(
            self, floor, host="h0", port=self.boot_port, token=None,
            dead_after=dead_after, max_restarts=3,
            members=[str(i) for i in range(hosts)], mirror="sim://",
            term=1, coord_id="0", advertise="h0", gather=False,
            clock=self.clock)
        for i in range(hosts):
            hid = str(i)
            env = {} if i == 0 else {"VELES_SNAPSHOT_DRY_RUN": "1"}
            member = self.member_cls(
                self, host_id=hid, coordinator_addr=f"h0:"
                f"{self.boot_port}",
                coordinator=self.boot_coord if i == 0 else None,
                env=env, floor=floor, beat_s=beat_s,
                dead_after=dead_after, coord_timeout=coord_timeout,
                max_restarts=3, advertise=f"h{hid}")
            self.agents[hid] = HostAgent(member)
        if join_host:
            hid = str(hosts)
            member = self.member_cls(
                self, host_id=hid,
                coordinator_addr=f"h0:{self.boot_port}",
                env={"VELES_SNAPSHOT_DRY_RUN": "1"}, floor=floor,
                beat_s=beat_s, dead_after=dead_after,
                coord_timeout=coord_timeout, max_restarts=3,
                join=True, advertise=f"h{hid}")
            self.agents[hid] = HostAgent(member)

    def mutate_pick(self, snapshot: Optional[str]) -> Optional[str]:
        if self.mutant == "oldest_pick" and self.mirror_snaps:
            # seeded bug (invariant 3): the pick sorts the wrong way
            return min(self.mirror_snaps,
                       key=lambda n: self.mirror_snaps[n]["mtime"])
        return snapshot

    def start(self) -> None:
        self._actor = ["0"]
        self.boot_coord.start()       # announces through the mirror
        self.register_coordinator(self.boot_coord)

    def enabled_actions(self):
        acts: List[Tuple[str, Callable[[], None]]] = []
        live = [a for a in self.agents.values() if a.live]
        # round-robin default: the least-stepped live host acts first,
        # so the all-defaults schedule is the fair healthy run and
        # every sibling branch perturbs it at one point
        for agent in sorted(live, key=lambda a: (a.steps,
                                                 a.member.host_id)):
            acts.append((f"step:h{agent.member.host_id}",
                         lambda a=agent: self.step_agent(a)))
        for agent in live:
            m = agent.member
            if m.sim_child == "running" and self.trains_left > 0 \
                    and m._sim_writer():
                acts.append((f"train:h{m.host_id}",
                             lambda a=agent: self._train(a)))
        for hid in self.failable:
            agent = self.agents.get(hid)
            if agent is not None and agent.live \
                    and agent.member.sim_child == "running" \
                    and f"fail:{hid}" not in self.used:
                acts.append((f"fail:h{hid}",
                             lambda h=hid: self._fail_children(h)))
        for hid in self.crashable:
            agent = self.agents.get(hid)
            if agent is not None and agent.live \
                    and f"crash:{hid}" not in self.used:
                acts.append((f"crash:h{hid}",
                             lambda h=hid: self._crash(h)))
        return acts

    def _train(self, agent: HostAgent) -> None:
        m = agent.member
        self.trains_left -= 1
        m.sim_epoch = max(m.sim_epoch, 0) + 1
        self.clock.advance(0.25)
        name = f"snap_h{m.host_id}_{m.sim_epoch:03d}.pickle"
        self.add_snap(name, epoch=m.sim_epoch,
                      mtime=self.clock.time(), hosts=(m.host_id,))

    def _fail_children(self, hid: str) -> None:
        self.used.add(f"fail:{hid}")
        self.agents[hid].member.sim_child = "failed"

    def _crash(self, hid: str) -> None:
        self.used.add(f"crash:{hid}")
        self.kill_host(hid)

    def fingerprint(self) -> str:
        st: Dict[str, Any] = {
            "t": round(self.clock.monotonic(), 4),
            "faults": self.sched.faults_used,
            "used": sorted(self.used),
            "trains": self.trains_left,
            "metas": self.mirror.metas,
            "snaps": sorted(self.mirror_snaps),
            "picked": self.max_picked_epoch,
            "writers": sorted(self.writer_by_gen.items()),
        }
        st["agents"] = [
            [a.member.host_id, a.member.term, a.member.generation,
             a.exit_code, a.crashed, a.steps, a.member.sim_child,
             a.member.sim_epoch, a.member._join_pending,
             a.member._reconnect_streak, a.member._killed_gen,
             round(a.member._last_contact, 4),
             a.member._beats_sent, a.member._respawns,
             a.member._beacon_term,
             sorted(a.member._stale_terms_seen),
             list(a.member._adopted), sorted(a.member._bad_mirror),
             sorted(a.member.sim_local)]
            for a in self.agents.values()]
        st["coords"] = sorted(
            [[c.coord_id, c.term, c.generation, c.action, c.restarts,
              c._gather, round(c._gather_deadline, 4), c._best_epoch,
              c._stagnant, c._superseded, sorted(c._acked),
              sorted(c.dead_hosts), sorted(c.members),
              sorted((hid, round(h["last_beat"], 4),
                      str(h["report"].get("status")),
                      int(h["report"].get("generation", 0) or 0))
                     for hid, h in c._hosts.items())]
             for c in set(self.router.values())])
        blob = json.dumps(st, sort_keys=True, default=str)
        return hashlib.md5(blob.encode()).hexdigest()


class PartitionWorld(ClusterWorld):
    """A legal mid-protocol start state: the fleet is already split —
    C1 (term 1, the pre-partition incumbent, two generations ahead on
    its island) still steers hosts 0 and 2, while host 1 was re-elected
    away and runs under its own C2 (term 2). The stale-route fault can
    deliver one of C1's directives to host 1; the member term fence is
    what must reject it."""

    def __init__(self, sched: Scheduler, mutant: Optional[str]) -> None:
        super().__init__(sched, mutant, hosts=3, floor=3, trains=0)
        self.stale_route = True
        now = self.clock.time()
        self.add_snap("snap_001.pickle", epoch=1, mtime=now - 100.0)
        self.add_snap("snap_002.pickle", epoch=2, mtime=now - 50.0,
                      hosts=("0",))
        self.max_picked_epoch = 2     # the fleet resumed from e2

    def start(self) -> None:
        self._actor = ["0"]
        self.seeding = True
        c1, clock = self.boot_coord, self.clock
        c1.start()
        self.register_coordinator(c1)
        c1.generation, c1.restarts = 8, 2
        c1.snapshot = "snap_002.pickle"
        # host 1's island: a promoted C2 at term 2, gathered at gen 7
        h1 = self.agents["1"].member
        c2 = self.coord_cls(
            self, self.floor, host="h1", port=self.next_port(),
            token=None, dead_after=self.dead_after, max_restarts=3,
            members=["1", "2"], mirror="sim://", term=2, coord_id="1",
            advertise="h1", gather=False, clock=clock,
            join_grace=self.dead_after * 2)
        c2.start()
        self.register_coordinator(c2)
        c2.generation, c2.snapshot = 7, "snap_002.pickle"
        h1.coordinator = c2
        h1.coord_host, h1.coord_port = "h1", c2.port
        h1.term, h1.generation = 2, 7
        h1._adopted = (2, f"h1:{c2.port}")
        h1.sim_child, h1.sim_epoch = "running", 2
        h1.env.pop("VELES_SNAPSHOT_DRY_RUN", None)   # h1 is C2's writer
        for hid, gen in (("0", 8), ("2", 8)):
            m = self.agents[hid].member
            m.generation, m.sim_child, m.sim_epoch = gen, "running", 2
        rep = {h: self.agents[h].member._report("running", [None])
               for h in ("0", "1", "2")}
        mono = clock.monotonic()
        c1._hosts = {h: {"last_beat": mono, "report": dict(rep[h])}
                     for h in ("0", "1", "2")}
        c2._hosts = {"1": {"last_beat": mono, "report": dict(rep["1"])}}
        for h in ("0", "1", "2"):
            self.agents[h].member._publish_beacon()
        self.seeding = False
        self.check_state()


class SimServer:
    """The serving tier's hot-swap surface as the watcher sees it,
    owning a REAL GenerationLedger: `swap_params` validation outcomes
    are scheduler choices (the jax-side checks are out of model), the
    commit/rollback/pinning state machine is the shipped code."""

    def __init__(self, world: "HotSwapWorld",
                 ledger: GenerationLedger) -> None:
        self.world = world
        self.ledger = ledger
        ledger.boot("d-boot", ("P", "d-boot"))
        self.n_swap_refusals = 0

    @property
    def rolled_back(self):
        return self.ledger.rolled_back

    def generation(self):
        return self.ledger.snapshot()

    def note_swap_refused(self, reason: str, msg: str = "") -> None:
        self.n_swap_refusals += 1

    def swap_params(self, wf, digest=None, source="watcher"):
        from veles_tpu.serving import SwapRefused
        pick = self.world.choice(
            f"validate:{digest}", ("ok", "nonfinite", "device_put"),
            fault=True)
        if pick == 1:   # deterministic: content is bad, digest pinned
            raise SwapRefused("nonfinite",
                              f"{digest} probe went non-finite")
        if pick == 2:   # transient: retried on a later poll
            raise SwapRefused("device_put",
                              f"{digest} device placement failed")
        gen = self._commit(digest, source)
        self.world.record_apply(str(digest), source)
        return gen

    def _commit(self, digest, source):
        return self.ledger.commit(str(digest), source, ("P",
                                                        str(digest)))

    def rollback(self):
        gen, outgoing = self.ledger.rollback()
        self.world.gt_rolled_back.add(str(outgoing["digest"]))
        self.world.events.append({"ev": "rollback",
                                  "from": outgoing["digest"],
                                  "to": gen["digest"]})
        return gen


class SplitCommitServer(SimServer):
    """Seeded mutant (invariant 6): the swap commit is torn in two —
    the params handle flips immediately, the generation label lands
    only when a separate `finish-commit` action fires, so a ring round
    scheduled in between reads a pair no single call published."""

    def __init__(self, world, ledger):
        super().__init__(world, ledger)
        self.pending: Optional[Tuple[str, str]] = None

    def _commit(self, digest, source):
        self.ledger.params = ("P", str(digest))
        self.pending = (str(digest), source)
        return dict(self.ledger.generation)

    def finish_commit(self) -> None:
        digest, source = self.pending
        self.pending = None
        self.ledger.prev_gen = dict(self.ledger.generation)
        self.ledger.generation = {
            "digest": digest, "since": self.world.clock.time(),
            "source": source}
        self.ledger.n_swaps += 1


class PinlessLedger(GenerationLedger):
    """Seeded mutant (invariant 7): rollback forgets to pin the digest
    it rolled back from, so the watcher re-applies it one poll later."""

    def rollback(self):
        out = super().rollback()
        self.rolled_back.clear()
        return out


class SimWatcher(WeightWatcher):
    """The real watcher over the simulated obtain: fetch/verify/import
    outcomes are scheduler choices; the scan, pinning and
    deterministic-refusal protocol above them is the shipped code."""

    def __init__(self, world: "HotSwapWorld", server: SimServer) -> None:
        self.world = world
        super().__init__(server, world.mirror, poll_s=1.0,
                         tmp_dir="sim")

    def _obtain(self, name, digest):
        pick = self.world.choice(
            f"obtain:{name}", ("ok", "fetch-failed", "import-failed"),
            fault=True)
        if pick == 1:
            self._refuse("fetch_failed", digest,
                         f"mirror could not deliver {name}")
            return None
        if pick == 2:
            self._refuse("import_failed", digest,
                         f"snapshot import of {name} failed")
            return None
        return ("wf", digest)


class HotSwapWorld(SimWorld):
    """The train→serve plane: a trainer pushing digest-addressed
    snapshots, the watcher polling, an operator who may roll back, and
    the serving ring reading its (params, generation) pair once per
    round — the read the commit must be atomic against."""

    def __init__(self, sched: Scheduler, mutant: Optional[str]) -> None:
        super().__init__(sched, mutant)
        ledger_cls = (PinlessLedger if mutant == "no_rollback_pin"
                      else GenerationLedger)
        server_cls = (SplitCommitServer if mutant == "split_commit"
                      else SimServer)
        self.server = server_cls(self, ledger_cls(clock=self.clock))
        self.watcher = SimWatcher(self, self.server)
        self.gt_rolled_back: set = set()
        self.pushes_left = 3
        self.rollbacks_left = 2
        self.rounds_left = 4
        self.polls = 0

    def enabled_actions(self):
        acts: List[Tuple[str, Callable[[], None]]] = [
            ("poll", self._poll)]
        if self.rounds_left > 0:
            acts.append(("round", self._round))
        if self.pushes_left > 0:
            acts.append(("push", self._push))
        if self.rollbacks_left > 0 \
                and self.server.ledger.prev_params is not None:
            acts.append(("rollback", self._rollback))
        pending = getattr(self.server, "pending", None)
        if pending is not None:
            acts.append(("finish-commit", self.server.finish_commit))
        return acts

    def _poll(self) -> None:
        self.polls += 1
        self.clock.advance(1.0)
        self.watcher.poll_once()

    def _push(self) -> None:
        self.pushes_left -= 1
        k = 3 - self.pushes_left
        self.clock.advance(1.0)
        self.add_snap(f"hot_{k:03d}.pickle", epoch=k,
                      mtime=self.clock.time())

    def _rollback(self) -> None:
        self.rollbacks_left -= 1
        self.server.rollback()

    def _round(self) -> None:
        self.rounds_left -= 1
        self._check_pair("a ring round")

    def _check_pair(self, where: str) -> None:
        led = self.server.ledger
        params, gen = led.params, dict(led.generation)
        if params != ("P", str(gen["digest"])):
            raise Violation(
                "mc-atomic-commit", 6,
                f"{where} read params handle {params!r} against "
                f"generation label {gen['digest']!r} — a pair no "
                f"single ledger call published")

    def record_apply(self, digest: str, source: str) -> None:
        self.events.append({"ev": "apply", "digest": digest,
                            "source": source})
        if source == "watcher" and digest in self.gt_rolled_back:
            raise Violation(
                "mc-rollback-pin", 7,
                f"the watcher re-applied {digest} after the operator "
                f"rolled back from it — the rollback pin is gone")

    def check_state(self) -> None:
        pass              # the plane has no term/generation agents

    def quiesce(self, rounds: int = 4) -> None:
        self.sched.quiescing = True
        for _ in range(rounds):
            self._poll()

    def check_final(self) -> None:
        self._check_pair("quiescence")

    def fingerprint(self) -> str:
        led = self.server.ledger
        st = {
            "gen": led.generation["digest"], "params": led.params,
            "prev": (led.prev_gen or {}).get("digest"),
            "swaps": led.n_swaps, "pins": sorted(led.rolled_back),
            "gt": sorted(self.gt_rolled_back),
            "pushes": self.pushes_left, "rb": self.rollbacks_left,
            "rounds": self.rounds_left, "polls": self.polls,
            "snaps": sorted(self.mirror_snaps),
            "refused": sorted(self.watcher._refused_digests),
            "streak": self.watcher._streak,
            "pending": getattr(self.server, "pending", None),
            "faults": self.sched.faults_used,
        }
        blob = json.dumps(st, sort_keys=True, default=str)
        return hashlib.md5(blob.encode()).hexdigest()


class RoutesToDrainingCore(RouterCore):
    """Seeded mutant (invariant 9): drain awareness dropped — the pick
    treats a draining replica as routable (the bug the beacon protocol
    exists to prevent: deregistration the router ignores)."""

    def _eligible(self, st, now):
        keep = st.status
        if st.status == "draining":
            st.status = "up"
        try:
            return super()._eligible(st, now)
        finally:
            st.status = keep


class FleetWorld(SimWorld):
    """The serving-fleet routing plane (ISSUE 19): three replica
    beacon publishers and the REAL `RouterCore` consuming them through
    the simulated mirror. Replicas beat, drain gracefully or crash to
    silence; the router polls (listing may fail — mirror outage — and
    any read may tear) and routes. Invariant 9: once a poll has
    OBSERVED a replica draining, no route lands there. Quiescence also
    checks the TTL sweep: a crash-silenced replica must be evicted
    once enough virtual time passes — a stale beacon file re-read must
    not count as liveness."""

    #: virtual seconds each poll advances; the TTL is sized so the
    #: quiesce polls alone cross it after a silence
    POLL_ADVANCE_S = 1.0
    TTL_S = 4.0

    def __init__(self, sched: Scheduler, mutant: Optional[str]) -> None:
        super().__init__(sched, mutant)
        core_cls = (RoutesToDrainingCore if mutant == "route_to_drained"
                    else RouterCore)
        self.core = core_cls(beacon_ttl_s=self.TTL_S, open_s=2.0)
        self.rids = ("r0", "r1", "r2")
        self.rep_status = {r: "up" for r in self.rids}
        self.rep_seq = {r: 0 for r in self.rids}
        self.rep_silent_at: Dict[str, Optional[float]] = {
            r: None for r in self.rids}
        #: ground truth: drains the router has actually SEEN (applied
        #: from a successfully-read beacon) — a lost/torn drain beacon
        #: leaves the replica legitimately routable
        self.gt_drained: set = set()
        self.beats_left = {r: 2 for r in self.rids}
        self.routes_left = 5
        self.drains_left = 1
        self.silences_left = 1
        self.polls = 0
        # seed: every replica announced and discovered (faults belong
        # to scheduled actions, not to world seeding)
        self.seeding = True
        for r in self.rids:
            self._beat(r)
        self._poll()
        self.seeding = False

    # -- replica side ---------------------------------------------------------

    def _beat(self, rid: str) -> None:
        self.rep_seq[rid] += 1
        self._actor.append(rid)
        try:
            self.mirror.put_meta(beacon_name(rid), {
                "rid": rid, "url": f"sim://{rid}",
                "status": self.rep_status[rid],
                "seq": self.rep_seq[rid], "capacity": 4.0})
        finally:
            self._actor.pop()

    def _drain(self, rid: str) -> None:
        self.drains_left -= 1
        self.rep_status[rid] = "draining"
        self.events.append({"ev": "drain", "rid": rid})
        self._beat(rid)

    def _silence(self, rid: str) -> None:
        self.silences_left -= 1
        self.rep_silent_at[rid] = self.clock.monotonic()
        self.events.append({"ev": "silence", "rid": rid})

    # -- router side ----------------------------------------------------------

    def _poll(self) -> None:
        self.polls += 1
        self.clock.advance(self.POLL_ADVANCE_S)
        now = self.clock.monotonic()
        self._actor.append("router")
        try:
            for name in self.mirror.meta_names(BEACON_PREFIX):
                rec = self.mirror.get_meta(name)
                if isinstance(rec, dict):
                    self.core.observe_beacon(rec, now)
            self.core.evict_silent(now)
        finally:
            self._actor.pop()
        for rid, st in self.core.replicas.items():
            if st.status == "draining":
                self.gt_drained.add(rid)

    def _route(self) -> None:
        self.routes_left -= 1
        now = self.clock.monotonic()
        rid = self.core.pick(now)
        self.events.append({"ev": "route", "to": rid})
        if rid is None:
            return                # shed: fine, never a wrong route
        if rid in self.gt_drained:
            raise Violation(
                "mc-no-route-to-drained", 9,
                f"router routed a request to {rid} after observing "
                f"its draining beacon — drain discipline is gone")
        self.core.note_dispatch(rid)
        pick = self.choice(f"dispatch:{rid}", ("ok", "fail", "shed"),
                           fault=True)
        if pick == 1:
            self.core.note_fail(rid, now)
        elif pick == 2:
            self.core.note_shed(rid, 2.0, now)
        else:
            self.core.note_ok(rid, 0.05)

    # -- scenario hooks -------------------------------------------------------

    def enabled_actions(self):
        acts: List[Tuple[str, Callable[[], None]]] = [
            ("poll", self._poll)]
        if self.routes_left > 0:
            acts.append(("route", self._route))
        if self.drains_left > 0 and self.rep_status["r0"] == "up":
            acts.append(("drain:r0", lambda: self._drain("r0")))
        if self.silences_left > 0:
            acts.append(("silence:r2", lambda: self._silence("r2")))
        for rid in self.rids:
            if self.beats_left[rid] > 0 \
                    and self.rep_silent_at[rid] is None:
                acts.append((f"beat:{rid}",
                             lambda r=rid: self._beat_action(r)))
        return acts

    def _beat_action(self, rid: str) -> None:
        self.beats_left[rid] -= 1
        self._beat(rid)

    def check_state(self) -> None:
        pass                      # the route action checks inline

    def quiesce(self, rounds: int = 6) -> None:
        self.sched.quiescing = True
        for _ in range(rounds):
            self._poll()
        for _ in range(2):
            if self.routes_left > 0:
                self._route()

    def check_final(self) -> None:
        now = self.clock.monotonic()
        for rid, t in self.rep_silent_at.items():
            if t is None:
                continue
            if now - t > self.TTL_S + self.POLL_ADVANCE_S \
                    and rid in self.core.replicas:
                raise Violation(
                    "mc-no-route-to-drained", 9,
                    f"crash-silenced replica {rid} still registered "
                    f"{now - t:.0f}s after its last beacon advance — "
                    f"the stale beacon record is being counted as "
                    f"liveness, so the TTL sweep never fires")

    def fingerprint(self) -> str:
        st = {
            "rep": [(r, self.rep_status[r], self.rep_seq[r],
                     self.rep_silent_at[r]) for r in self.rids],
            "core": [(rid, s.status, s.seq, s.circuit, s.fails,
                      s.inflight, round(s.not_before, 3),
                      round(s.last_seen, 3))
                     for rid, s in sorted(self.core.replicas.items())],
            "rr": self.core._rr,
            "tomb": sorted(self.core._tombstones.items()),
            "gt": sorted(self.gt_drained),
            "beats": sorted(self.beats_left.items()),
            "routes": self.routes_left, "drains": self.drains_left,
            "silences": self.silences_left, "polls": self.polls,
            "metas": sorted(self.mirror.metas),
            "faults": self.sched.faults_used,
        }
        blob = json.dumps(st, sort_keys=True, default=str)
        return hashlib.md5(blob.encode()).hexdigest()


# -- scenario / mutant registries ---------------------------------------------

@dataclass
class Scenario:
    name: str
    build: Callable[[Scheduler, Optional[str]], SimWorld]
    max_actions: int
    description: str


SCENARIOS: Dict[str, Scenario] = {s.name: s for s in (
    Scenario(
        "election",
        lambda sched, mutant: ClusterWorld(
            sched, mutant, crashes=("0", "1"), fails=("2",)),
        14,
        "3-host boot fleet; coordinator-host and peer crashes force "
        "re-elections, a child failure forces a quorum restart"),
    Scenario(
        "membership",
        lambda sched, mutant: _build_membership(sched, mutant),
        14,
        "3-host fleet + one joining host; child failures, a peer "
        "crash and trainer snapshots drive admission / eviction / "
        "quorum-pick bumps"),
    Scenario(
        "partition",
        lambda sched, mutant: PartitionWorld(sched, mutant),
        10,
        "already-split fleet: a deposed term-1 incumbent still steers "
        "two hosts while host 1 runs under its term-2 successor; "
        "stale routes probe the member term fence"),
    Scenario(
        "hotswap",
        lambda sched, mutant: HotSwapWorld(sched, mutant),
        10,
        "trainer pushes, watcher polls, operator rollbacks and ring "
        "rounds interleave against the real GenerationLedger"),
    Scenario(
        "fleet",
        lambda sched, mutant: FleetWorld(sched, mutant),
        10,
        "3-replica serving fleet: beacons beat / drain / crash to "
        "silence while the real RouterCore polls (lossy listing, torn "
        "reads) and routes; drain discipline + TTL sweep"),
)}


def _build_membership(sched: Scheduler,
                      mutant: Optional[str]) -> ClusterWorld:
    world = ClusterWorld(sched, mutant, hosts=3, floor=3,
                         join_host=True, crashes=("2",),
                         fails=("1", "2"), trains=2)
    now = world.clock.time()
    world.add_snap("snap_001.pickle", epoch=1, mtime=now - 100.0)
    world.add_snap("snap_002.pickle", epoch=2, mtime=now - 50.0,
                   hosts=("0",))
    # the rotted pair: two hosts hold the same corrupt local copy of a
    # NEWER snapshot whose bytes no longer match its sidecar claim —
    # honest reports re-hash and exclude it; the unverified_votes
    # mutant lets its claimed digest reach quorum
    world.add_snap("snap_009.pickle", epoch=9, mtime=now - 5.0,
                   rotted=True, on_mirror=False, hosts=("1", "2"))
    # the fleet is running FROM snap_002 (the boot pick): picks below
    # epoch 2 are a rollback
    world.boot_coord.snapshot = "snap_002.pickle"
    world.max_picked_epoch = 2
    return world


#: seeded mutants: one per invariant, each a deliberate protocol bug
#: the checker must catch (tests pair every entry with a clean run)
MUTANTS: Dict[str, Dict[str, Any]] = {
    "no_term_fence": {
        "scenario": "partition", "invariant": 1,
        "rule": "mc-term-fence",
        "explore": {"budget": 400, "max_faults": 2},
        "description": "directive term fence dropped — a stale "
                       "coordinator's directive is executed"},
    "double_coordinator": {
        "scenario": "election", "invariant": 2,
        "rule": "mc-single-coordinator",
        "explore": {"budget": 400, "max_faults": 0},
        "description": "election plane rots (deaf adoption, solipsist "
                       "liveness, saturating term counter) — two "
                       "coordinators bind the same term"},
    "oldest_pick": {
        "scenario": "membership", "invariant": 3,
        "rule": "mc-generation-rollback",
        "explore": {"budget": 600, "max_faults": 0},
        "description": "restart pick sorts the wrong way — the fleet "
                       "resumes from the OLDEST snapshot"},
    "all_writers": {
        "scenario": "membership", "invariant": 4,
        "rule": "mc-single-writer",
        "explore": {"budget": 600, "max_faults": 0},
        "description": "single-writer dry-run pin dropped — every "
                       "host spawns as the snapshot writer"},
    "unverified_votes": {
        "scenario": "membership", "invariant": 5,
        "rule": "mc-verified-pick",
        "explore": {"budget": 400, "max_faults": 0},
        "description": "local snapshot reports skip the sidecar "
                       "re-hash — a rotted copy's claim reaches "
                       "quorum"},
    "split_commit": {
        "scenario": "hotswap", "invariant": 6,
        "rule": "mc-atomic-commit",
        "explore": {"budget": 400, "max_faults": 0},
        "description": "swap commit torn in two — params flip before "
                       "the generation label lands"},
    "no_rollback_pin": {
        "scenario": "hotswap", "invariant": 7,
        "rule": "mc-rollback-pin",
        "explore": {"budget": 400, "max_faults": 0},
        "description": "rollback forgets the pin — the watcher "
                       "re-applies the rolled-back digest"},
    "no_floor_stop": {
        "scenario": "election", "invariant": 8,
        "rule": "mc-floor-failstop",
        "explore": {"budget": 400, "max_faults": 0},
        "description": "promotion-path floor guard removed — a "
                       "sub-floor fleet resumes instead of "
                       "fail-stopping"},
    "no_beacon_term": {
        "scenario": "partition", "invariant": 2,
        "rule": "mc-single-coordinator",
        "explore": {"budget": 500, "max_faults": 2},
        "description": "beacon-term claim fence reverted — a "
                       "candidate with lossy announcement reads "
                       "double-binds a live term (regression witness "
                       "for the shipped fix)"},
    "no_writer_repin": {
        "scenario": "partition", "invariant": 4,
        "rule": "mc-single-writer",
        "explore": {"budget": 800, "max_faults": 2},
        "description": "writer re-pin reverted — a re-homed "
                       "ex-coordinator host and the successor's host "
                       "both write one generation (regression witness "
                       "for the shipped fix)"},
    "route_to_drained": {
        "scenario": "fleet", "invariant": 9,
        "rule": "mc-no-route-to-drained",
        "explore": {"budget": 600, "max_faults": 0},
        "description": "router drain awareness dropped — a replica "
                       "the router saw deregister keeps receiving "
                       "routed requests"},
}


# -- the explorer -------------------------------------------------------------

@dataclass
class ExploreResult:
    scenario: str
    mutant: Optional[str]
    seed: int
    schedules: int = 0
    pruned: int = 0
    exhausted: bool = False
    violations: List[Dict[str, Any]] = field(default_factory=list)

    def as_dict(self) -> Dict[str, Any]:
        return {"scenario": self.scenario, "mutant": self.mutant,
                "seed": self.seed, "schedules": self.schedules,
                "pruned": self.pruned, "exhausted": self.exhausted,
                "violations": self.violations}


def _run_schedule(scenario: str, prefix: Sequence[Tuple[str, int]],
                  seed: int, mutant: Optional[str], max_actions: int,
                  max_faults: int
                  ) -> Tuple[Scheduler, Optional[Violation]]:
    sched = Scheduler(prefix=prefix, max_faults=max_faults)
    random.seed(seed)           # pins the backoff jitter per run
    violation: Optional[Violation] = None
    try:
        world = SCENARIOS[scenario].build(sched, mutant)
        world.run(max_actions)
    except Violation as v:
        violation = v
        violation.events = world.events[-40:]
    return sched, violation


def _counterexample(scenario: str, mutant: Optional[str], seed: int,
                    max_actions: int, max_faults: int, sched: Scheduler,
                    violation: Violation) -> Dict[str, Any]:
    return {
        "scenario": scenario, "mutant": mutant, "seed": seed,
        "max_actions": max_actions, "max_faults": max_faults,
        "rule": violation.rule, "invariant": violation.invariant,
        "message": violation.message,
        "schedule": [[label, idx, opt]
                     for (label, idx, _n, opt, _fp) in sched.trace],
        "events": violation.events,
    }


def explore(scenario: str, *, budget: int = 500, seed: int = 0,
            mutant: Optional[str] = None,
            max_actions: Optional[int] = None, max_faults: int = 2,
            stop_on_violation: bool = True) -> ExploreResult:
    """DFS over the scenario's choice tree: run the all-defaults
    schedule, enumerate every unexplored sibling of every choice point,
    and keep replaying prefixes until the budget or the tree runs out.
    State-fingerprint convergence pruning skips a pending action whose
    (state, action) pair another schedule already explored."""
    if max_actions is None:
        max_actions = SCENARIOS[scenario].max_actions
    result = ExploreResult(scenario=scenario, mutant=mutant, seed=seed)
    prev_disable = logging.root.manager.disable
    logging.disable(logging.CRITICAL)
    try:
        stack: List[tuple] = [()]
        visited: set = set()
        while stack and result.schedules < budget:
            prefix = stack.pop()
            sched, violation = _run_schedule(
                scenario, prefix, seed, mutant, max_actions, max_faults)
            result.schedules += 1
            if violation is not None:
                result.violations.append(_counterexample(
                    scenario, mutant, seed, max_actions, max_faults,
                    sched, violation))
                if stop_on_violation:
                    return result
            for p in range(len(prefix), len(sched.trace)):
                label, _idx, arity, _opt, fp = sched.trace[p]
                base = tuple((t[0], t[1]) for t in sched.trace[:p])
                for alt in range(arity - 1, 0, -1):
                    if fp is not None:
                        key = (fp, label, alt)
                        if key in visited:
                            result.pruned += 1
                            continue
                        visited.add(key)
                    stack.append(base + ((label, alt),))
        result.exhausted = not stack
        return result
    finally:
        logging.disable(prev_disable)


def replay(counterexample: Dict[str, Any]) -> Optional[Violation]:
    """Re-run one recorded schedule; returns the reproduced Violation
    (None if the run is clean — e.g. the bug it witnessed was fixed)."""
    prefix = [(c[0], int(c[1]))
              for c in counterexample.get("schedule", ())]
    prev_disable = logging.root.manager.disable
    logging.disable(logging.CRITICAL)
    try:
        _sched, violation = _run_schedule(
            counterexample["scenario"], prefix,
            int(counterexample.get("seed", 0)),
            counterexample.get("mutant"),
            int(counterexample.get("max_actions", 14)),
            int(counterexample.get("max_faults", 2)))
        return violation
    finally:
        logging.disable(prev_disable)


def findings_from(results: Sequence[ExploreResult]) -> List[Finding]:
    out: List[Finding] = []
    for res in results:
        for cx in res.violations:
            unit = f"modelcheck:{cx['scenario']}" + (
                f"+{cx['mutant']}" if cx.get("mutant") else "")
            out.append(Finding(
                rule=cx["rule"], severity=SEV_ERROR, unit=unit,
                message=cx["message"],
                site=f"schedule[{len(cx['schedule'])} choices, "
                     f"seed {cx['seed']}]"))
    return out


def check_tree(budget_per_scenario: int = 300, seed: int = 0,
               max_faults: int = 2,
               scenarios: Optional[Sequence[str]] = None
               ) -> Tuple[List[Finding], List[ExploreResult]]:
    """The shipped-tree sweep every CI/verify entry point runs: explore
    every scenario with no mutant; any finding is a protocol bug (or a
    checker bug — both block)."""
    results = [explore(name, budget=budget_per_scenario, seed=seed,
                       max_faults=max_faults, stop_on_violation=False)
               for name in (scenarios or SCENARIOS)]
    return findings_from(results), results


def quick_check(budget_per_scenario: int = 40,
                seed: int = 0) -> Tuple[List[Finding], Dict[str, Any]]:
    """The `--verify-workflow` section: a small fixed-budget sweep over
    every scenario (seconds, deterministic)."""
    findings, results = check_tree(
        budget_per_scenario=budget_per_scenario, seed=seed)
    stats = {
        "schedules": sum(r.schedules for r in results),
        "pruned": sum(r.pruned for r in results),
        "scenarios": {r.scenario: r.schedules for r in results},
    }
    return findings, stats
