"""Jaxpr auditor (analysis pass 2 of 3): audit the fused train step by
ABSTRACT tracing — `jax.make_jaxpr` over the unjitted step callable — so
every property checks on CPU in CI with no compile and no devices.

Rules (docs/ANALYSIS.md):

- `f64-promotion` (error): an op in the traced step produces float64 —
  a weak-type leak above the configured compute dtype that doubles HBM
  traffic and silently de-optimizes the whole chain;
- `precision-above-compute` (warn): matmul/conv ops run in float32 while
  the step is configured for a sub-f32 compute dtype (bf16/f16) — the
  MXU-feeding flops are not actually in the cheap dtype;
- `host-sync` (error): a callback/infeed/outfeed primitive inside the
  hot step (jax.debug.print, pure_callback, ...) forces a host
  round-trip per dispatch;
- `donation-dropped` (error): the step donates its input state, but a
  buffer shaped like a donated state leaf is ALSO captured as a trace
  constant (e.g. a unit reading `self.weights` instead of the `params`
  argument) — XLA keeps the constant copy alive and the donation is
  silently worthless;
- `large-trace-constant` (warn): a large array rides the jaxpr as a
  closure constant — it is re-hashed on every trace and duplicated in
  every executable;
- `retrace-hazard` (warn): the carried state contains Python scalars —
  each step's new value becomes a fresh trace constant, recompiling the
  step every call;
- `sharding-mismatch` (error): a param PartitionSpec names a mesh axis
  that does not exist or shards a dimension the axis size does not
  divide — the exact drift class the PR-2 `out_shardings` pin fixed.
  Covers OPTIMIZER-STATE specs too: a ZeRO-sharded step's velocity/
  moment plan (parallel.mesh.zero_plan) is checked leaf-by-leaf — the
  flat (padded,) vector must be divisible by the data axis, split into
  equal local slices, and must not drop elements of the leaf it encodes.
  Since ISSUE 13 it also covers the FUSED PAIR's traced step: a
  selected cross-op fusion winner (lrn_maxpool) claims an adjacent unit
  pair, and the fused kernel's geometry must equal what the claimed
  pass-through unit declared at initialize time (`_fusion_findings`);
- `pre-vma-numerics` (warn): the structured form of
  `_compat.warn_pre_vma_numerics` — GPipe / seq×TP builds on pre-vma
  jax have ~1e-3 trained-loss deviation;
- `nonfinite-guard-off` (warn): the run is configured without the
  non-finite loss guard, so the supervisor's snapshot rollback
  (exit 81) can never trigger on divergence.

Entry points: `audit_fused_step(step, x, y)` for a built
FusedTrainStep / PipelineTrainStep, `audit_workflow(workflow)` to derive
shapes from the workflow's loader, `environment_findings(...)` for the
import-cheap checks the supervisor embeds in its exit report.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import numpy as np

from veles_tpu.analysis.findings import SEV_ERROR, SEV_WARN, Finding

#: substrings of primitive names that force a host round-trip per step
_HOST_SYNC_MARKERS = ("callback", "infeed", "outfeed")

#: primitives whose flops dominate — the ones `precision-above-compute`
#: watches when a sub-f32 compute dtype is configured
_MATMUL_PRIMS = ("dot_general", "conv_general_dilated")

#: consts at least this many elements trigger `large-trace-constant`
LARGE_CONST_ELEMS = 1 << 18

#: consts smaller than this are ignored by the donation check (iota
#: tables, one-hot templates — too small to matter, too common to flag)
_DONATION_MIN_ELEMS = 32


# -- jaxpr walking ------------------------------------------------------------

def _sub_jaxprs(params):
    from jax.core import ClosedJaxpr, Jaxpr
    for v in params.values():
        vs = v if isinstance(v, (list, tuple)) else (v,)
        for x in vs:
            if isinstance(x, ClosedJaxpr):
                yield x.jaxpr
            elif isinstance(x, Jaxpr):
                yield x


def iter_eqns(jaxpr):
    """All equations of `jaxpr` including nested sub-jaxprs (scan/cond/
    pjit bodies), each visited once."""
    stack, seen = [jaxpr], set()
    while stack:
        j = stack.pop()
        if id(j) in seen:
            continue
        seen.add(id(j))
        for eqn in j.eqns:
            yield eqn
            stack.extend(_sub_jaxprs(eqn.params))


# -- individual checks --------------------------------------------------------

def _dtype_findings(closed, compute_dtype) -> List[Finding]:
    out: List[Finding] = []
    f64_prims: dict = {}
    f32_matmuls = 0
    cd = np.dtype(compute_dtype) if compute_dtype is not None \
        else np.dtype(np.float32)
    for eqn in iter_eqns(closed.jaxpr):
        name = eqn.primitive.name
        for var in eqn.outvars:
            dt = getattr(var.aval, "dtype", None)
            if dt is None:
                continue
            if dt == np.float64:
                f64_prims[name] = f64_prims.get(name, 0) + 1
            elif (cd.itemsize < 4 and dt == np.float32
                    and name in _MATMUL_PRIMS):
                f32_matmuls += 1
    for name, count in sorted(f64_prims.items()):
        out.append(Finding(
            "f64-promotion", SEV_ERROR, name,
            f"{count} op(s) produce float64 above the configured "
            f"compute dtype {cd.name}: a weak-type promotion leak "
            "(2x HBM traffic, no MXU path)"))
    if f32_matmuls:
        out.append(Finding(
            "precision-above-compute", SEV_WARN, "dot/conv",
            f"{f32_matmuls} matmul/conv op(s) run in float32 while the "
            f"step is configured for {cd.name}: the dominant flops are "
            "not in the cheap dtype"))
    return out


def _host_sync_findings(closed) -> List[Finding]:
    hits: dict = {}
    for eqn in iter_eqns(closed.jaxpr):
        name = eqn.primitive.name
        if any(m in name for m in _HOST_SYNC_MARKERS):
            hits[name] = hits.get(name, 0) + 1
    return [Finding(
        "host-sync", SEV_ERROR, name,
        f"{count} {name} op(s) in the hot step force a host round-trip "
        "per dispatch (debug_print/pure_callback do not belong in the "
        "train step)") for name, count in sorted(hits.items())]


def _const_findings(closed, state, donate: bool) -> List[Finding]:
    out: List[Finding] = []
    leaves = []
    try:
        import jax
        leaves = jax.tree_util.tree_leaves(state)
    except Exception:   # noqa: BLE001
        pass
    leaf_sigs = {(np.shape(a), np.dtype(getattr(a, "dtype", "f4")).name)
                 for a in leaves if np.ndim(a) >= 1}
    for c in closed.consts:
        shape = np.shape(c)
        if len(shape) < 1 or int(np.prod(shape)) < _DONATION_MIN_ELEMS:
            continue
        dt = np.dtype(getattr(c, "dtype", np.asarray(c).dtype)).name
        site = f"const {dt}{list(shape)}"
        identical = any(c is a for a in leaves)
        if donate and (identical or (shape, dt) in leaf_sigs):
            out.append(Finding(
                "donation-dropped", SEV_ERROR, site,
                "a buffer shaped like a donated state leaf is captured "
                "as a trace constant (a unit reading its own Array "
                "instead of the params argument?): XLA keeps the "
                "constant copy alive and the donation is silently "
                "dropped"))
        elif int(np.prod(shape)) >= LARGE_CONST_ELEMS:
            out.append(Finding(
                "large-trace-constant", SEV_WARN, site,
                "a large array rides the jaxpr as a closure constant: "
                "duplicated per executable and re-hashed per trace — "
                "pass it as an argument instead"))
    return out


def _state_findings(state) -> List[Finding]:
    out: List[Finding] = []
    try:
        import jax
        from jax.tree_util import keystr, tree_flatten_with_path
        pairs = [(keystr(kp), v)
                 for kp, v in tree_flatten_with_path(state)[0]]
    except Exception:   # noqa: BLE001
        import jax
        pairs = [("", v) for v in jax.tree_util.tree_leaves(state)]
    for name, v in pairs:
        if isinstance(v, (bool, int, float)):
            out.append(Finding(
                "retrace-hazard", SEV_WARN, f"state{name}",
                f"carried state leaf is a Python {type(v).__name__}: "
                "every new value becomes a fresh trace constant and "
                "recompiles the step (wrap it in jnp.asarray)"))
    return out


def _spec_axes(part) -> Sequence[str]:
    if part is None:
        return ()
    return (part,) if isinstance(part, str) else tuple(part)


def _sharding_findings(step) -> List[Finding]:
    """Check the step's param PartitionSpecs against its mesh — the
    static form of the PR-2 sharding-drift bug class."""
    mesh = getattr(step, "mesh", None)
    mode = getattr(step, "mode", None)
    if mesh is None or mode not in ("gspmd", "dp", "seq"):
        return []
    if mode == "gspmd":
        specs, _ = step._tp_plan()
    elif mode == "dp":
        specs = step._smap_param_specs()
    else:
        specs = step._seq_param_specs()
    out: List[Finding] = []
    for u, spec_d in zip(step.forwards, specs):
        arrs = u.param_arrays()
        for k, spec in spec_d.items():
            shape = tuple(getattr(arrs.get(k), "shape", None) or ())
            site = f"{getattr(u, 'name', u)}.{k} {tuple(spec)!r}"
            for i, part in enumerate(tuple(spec)):
                axes = _spec_axes(part)
                if not axes:
                    continue
                if i >= len(shape):
                    out.append(Finding(
                        "sharding-mismatch", SEV_ERROR, repr(u),
                        f"PartitionSpec for param {k!r} shards dim {i} "
                        f"but the array has rank {len(shape)}", site))
                    continue
                for ax in axes:
                    if ax not in mesh.shape:
                        out.append(Finding(
                            "sharding-mismatch", SEV_ERROR, repr(u),
                            f"PartitionSpec for param {k!r} names mesh "
                            f"axis {ax!r}, which the mesh "
                            f"{dict(mesh.shape)} does not have", site))
                    elif shape[i] % mesh.shape[ax]:
                        out.append(Finding(
                            "sharding-mismatch", SEV_ERROR, repr(u),
                            f"param {k!r} dim {i} ({shape[i]}) is not "
                            f"divisible by mesh axis {ax!r} "
                            f"({mesh.shape[ax]} shards): XLA would "
                            "pad-shard or reject it", site))
    out += _optstate_findings(step, mesh)
    out += _collective_findings(step, mesh)
    return out


def _collective_findings(step, mesh) -> List[Finding]:
    """Link-geometry half of the sharding audit (ISSUE 12): the
    hierarchical grad_reduce variants decompose the data axis into a
    (hosts x local) 2-level factorization. An EXPLICIT local-group
    request (env VELES_GRAD_REDUCE_LOCAL) that does not divide the
    data axis is a config bug — the traced op degrades safely to the
    flat exchange, but the user asked for a two-level decomposition
    that cannot tile, so this pass fails loud pre-flight; a merely
    degenerate geometry (single host) gets a warning, not an error."""
    if not getattr(step, "zero_active", False):
        return []
    import os

    from veles_tpu import _compat
    if _compat.GRAD_TRANSPOSE_PSUM:
        return []
    from veles_tpu.ops import variants as va
    from veles_tpu.parallel.mesh import DATA_AXIS
    name = step._grad_reduce_variant().name
    cfg = va.grad_reduce_config(name) or {}
    if not cfg.get("hier"):
        return []
    n = mesh.shape.get(DATA_AXIS, 1)
    out: List[Finding] = []
    raw = os.environ.get(va.GRAD_REDUCE_LOCAL_ENV)
    site = f"grad_reduce/{name} over {DATA_AXIS!r} ({n} shards)"
    if raw is not None:
        try:
            req = int(raw)
        except ValueError:
            req = 0
        if req < 1 or n % req:
            h, loc = va.grad_reduce_geometry(n)
            out.append(Finding(
                "sharding-mismatch", SEV_ERROR, "grad_reduce",
                f"hierarchical grad_reduce local-group request "
                f"{raw!r} ({va.GRAD_REDUCE_LOCAL_ENV}) does not divide "
                f"the data axis ({n} shards): the requested "
                f"(hosts x local) decomposition cannot tile it, so the "
                f"traced op silently clamps to the largest divisor and "
                f"runs ({h} x {loc}) instead — a DIFFERENT "
                f"decomposition than asked for; fix the override or "
                f"the mesh", site))
            return out
    h, loc = va.grad_reduce_geometry(n)
    if h <= 1 or loc <= 1:
        out.append(Finding(
            "sharding-mismatch", SEV_WARN, "grad_reduce",
            f"hierarchical grad_reduce variant selected but the link "
            f"geometry is single-level (hosts={h}, local={loc}): the "
            f"traced op degrades to the flat exchange here — expected "
            f"on a single host; set {va.GRAD_REDUCE_LOCAL_ENV} to test "
            f"the two-level path on a CPU mesh", site))
    return out


def audit_serving(server) -> List[Finding]:
    """Sharded-serve audit (ISSUE 15): the ring server's forward must
    trace under the TRAINER'S NamedSharding plan — run the
    sharding-mismatch pass over the serving step's param specs/mesh,
    and check the serve plan's ring input spec equals the step's
    data-axis put spec (the same spec DeviceFeed puts training batches
    to) and that the frozen ring shape divides the data axis. Empty
    list = clean; merge-mode servers (the unsharded pre-ring baseline)
    have nothing to audit."""
    from veles_tpu.parallel.mesh import DATA_AXIS
    out: List[Finding] = []
    step = getattr(server, "_step", None)
    plan = getattr(server, "_plan", None)
    if step is None or plan is None:
        return out
    out += _sharding_findings(step)
    mesh = plan["mesh"]
    if mesh is None:
        return out
    want = step.input_put_specs()[0]
    site = f"serve_plan x_spec {tuple(plan['x_spec'])!r}"
    if tuple(plan["x_spec"]) != tuple(want):
        out.append(Finding(
            "sharding-mismatch", SEV_ERROR, "serving",
            f"ring input spec {tuple(plan['x_spec'])} diverges from "
            f"the trainer's data-axis put spec {tuple(want)} "
            f"(input_put_specs — the DeviceFeed rule)", site))
    n = mesh.shape.get(DATA_AXIS, 1)
    slots = server.ring_slots or 0
    if n > 1 and slots % n:
        out.append(Finding(
            "sharding-mismatch", SEV_ERROR, "serving",
            f"ring_slots ({slots}) not divisible by the mesh data axis "
            f"({n} shards): the fixed ring batch cannot lay out under "
            f"the plan", site))
    return out


def _fusion_findings(step) -> List[Finding]:
    """Fused-pair half of the sharding-mismatch audit (ISSUE 13): when a
    selected fusion winner claims an adjacent unit pair, the trailing
    unit becomes a pass-through — so the fused kernel must reproduce
    EXACTLY the geometry that unit declared at initialize time (its
    output Array shape, which every downstream layer sized its params
    against). A post-init reconfiguration (ksize/stride edited on the
    live unit) silently drifts the two apart: the fused trace would feed
    downstream layers a differently-shaped tensor than the one their
    weights were built for. Runs mesh or no mesh — the fusion claim is
    mode-gated inside fusion_pairs() itself."""
    pairs_fn = getattr(step, "fusion_pairs", None)
    if pairs_fn is None:
        return []
    out: List[Finding] = []
    for i, j, v in pairs_fn():
        a, b = step.forwards[i], step.forwards[j]
        if getattr(a, "variant_op", None) != "lrn":
            # conv epilogue: elementwise fold, geometry untouched —
            # the claimed LRN unit's output shape equals its input's
            continue
        in_shape = tuple(getattr(getattr(a, "input", None), "shape",
                                 ()) or ())
        decl = tuple(getattr(getattr(b, "output", None), "shape",
                             ()) or ())
        if len(in_shape) != 4 or len(decl) != 4:
            continue
        from veles_tpu.ops.pallas_kernels import _pool_out_hw
        ky, kx = b.ksize
        sy, sx = b.stride
        oh, ow = _pool_out_hw(in_shape[1], in_shape[2], ky, kx, sy, sx)
        traced = (in_shape[0], oh, ow, in_shape[3])
        site = (f"{getattr(a, 'name', a)}+{getattr(b, 'name', b)} "
                f"-> {v.name}")
        if traced != decl:
            out.append(Finding(
                "sharding-mismatch", SEV_ERROR, repr(b),
                f"fused pair {v.name!r} would trace a "
                f"{traced} output where the claimed pass-through "
                f"pooling unit declared {decl}: the pair's geometry "
                "drifted after initialize (ksize/stride edited on the "
                "live unit?) — downstream layers would consume a "
                "silently different tensor", site))
    return out


def _optstate_findings(step, mesh) -> List[Finding]:
    """Optimizer-state half of the sharding audit: a ZeRO-sharded step
    carries its velocities/Adam moments as flat vectors split over the
    data axis per the update-sharding plan. These checks guard the
    PLAN CACHE (step._zero_plan_cache) — the mutable handoff every
    consumer (specs, init, the traced update, checkpoint geometry)
    reads — against a corrupted/stale entry; a freshly computed plan
    satisfies them by construction, so the independent ledger is the
    LIVE state cross-check in `_optstate_state_findings` (what a
    restore or caller actually handed the step)."""
    if not getattr(step, "zero_active", False):
        return []
    from veles_tpu.parallel.mesh import DATA_AXIS
    n = mesh.shape.get(DATA_AXIS, 1)
    out: List[Finding] = []
    for u, plan in zip(step.forwards, step.zero_plans()):
        for k, lp in plan.items():
            site = (f"{getattr(u, 'name', u)}.vel[{k}] "
                    f"({lp.padded},) over {DATA_AXIS!r}")
            if lp.padded % n:
                out.append(Finding(
                    "sharding-mismatch", SEV_ERROR, repr(u),
                    f"optimizer-state leaf {k!r} plans {lp.padded} "
                    f"elements, not divisible by the data axis "
                    f"({n} shards): the reduce-scatter/all-gather pair "
                    "cannot tile it", site))
            elif lp.local * n != lp.padded:
                out.append(Finding(
                    "sharding-mismatch", SEV_ERROR, repr(u),
                    f"optimizer-state leaf {k!r} plans local slices of "
                    f"{lp.local} x {n} shards != {lp.padded} padded "
                    "elements: shards would overlap or leave gaps",
                    site))
            if lp.padded < lp.size:
                out.append(Finding(
                    "sharding-mismatch", SEV_ERROR, repr(u),
                    f"optimizer-state leaf {k!r} plans only {lp.padded} "
                    f"elements for a {lp.size}-element leaf: the "
                    "update would silently drop the tail", site))
    return out


def _optstate_state_findings(step, state) -> List[Finding]:
    """Cross-check the LIVE optimizer state against the update-sharding
    plan — the independent ledger for the plan checks above: the plan
    is what the step will trace, the state is what `init_state()`, a
    checkpoint restore, or the caller actually handed it. A velocity /
    moment leaf whose stored geometry disagrees with the plan (wrong
    flat length) would dynamic-slice out of bounds or drop tail
    elements at update time."""
    if not getattr(step, "zero_active", False):
        return []
    vel = state.get("vel") if isinstance(state, dict) else None
    if vel is None:
        return []
    from veles_tpu.ops import optim
    out: List[Finding] = []
    cfgs = getattr(step, "cfgs", None) or [None] * len(step.forwards)
    for u, plan, v, cfg in zip(step.forwards, step.zero_plans(), vel,
                               cfgs):
        if isinstance(cfg, optim.AdamConfig):
            groups = (("m", v.get("m", {})), ("v", v.get("v", {})))
        else:
            groups = (("", v),)
        for gname, leaves in groups:
            if not isinstance(leaves, dict):
                continue
            for k, lp in plan.items():
                leaf = leaves.get(k)
                if leaf is None:
                    continue
                shape = tuple(np.shape(leaf))
                label = f"{gname}.{k}" if gname else k
                if shape != (lp.padded,):
                    out.append(Finding(
                        "sharding-mismatch", SEV_ERROR, repr(u),
                        f"optimizer-state leaf {label!r} carries shape "
                        f"{shape}, but the update-sharding plan slices "
                        f"a ({lp.padded},) flat vector (leaf "
                        f"{lp.shape}, {lp.size} elements): the state "
                        "does not match the plan it will be updated "
                        "under",
                        f"{getattr(u, 'name', u)}.vel[{label}]"))
    out += _ef_state_findings(step, state)
    return out


def _ef_state_findings(step, state) -> List[Finding]:
    """Error-feedback-slot half of the live-state cross-check (ISSUE
    12): a stateful (int8+EF) grad_reduce variant carries one flat
    residual vector per param leaf, sized by the variant's rule
    (ops.variants.grad_reduce_resid_len x data-axis shards). A residual
    whose stored length disagrees — e.g. a checkpoint hand-carried
    across a (hosts x local) geometry change — would be reshaped onto
    the WRONG elements and compensate them forever: mis-sharded, the
    exact failure the reshard path's drop rule exists to prevent."""
    if not getattr(step, "ef_active", lambda: False)():
        return []
    from veles_tpu.parallel.mesh import DATA_AXIS
    n = step.mesh.shape.get(DATA_AXIS, 1)
    ef = state.get("ef") if isinstance(state, dict) else None
    out: List[Finding] = []
    if ef is None:
        out.append(Finding(
            "sharding-mismatch", SEV_ERROR, "grad_reduce",
            "the selected grad_reduce variant is stateful (error "
            "feedback) but the state carries no 'ef' slot: the traced "
            "update would have no residual to thread (rebuild the "
            "state via init_state()/restore_state())", "state[ef]"))
        return out
    for u, lens, layer in zip(step.forwards, step.ef_lens(), ef):
        if not isinstance(layer, dict):
            continue
        for k, rl in lens.items():
            leaf = layer.get(k)
            if leaf is None:
                continue
            shape = tuple(np.shape(leaf))
            if shape != (n * rl,):
                out.append(Finding(
                    "sharding-mismatch", SEV_ERROR, repr(u),
                    f"error-feedback residual {k!r} carries shape "
                    f"{shape}, but the selected grad_reduce variant "
                    f"slices ({n * rl},) ({n} shards x {rl} per-shard "
                    f"elements): a mis-sized residual would compensate "
                    f"the wrong gradient elements",
                    f"{getattr(u, 'name', u)}.ef[{k}]"))
    return out


# -- entry points -------------------------------------------------------------

def audit_fused_step(step, x, y, w=None, state=None,
                     nonfinite_guard: Optional[bool] = None
                     ) -> List[Finding]:
    """Audit a built FusedTrainStep (any mode) or PipelineTrainStep by
    tracing its unjitted train callable over the given minibatch. `x`/`y`
    are host arrays with the real shapes (values are irrelevant); `state`
    defaults to `step.init_state()`. No compile happens — `make_jaxpr`
    only traces."""
    import jax

    from veles_tpu import _compat
    from veles_tpu.parallel.mesh import MODEL_AXIS

    findings: List[Finding] = []
    sharding = _sharding_findings(step)
    sharding += _fusion_findings(step)   # fused-pair geometry (any mode)
    findings += sharding
    if any(f.severity == SEV_ERROR for f in sharding):
        # a broken partition plan (or a drifted fused-pair geometry):
        # building state / tracing would crash on the very defect just
        # reported — stop at the static verdict
        return findings
    mesh = getattr(step, "mesh", None)
    is_pipeline = hasattr(step, "_microbatch")
    if not _compat.GRAD_TRANSPOSE_PSUM:
        if is_pipeline:
            findings.append(_pre_vma_finding("GPipe pipeline step"))
        elif (getattr(step, "mode", None) == "seq" and mesh is not None
                and mesh.shape.get(MODEL_AXIS, 1) > 1):
            findings.append(_pre_vma_finding("seq x TP (3-axis) "
                                             "fused step"))
    if nonfinite_guard is not None and not nonfinite_guard:
        findings.append(_guard_off_finding())

    if state is None:
        state = step.init_state()
    findings += _state_findings(state)
    optstate = _optstate_state_findings(step, state)
    findings += optstate
    if any(f.severity == SEV_ERROR for f in optstate):
        # state geometry disagrees with the plan the trace would slice
        # under — tracing would crash on (or worse, silently mask) the
        # defect just reported
        return findings

    x = np.asarray(x)
    y = np.asarray(y)
    if w is None:
        w = np.ones(np.shape(x)[0], np.float32)
    if is_pipeline:
        xs, yb, wb = step._microbatch(x, y, w)
        args = (state, step._gid, xs, yb, wb)
    else:
        xb, yb = step._seq_xy(x, y)
        args = (state, xb, yb,
                step._weights_or_ones(np.asarray(w, np.float32),
                                      np.shape(x)[0]))
    closed = jax.make_jaxpr(step.train_callable())(*args)
    findings += _dtype_findings(closed, getattr(step, "compute_dtype",
                                                None))
    findings += _host_sync_findings(closed)
    findings += _const_findings(closed, state,
                                bool(getattr(step, "donate", False)))
    return findings


def audit_workflow(workflow, step=None,
                   nonfinite_guard: Optional[bool] = None,
                   **step_kwargs) -> List[Finding]:
    """Build (or take) a fused step for `workflow` and audit it with the
    loader's real minibatch shapes. Initializes the workflow on the
    default backend when needed (host-side allocation only)."""
    if not workflow.is_initialized:
        workflow.initialize(device=None, verify="off")
    if step is None:
        step = workflow.build_fused_step(**step_kwargs)
    loader = workflow.loader
    x = np.asarray(loader.minibatch_data.mem)
    y = np.asarray(loader.minibatch_labels.mem)
    w = loader.minibatch_valid.mem
    w = (np.asarray(w, np.float32) if w is not None
         else np.ones(x.shape[0], np.float32))
    return audit_fused_step(step, x, y, w=w,
                            nonfinite_guard=nonfinite_guard)


# -- environment findings (supervisor exit report, --verify-workflow) ---------

def _pre_vma_finding(context: str) -> Finding:
    from veles_tpu._compat import _jax_version
    return Finding(
        "pre-vma-numerics", SEV_WARN, context,
        f"built on pre-vma jax {_jax_version()}: trained numerics may "
        "deviate ~1e-3 relative from the single-device trajectory "
        "(grad-transpose psum semantics); a jax upgrade clears it")


def _guard_off_finding() -> Finding:
    return Finding(
        "nonfinite-guard-off", SEV_WARN, "training loop",
        "running without --nonfinite-guard: a NaN/inf loss trains on "
        "and the supervisor's snapshot rollback (exit 81) never "
        "triggers")


def _flag_value(argv: Sequence[str], flag: str) -> Optional[str]:
    for i, a in enumerate(argv):
        if a == flag and i + 1 < len(argv):
            return argv[i + 1]
        if a.startswith(flag + "="):
            return a.split("=", 1)[1]
    return None


def environment_findings(argv: Optional[Sequence[str]] = None,
                         pp: Optional[int] = None,
                         tp: Optional[int] = None,
                         sp: Optional[int] = None,
                         nonfinite_guard: Optional[bool] = None
                         ) -> List[Finding]:
    """Config-level findings derivable WITHOUT building a step: the
    pre-vma numerics hazard for GPipe / seq×TP configurations and the
    disabled non-finite guard. Accepts either explicit flag values or a
    child argv to parse them from (the supervisor passes its child
    command line)."""
    argv = list(argv or ())

    def parsed(flag: str) -> Optional[int]:
        raw = _flag_value(argv, flag)
        if raw is None:
            return None
        try:
            return int(raw)
        except ValueError:
            return 1    # present but unparsable: treat as enabled

    if argv:
        if pp is None:
            pp = parsed("--pp")
        if tp is None:
            tp = parsed("--tp")
        if sp is None:
            sp = parsed("--sp")
        if nonfinite_guard is None:
            nonfinite_guard = ("--nonfinite-guard" in argv
                               or "--debug-nans" in argv)
    out: List[Finding] = []
    from veles_tpu import _compat
    if not _compat.GRAD_TRANSPOSE_PSUM:
        if pp:
            out.append(_pre_vma_finding("GPipe pipeline step"))
        if (sp or 1) > 1 and (tp or 1) > 1:
            out.append(_pre_vma_finding("seq x TP (3-axis) fused step"))
        for context in sorted(_compat._WARNED):
            if not any(f.unit == context for f in out):
                out.append(_pre_vma_finding(context))
    if nonfinite_guard is not None and not nonfinite_guard:
        out.append(_guard_off_finding())
    return out
