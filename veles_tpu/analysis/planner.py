"""Analysis pass 7 — the whole-system performance planner.

Every earlier perf PR shipped one fragment of a step-time model:
per-op cost shares (tools/layer_profile.py -> LAYER_PROFILE.json,
PR 8), a collective byte model keyed by the searched `wire[dt,blk,ef,
hier]` family (ops/variants.grad_reduce_bytes, PR 11), ring/TP/DP
analytic cost functions (parallel/scaling_model.py, PR 12), measured
fusion gains (FUSION_AB_RECORD.json, PR 13), and static VMEM/HBM
ledgers (analysis/resources.py, PR 14). This module fuses them into
ONE analytical model of the fused train step and puts a budgeted
configuration search on top:

    predicted step time = compute roofline + exposed collective time
                          (+ exposed feed time, normally hidden)

- **compute**: `train_flops_per_sample * batch / (peak * MFU(batch))`
  where MFU(b) is a saturating curve `MFU_MAX * b / (b + B_HALF)`
  calibrated on the committed r4 on-chip batch sweep (MEASURED.json;
  see docs/PLANNER.md for the fit and its error). Fusion claims scale
  the whole-step time by the measured fused/composed ratio from
  FUSION_AB_RECORD.json when the record's device kind matches.
- **comms**: ZeRO-on steps pay the reduce-scatter + param all-gather
  legs of the PR-11 wire byte model, each leg riding its own link
  class (scaling_model.wire_collective_time_s); ZeRO-off steps pay
  the classic per-axis ring all-reduce of the full f32 gradient
  (scaling_model.allreduce_time_s), which is where the mesh SHAPE
  enters the ranking.
- **feed**: modeled hidden by default (the PR-5 device-feed overlap
  measured ~1.0); set VELES_PLAN_FEED_BW (bytes/s) to expose the
  remainder `max(0, feed_bytes/bw - (compute+comms))`.
- **memory gate**: every candidate is pre-flighted through the PR-14
  ledgers BEFORE it can be ranked or timed — an `hbm-over-limit`
  or VMEM-over-budget finding refuses the config with the ledger's
  own message (the generate-then-gate discipline: no candidate is
  timed without passing the static feasibility gate).

`plan_search()` is the PR-8 budgeted-search machinery one level up:
the hand-set defaults are the incumbent, the model-evaluation budget
is split across config axes by fixed weights through
`autotune.allocate_budget`, coordinate descent walks one axis at a
time from the incumbent, and any remaining budget is spent on a
deterministic sweep of the untried cross product. An optional `timer`
callback measures the model's top-k (incumbent always included, so
the measured winner can never lose to the defaults silently).

Import discipline: importing this module must never initialize a jax
backend — tools/plan.py proves it per run (`jax_backends=0` on the
compact line) and tests/test_planner.py pins it. Keep device/compile
work out of module scope and out of every pure-model entry point.
"""

from __future__ import annotations

import json
import math
import os
from dataclasses import asdict, dataclass, field, replace
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from veles_tpu.analysis import resources
from veles_tpu.analysis.findings import SEV_ERROR, Finding
from veles_tpu.ops import autotune as _autotune
from veles_tpu.ops import variants as _variants
from veles_tpu.parallel import scaling_model

# --------------------------------------------------------------------
# device constants
# --------------------------------------------------------------------

#: dense bf16 peak FLOP/s by device kind (bench.py PEAK_TFLOPS)
DEVICE_PEAK_FLOPS: Dict[str, float] = {
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}

#: per-device HBM by kind (public specs); VELES_HBM_LIMIT overrides
DEVICE_HBM_BYTES: Dict[str, int] = {
    "TPU v5 lite": 16 << 30,
    "TPU v5e": 16 << 30,
    "TPU v4": 32 << 30,
    "TPU v6 lite": 32 << 30,
    "TPU v6e": 32 << 30,
}

#: MFU(b) = MFU_MAX * b / (b + B_HALF), exact fit through the r4
#: on-chip sweep endpoints (MEASURED.json batch_sweep: 0.4745 @ 512,
#: 0.5244 @ 2048; the interior point 1024 lands within 1.7%). The fit
#: is per-device-kind in principle; only the v5e family has a
#: committed sweep, so predictions elsewhere carry calibrated=False.
MFU_MAX = 0.543448
MFU_B_HALF = 74.397

#: kinds whose MFU curve is backed by a committed measured sweep
CALIBRATED_KINDS = frozenset({"TPU v5 lite", "TPU v5e"})

#: the fused lrn+maxpool search point the planner's `fusion="fused"`
#: arm claims (the FUSION_AB_RECORD.json point; its VMEM footprint is
#: the fused arm's gate input)
FUSED_LRN_POOL_POINT = "fused[rt=2,io=native,fuse=1]"

#: bytes of one feed sample beyond the f32 image: int32 label + f32
#: sample weight (loader minibatch_labels + minibatch_valid)
LABEL_BYTES = 8

PLAN_SCHEMA = "veles-plan"


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


# --------------------------------------------------------------------
# model geometry: pure arithmetic over the declarative layer list
# --------------------------------------------------------------------

@dataclass
class StepGeometry:
    """Everything the model needs to know about one workflow, derived
    arithmetically from its declarative layer list — no tracing, no
    arrays, no devices."""

    n_params: int
    fwd_flops_per_sample: float
    train_flops_per_sample: float
    per_op_fwd_flops: Dict[str, float]
    #: (c, h, w) activation shapes at every LRN site — the VMEM gate
    #: input for the fused lrn+maxpool claim
    lrn_sites: List[Dict[str, int]] = field(default_factory=list)
    input_hw: int = 227
    input_channels: int = 3
    name: str = "model"

    def sample_bytes(self) -> int:
        """Host->device bytes of one feed sample (f32 image + label
        + weight)."""
        return self.input_hw * self.input_hw * self.input_channels * 4 \
            + LABEL_BYTES


def model_geometry(layers: Sequence[Dict[str, Any]], *,
                   input_hw: int = 227, input_channels: int = 3,
                   name: str = "model") -> StepGeometry:
    """Walk a Znicz declarative layer list, tracking the activation
    grid (h, w, c) and accumulating params + forward MACs per op
    class. conv/fc MACs count the MXU work (2 FLOPs each); LRN /
    pool / dropout are bandwidth-bound and carry zero MACs — their
    cost lives in the measured MFU curve, their fusion upside in the
    measured fusion gain."""
    h = w = int(input_hw)
    c = int(input_channels)
    params = 0
    macs: Dict[str, float] = {}
    lrn_sites: List[Dict[str, int]] = []
    saw_conv = False
    for layer in layers:
        kind = layer["type"]
        if kind.startswith("conv"):
            kx, ky = int(layer["kx"]), int(layer["ky"])
            sx, sy = (int(v) for v in layer.get("stride", (1, 1)))
            px, py = (int(v) for v in layer.get("padding", (0, 0)))
            nk = int(layer["n_kernels"])
            oh = (h + 2 * py - ky) // sy + 1
            ow = (w + 2 * px - kx) // sx + 1
            op = "conv_stem" if not saw_conv else "conv"
            saw_conv = True
            macs[op] = macs.get(op, 0.0) + float(oh * ow) * kx * ky * c * nk
            params += kx * ky * c * nk + nk
            h, w, c = oh, ow, nk
        elif kind == "norm":
            lrn_sites.append({"c": c, "h": h, "w": w})
            macs.setdefault("lrn", 0.0)
        elif kind == "max_pooling":
            kx, ky = (int(v) for v in layer["ksize"])
            sx, sy = (int(v) for v in layer["stride"])
            h = (h - ky) // sy + 1
            w = (w - kx) // sx + 1
            macs.setdefault("maxpool", 0.0)
        elif kind in ("all2all", "all2all_strictrelu", "all2all_tanh",
                      "softmax"):
            n_in = h * w * c if h else c
            n_out = int(layer["output_sample_shape"])
            op = "softmax" if kind == "softmax" else "matmul"
            macs[op] = macs.get(op, 0.0) + float(n_in) * n_out
            params += n_in * n_out + n_out
            h = w = 0
            c = n_out
        elif kind == "dropout":
            macs.setdefault("dropout", 0.0)
        # activation-only / unknown layers carry no params and no MACs
    fwd = 2.0 * sum(macs.values())          # MAC -> FLOP
    per_op = {op: 2.0 * m for op, m in macs.items()}
    return StepGeometry(
        n_params=params,
        fwd_flops_per_sample=fwd,
        train_flops_per_sample=3.0 * fwd,   # fwd + ~2x bwd
        per_op_fwd_flops=per_op,
        lrn_sites=lrn_sites,
        input_hw=int(input_hw),
        input_channels=int(input_channels),
        name=name,
    )


def alexnet_geometry(*, n_classes: int = 1000, width_mult: float = 1.0,
                     fc_width: int = 4096,
                     input_hw: int = 227) -> StepGeometry:
    """The flagship's geometry from its own declarative layer list —
    the single source of truth samples/alexnet.py builds units from.
    Import kept local: samples pulls the Znicz stack, which this
    module must not cost at import."""
    from veles_tpu.samples.alexnet import alexnet_layers
    layers = alexnet_layers(n_classes=n_classes, width_mult=width_mult,
                            fc_width=fc_width)
    return model_geometry(layers, input_hw=input_hw, name="alexnet")


# --------------------------------------------------------------------
# compute leg
# --------------------------------------------------------------------

def mfu_model(batch_per_chip: float, *, mfu_max: float = MFU_MAX,
              b_half: float = MFU_B_HALF) -> float:
    """Saturating MFU-vs-per-chip-batch curve (r4 sweep fit)."""
    b = float(batch_per_chip)
    return mfu_max * b / (b + b_half)


def fusion_gain(device_kind: str,
                record_path: str = "FUSION_AB_RECORD.json"
                ) -> Tuple[float, str]:
    """Whole-step fused/composed speedup claimed by the committed
    PR-13 A/B record, applied only when the record was measured on
    the SAME device kind (the CPU-interpret record must not predict
    chip behavior). Returns (gain, provenance)."""
    try:
        with open(record_path) as fh:
            rec = json.load(fh)
        if rec.get("device_kind") == device_kind:
            comp = float(rec["arms"]["composed"]["samples_per_sec"])
            fused = float(rec["arms"]["fused"]["samples_per_sec"])
            if comp > 0 and fused > 0:
                return fused / comp, record_path
    except (OSError, ValueError, KeyError, TypeError):
        pass
    return 1.0, "none (no matching measured record; neutral gain 1.0)"


# --------------------------------------------------------------------
# configuration
# --------------------------------------------------------------------

@dataclass(frozen=True)
class PlanConfig:
    """One full system configuration — every knob that was hand-set
    before this pass existed."""

    mesh_shape: Tuple[int, ...] = (8,)
    batch_per_chip: int = 1024
    zero: str = "on"                 # ZeRO-sharded optimizer state
    wire: str = "f32"                # grad_reduce wire variant name
    fusion: str = "composed"         # "composed" | "fused"
    hosts: int = 1
    compute_dtype: str = "bfloat16"

    @property
    def n_chips(self) -> int:
        return int(math.prod(self.mesh_shape))

    def key(self) -> Tuple:
        return (tuple(self.mesh_shape), self.batch_per_chip, self.zero,
                self.wire, self.fusion, self.hosts, self.compute_dtype)


def mesh_factorizations(n: int) -> List[Tuple[int, ...]]:
    """(n,) plus every 2-axis torus factorization with a <= b —
    the shapes the zero-off ring all-reduce decomposes over."""
    out: List[Tuple[int, ...]] = [(n,)]
    for a in range(2, int(math.isqrt(n)) + 1):
        if n % a == 0:
            out.append((a, n // a))
    return out


def _wire_bytes(cfg: PlanConfig, n_params: int) -> Dict[str, Any]:
    """PR-11 byte model legs for this config's wire + geometry. The
    byte model reads host geometry from VELES_GRAD_REDUCE_LOCAL; pin
    it from the config so planning 2-host geometries needs no real
    processes, then restore."""
    n = cfg.n_chips
    local = max(1, n // max(1, cfg.hosts))
    prev = os.environ.get(_variants.GRAD_REDUCE_LOCAL_ENV)
    os.environ[_variants.GRAD_REDUCE_LOCAL_ENV] = str(local)
    try:
        return _variants.grad_reduce_bytes(cfg.wire, int(n_params), n)
    finally:
        if prev is None:
            os.environ.pop(_variants.GRAD_REDUCE_LOCAL_ENV, None)
        else:
            os.environ[_variants.GRAD_REDUCE_LOCAL_ENV] = prev


def predict_step(cfg: PlanConfig, geom: StepGeometry, *,
                 device_kind: str = "TPU v5 lite",
                 overlap: float = 0.0) -> Dict[str, Any]:
    """The model: predicted seconds for one optimizer step of `cfg`
    on `device_kind`, with every term exposed for falsification."""
    peak = _env_float("VELES_PLAN_PEAK_FLOPS", 0.0) \
        or DEVICE_PEAK_FLOPS.get(device_kind, 0.0) \
        or DEVICE_PEAK_FLOPS["TPU v5 lite"]
    calibrated = (device_kind in CALIBRATED_KINDS
                  and "VELES_PLAN_PEAK_FLOPS" not in os.environ)
    batch = int(cfg.batch_per_chip)
    mfu = mfu_model(batch)
    t_compute = geom.train_flops_per_sample * batch / (peak * mfu)
    gain, gain_src = (fusion_gain(device_kind)
                      if cfg.fusion != "composed" else
                      (1.0, "composed baseline"))
    t_compute /= gain

    dcn_bw = _env_float("VELES_PLAN_DCN_BW", scaling_model.DCN_BW_DEFAULT)
    if cfg.zero == "on":
        legs = _wire_bytes(cfg, geom.n_params)
        dcn = legs["dcn_bytes"] + legs["allgather_dcn_bytes"]
        ici = legs["ici_bytes"] + legs["allgather_ici_bytes"]
        wire_t = scaling_model.wire_collective_time_s(
            dcn_bytes=dcn, ici_bytes=ici, dcn_bw=dcn_bw)
        t_comms = wire_t["total_s"]
        comms = {"model": "wire[dt,blk,ef,hier] reduce-scatter + "
                          "param all-gather",
                 "dcn_bytes": int(dcn), "ici_bytes": int(ici),
                 "legs": legs, "dcn_s": wire_t["dcn_s"],
                 "ici_s": wire_t["ici_s"]}
    else:
        nbytes = 4.0 * geom.n_params
        t_comms = scaling_model.allreduce_time_s(nbytes, cfg.mesh_shape)
        n = cfg.n_chips
        comms = {"model": "per-axis ring all-reduce of the full f32 "
                          "gradient",
                 "dcn_bytes": 0,
                 "ici_bytes": int(2.0 * nbytes * (n - 1) / max(1, n)),
                 "dcn_s": 0.0, "ici_s": t_comms}
    t_comms_exposed = t_comms * (1.0 - overlap)

    feed_bytes = geom.sample_bytes() * batch   # per chip per step
    feed_bw = _env_float("VELES_PLAN_FEED_BW", 0.0)
    t_feed = (max(0.0, feed_bytes / feed_bw
                  - (t_compute + t_comms_exposed))
              if feed_bw > 0 else 0.0)

    step = t_compute + t_comms_exposed + t_feed
    total_batch = batch * cfg.n_chips
    return {
        "step_time_s": step,
        "samples_per_sec": total_batch / step if step > 0 else 0.0,
        "samples_per_sec_per_chip": batch / step if step > 0 else 0.0,
        "compute_s": t_compute,
        "comms_s": t_comms_exposed,
        "feed_s": t_feed,
        "comms": comms,
        "feed_bytes_per_chip": int(feed_bytes),
        "mfu_at_batch": mfu,
        "fusion_gain": gain,
        "fusion_gain_source": gain_src,
        "peak_flops": peak,
        "overlap": float(overlap),
        "calibrated": calibrated,
    }


# --------------------------------------------------------------------
# memory gate: the PR-14 ledgers as the planner's hard constraint
# --------------------------------------------------------------------

def plan_memory_report(cfg: PlanConfig, geom: StepGeometry, *,
                       device_kind: str = "TPU v5 lite"
                       ) -> Dict[str, Any]:
    """Static per-device HBM report for `cfg`, shaped exactly like
    resources.step_resource_report's static-only path so the verdict
    comes from resources.hbm_findings — the ledger's rule, not a
    planner re-implementation. Plus the VMEM gate for fused claims
    (resources.kernel_footprint vs the device budget at every LRN
    site) and the structural refusals no ledger models."""
    n = cfg.n_chips
    params = 4 * geom.n_params
    if cfg.zero == "on":
        opt = 4 * ((geom.n_params + n - 1) // n)    # momentum, 1/N +pad
    else:
        opt = params                                # replicated momentum
    wire_cfg = _variants.grad_reduce_config(cfg.wire) or {}
    ef = 0
    if wire_cfg.get("ef"):
        resid = _variants.grad_reduce_resid_len(cfg.wire, geom.n_params, n)
        ef = 4 * int(resid or 0)
    per_shard_feed = geom.sample_bytes() * cfg.batch_per_chip
    components = {
        "params": params,
        "optimizer_state": opt,
        "ef": ef,
        "feed": 2 * per_shard_feed,      # DeviceFeed double buffer
    }
    resident = sum(components.values())
    # static-only high-water: resident + the transient full-size
    # per-shard gradient + the bwd params copy (resources.py's rule
    # when no traced activation walk is available)
    highwater = resident + 2 * params
    report: Dict[str, Any] = {
        "schema": "veles-resources",
        "static_only": True,
        "n_data_shards": n,
        "zero_active": cfg.zero == "on",
        "batch_bytes_per_device": per_shard_feed,
        "components": components,
        "resident_per_device": resident,
        "highwater_per_device": highwater,
    }

    limit = int(_env_float("VELES_HBM_LIMIT", 0.0)) \
        or DEVICE_HBM_BYTES.get(device_kind, 0)
    findings: List[Finding] = list(resources.hbm_findings(report, limit))

    if cfg.fusion != "composed":
        for site in geom.lrn_sites:
            verdict = resources.kernel_verdict(
                "lrn_maxpool", FUSED_LRN_POOL_POINT, shapes=site,
                device_kind=device_kind)
            if verdict is not None:
                findings.append(Finding(
                    "vmem-over-budget", SEV_ERROR, "lrn_maxpool",
                    f"fused point {FUSED_LRN_POOL_POINT} needs "
                    f"{verdict.get('footprint')} B VMEM at LRN site "
                    f"{site}, budget {verdict.get('vmem_budget')} B "
                    f"on {device_kind}", "plan"))
                break
    if wire_cfg.get("ef") and cfg.zero != "on":
        findings.append(Finding(
            "wire-ef-needs-zero", SEV_ERROR, "grad_reduce",
            f"wire {cfg.wire} carries error feedback in the ZeRO "
            "optimizer slice; it cannot run with zero=off", "plan"))
    if wire_cfg.get("hier") and cfg.hosts <= 1:
        findings.append(Finding(
            "wire-hier-degenerate", "warn", "grad_reduce",
            f"hierarchical wire {cfg.wire} on a single host is "
            "byte-identical to the flat leg (no DCN tier)", "plan"))

    errors = [f for f in findings if f.severity == SEV_ERROR]
    return {
        "verdict": "refused" if errors else "feasible",
        "reasons": [f.format() for f in errors],
        "warnings": [f.format() for f in findings
                     if f.severity != SEV_ERROR],
        "hbm_limit": limit,
        "report": report,
    }


# --------------------------------------------------------------------
# pod-efficiency bridge (docs/SCALING.md recipe through the planner)
# --------------------------------------------------------------------

def pod_efficiency(geom: StepGeometry, *, batch_per_chip: int,
                   mesh_shape: Sequence[int] = (8, 8),
                   device_kind: str = "TPU v5 lite",
                   step_time_s: Optional[float] = None,
                   target: float = 0.90) -> Dict[str, Any]:
    """The docs/SCALING.md pod prediction with the planner supplying
    its inputs: grad bytes from the geometry, step time from the
    model unless a measured one is given."""
    if step_time_s is None:
        cfg = PlanConfig(mesh_shape=(1,), batch_per_chip=batch_per_chip)
        step_time_s = predict_step(cfg, geom,
                                   device_kind=device_kind)["compute_s"]
    return scaling_model.predict_dp_scaling(
        grad_bytes=4.0 * geom.n_params, step_time_s=step_time_s,
        batch_per_chip=batch_per_chip, mesh_shape=mesh_shape,
        target=target)


# --------------------------------------------------------------------
# serve proposal (the serving-tier knobs, same gate)
# --------------------------------------------------------------------

SERVE_RING_CHOICES = (512, 256, 128, 64)


def propose_serve(cfg: PlanConfig, geom: StepGeometry, *,
                  device_kind: str = "TPU v5 lite") -> Dict[str, Any]:
    """Serving-tier knobs for a train config, under the same HBM
    ledger: weight wire int8 when bf16 weights alone would pass 25%
    of the device, the largest ring that divides the data axis and
    keeps serve residency under half the device."""
    limit = int(_env_float("VELES_HBM_LIMIT", 0.0)) \
        or DEVICE_HBM_BYTES.get(device_kind, 16 << 30)
    quant = "int8" if 2 * geom.n_params > 0.25 * limit else "bf16"
    wbytes = geom.n_params * (1 if quant == "int8" else 2)
    sample = geom.sample_bytes()
    ring = 0
    for slots in SERVE_RING_CHOICES:
        if slots % cfg.n_chips:
            continue
        if wbytes + slots * sample <= 0.5 * limit:
            ring = slots
            break
    return {"serve_quantize": quant, "ring_slots": ring or
            min(SERVE_RING_CHOICES),
            "weights_bytes": int(wbytes), "hbm_limit": limit}


# --------------------------------------------------------------------
# budgeted configuration search (the PR-8 machinery one level up)
# --------------------------------------------------------------------

#: axis exploration weights for allocate_budget — batch dominates the
#: measured step time (the r4 sweep moved it 10.5%/octave), the wire
#: dominates multi-host comms, mesh/zero reshape the collective, the
#: fusion claim is binary
AXIS_WEIGHTS: List[Tuple[str, float]] = [
    ("batch_per_chip", 0.35),
    ("wire", 0.25),
    ("mesh_shape", 0.15),
    ("zero", 0.15),
    ("fusion", 0.10),
]

BATCH_CHOICES = (128, 256, 512, 1024, 2048)


def default_space(n_chips: int, hosts: int = 1) -> Dict[str, List[Any]]:
    wires = ["f32", "bf16", "int8_block", "int8_ef"]
    if hosts > 1:
        wires.append("hier2")       # degenerate (= f32) on one host
    return {
        "batch_per_chip": list(BATCH_CHOICES),
        "wire": wires,
        "mesh_shape": mesh_factorizations(n_chips),
        "zero": ["on", "off"],
        "fusion": ["composed", "fused"],
    }


def _plan_counter():
    """veles_plan_configs_total{outcome} on the PR-7 registry; lazily
    bound like autotune's trials counter (planning is not a hot
    path)."""
    from veles_tpu.telemetry import metrics as tm
    return tm.default_registry().counter(
        "veles_plan_configs_total",
        "planner candidate configurations by gate outcome "
        "(feasible / refused / timed)",
        labelnames=("outcome",))


def plan_search(geom: Optional[StepGeometry] = None, *,
                device_kind: str = "TPU v5 lite", n_chips: int = 8,
                hosts: int = 1, budget: int = 32,
                incumbent: Optional[PlanConfig] = None,
                space: Optional[Dict[str, List[Any]]] = None,
                timer: Optional[Callable[[PlanConfig], float]] = None,
                top_k: int = 3) -> Dict[str, Any]:
    """Incumbent-first coordinate descent over the config space, then
    deterministic exploration of whatever budget remains; every
    candidate is model-priced and ledger-gated, and only the model's
    top-k (plus the incumbent, always) is ever timed."""
    if geom is None:
        geom = alexnet_geometry()
    if space is None:
        space = default_space(n_chips, hosts)
    if incumbent is None:
        incumbent = PlanConfig(mesh_shape=(n_chips,), hosts=hosts)
    counter = None
    try:
        counter = _plan_counter()
    except Exception:           # telemetry must never break planning
        pass

    evaluated: Dict[Tuple, Dict[str, Any]] = {}

    def evaluate(cfg: PlanConfig) -> Dict[str, Any]:
        k = cfg.key()
        if k in evaluated:
            return evaluated[k]
        pred = predict_step(cfg, geom, device_kind=device_kind)
        mem = plan_memory_report(cfg, geom, device_kind=device_kind)
        entry = {"config": asdict(cfg), "predicted": pred,
                 "memory": {kk: mem[kk] for kk in
                            ("verdict", "reasons", "warnings",
                             "hbm_limit")},
                 "hbm_highwater_per_device":
                     mem["report"]["highwater_per_device"],
                 "_cfg": cfg}
        evaluated[k] = entry
        if counter is not None:
            counter.labels(outcome=mem["verdict"]).inc()
        return entry

    axes = [a for a, _ in AXIS_WEIGHTS if len(space.get(a, [])) > 1]
    weights = [(a, w) for a, w in AXIS_WEIGHTS if a in axes]
    alloc = (_autotune.allocate_budget(
        weights, max(0, budget - 1), floors={a: 1 for a in axes})
        if weights else {})

    # the objective is throughput: seconds per SAMPLE, not per step —
    # otherwise a tiny batch wins on raw step time while starving the
    # MXU (the r4 sweep's whole point)
    def per_sample(e: Dict[str, Any]) -> float:
        rate = e["predicted"]["samples_per_sec"]
        return 1.0 / rate if rate > 0 else float("inf")

    def better(a: Dict[str, Any], b: Optional[Dict[str, Any]]) -> bool:
        if b is None:
            return a["memory"]["verdict"] == "feasible"
        return (a["memory"]["verdict"] == "feasible"
                and per_sample(a) < per_sample(b))

    inc_entry = evaluate(incumbent)
    best_entry = inc_entry if inc_entry["memory"]["verdict"] == \
        "feasible" else None

    # coordinate descent: walk each axis from the current best point
    for axis in axes:
        base = best_entry["_cfg"] if best_entry else incumbent
        spent = 0
        for choice in space[axis]:
            if choice == getattr(base, axis):
                continue
            if spent >= alloc.get(axis, 0):
                break
            if axis == "mesh_shape":
                cand = replace(base, mesh_shape=tuple(choice))
            else:
                cand = replace(base, **{axis: choice})
            if cand.key() not in evaluated:
                spent += 1
            e = evaluate(cand)
            if better(e, best_entry):
                best_entry = e

    # deterministic exploration of the remaining budget over the
    # untried cross product, fixed axis order
    import itertools
    names = list(space.keys())
    for combo in itertools.product(*(space[a] for a in names)):
        if len(evaluated) >= budget:
            break
        kw = dict(zip(names, combo))
        if "mesh_shape" in kw:
            kw["mesh_shape"] = tuple(kw["mesh_shape"])
        cand = replace(incumbent, **kw)
        if cand.key() in evaluated:
            continue
        e = evaluate(cand)
        if better(e, best_entry):
            best_entry = e

    feasible = [e for e in evaluated.values()
                if e["memory"]["verdict"] == "feasible"]
    refused = [e for e in evaluated.values()
               if e["memory"]["verdict"] != "feasible"]
    feasible.sort(key=lambda e: (per_sample(e),
                                 e["config"]["batch_per_chip"]))
    refused.sort(key=per_sample)

    measured_top1 = None
    if timer is not None:
        to_time: List[Dict[str, Any]] = []
        if inc_entry not in to_time:
            to_time.append(inc_entry)
        for e in feasible:
            if e not in to_time:
                to_time.append(e)
            if len(to_time) >= top_k + 1:
                break
        for e in to_time:
            e["measured_step_s"] = float(timer(e["_cfg"]))
            if counter is not None:
                counter.labels(outcome="timed").inc()
        timed = [e for e in to_time if e.get("measured_step_s")]
        if timed:
            # same objective measured: seconds per sample
            measured_top1 = min(
                timed, key=lambda e: e["measured_step_s"]
                / (e["config"]["batch_per_chip"]
                   * max(1, math.prod(e["config"]["mesh_shape"]))))

    for e in feasible[: max(1, top_k)]:
        e["serve"] = propose_serve(e["_cfg"], geom,
                                   device_kind=device_kind)
    ranked = feasible + refused
    for e in ranked:
        e.pop("_cfg", None)

    plan: Dict[str, Any] = {
        "schema": PLAN_SCHEMA,
        "version": 1,
        "model": {
            "name": geom.name,
            "n_params": geom.n_params,
            "train_gflops_per_sample":
                geom.train_flops_per_sample / 1e9,
            "mfu_curve": {"mfu_max": MFU_MAX, "b_half": MFU_B_HALF,
                          "source": "r4 on-chip batch sweep "
                                    "(MEASURED.json)"},
        },
        "device_kind": device_kind,
        "n_chips": n_chips,
        "hosts": hosts,
        "calibrated": device_kind in CALIBRATED_KINDS,
        "budget": {"total": budget, "allocation": alloc,
                   "evaluated": len(evaluated)},
        "incumbent": inc_entry,
        "ranked": ranked,
        "n_feasible": len(feasible),
        "n_refused": len(refused),
    }
    if measured_top1 is not None:
        plan["measured_top1"] = {"config": measured_top1["config"],
                                 "measured_step_s":
                                     measured_top1["measured_step_s"]}
    return plan


# --------------------------------------------------------------------
# bench bridge: one predicted block per measured record
# --------------------------------------------------------------------

def predict_for_bench(*, n_params: int, train_flops_per_sample: float,
                      device_kind: str, n_chips: int,
                      batch_per_chip: int, zero_active: bool,
                      wire: str = "f32", fused: bool = False,
                      input_hw: int = 227) -> Dict[str, Any]:
    """The compact `predicted` block bench.py embeds next to every
    measured record — geometry taken from the bench's OWN counts so
    the comparison isolates the time model, not the FLOP walk."""
    geom = StepGeometry(
        n_params=int(n_params),
        fwd_flops_per_sample=train_flops_per_sample / 3.0,
        train_flops_per_sample=float(train_flops_per_sample),
        per_op_fwd_flops={}, lrn_sites=[], input_hw=int(input_hw),
        name="bench")
    cfg = PlanConfig(mesh_shape=(int(n_chips),),
                     batch_per_chip=int(batch_per_chip),
                     zero="on" if zero_active else "off",
                     wire=wire or "f32",
                     fusion="fused" if fused else "composed")
    pred = predict_step(cfg, geom, device_kind=device_kind)
    mem = plan_memory_report(cfg, geom, device_kind=device_kind)
    return {
        "step_time_s": pred["step_time_s"],
        "samples_per_sec": pred["samples_per_sec"],
        "samples_per_sec_per_chip": pred["samples_per_sec_per_chip"],
        "compute_s": pred["compute_s"],
        "comms_s": pred["comms_s"],
        "comms_bytes": {"dcn": pred["comms"]["dcn_bytes"],
                        "ici": pred["comms"]["ici_bytes"]},
        "hbm_highwater_per_device":
            mem["report"]["highwater_per_device"],
        "memory_verdict": mem["verdict"],
        "mfu_at_batch": pred["mfu_at_batch"],
        "calibrated": pred["calibrated"],
    }
