"""velint (analysis pass 3 of 3): project-specific AST lint.

Generic linters don't know this codebase's contracts; velint encodes
them (rule catalogue + one-line triggering examples in docs/ANALYSIS.md):

- `hot-sync` (error): `jax.device_get(...)`, `.item()` or
  `np.asarray(...)` inside a unit's `run()` / `xla_run()` — the pulse
  graph's per-minibatch hot path. Each one is a device->host sync that
  stalls the dispatch pipeline. (`numpy_run` is the golden HOST path by
  design and is exempt.)
- `jit-in-loop` (error): `jax.jit(...)` constructed lexically inside a
  `for`/`while` body — a fresh jit wrapper per iteration defeats the
  trace cache (re-trace every pass even when shapes repeat).
- `trace-time` (error): `time.time()`/`time.perf_counter()`/
  `time.monotonic()`, `random.*` or `np.random.*` inside a TRACED
  function (a `fused_apply`/`_apply` method, or a local function passed
  to `jax.jit`/`self.jit`/`shard_map`/`jax.grad`/...). The call runs
  once at trace time and freezes into the jaxpr as a constant — the
  step silently stops varying.
- `lock-no-with` (error): an `.acquire()` call on a lock-named
  attribute with no paired `finally: <x>.release()` in the same
  function: an exception between acquire and release wedges every
  later caller. Use `with lock:`, or release in a `finally`. (One
  implementation of the ISSUE-10 acquire-release rule — the old
  bare-statement case is the subsumed special case.)
- `loader-thread` (error): a `threading.Thread` / `ThreadPoolExecutor`
  constructed in LOADER code (path under `loader/`) by a class that
  defines no `stop()` method. Loaders own background prefetch threads,
  and the teardown contract is `Workflow._stop_units` calling every
  unit's `stop()` — a loader that spawns threads without a stop/join
  path leaks them past Ctrl-C/teardown (the exact bug the teardown
  hardening fixed once already).
- `stray-collective` (error): a cross-replica collective
  (`lax.psum`/`pmean`/`all_gather`/`psum_scatter`) called outside the
  lowering-variant registry (`ops/variants.py`) or the fused/pipeline
  step modules. Collectives placed ad hoc in step code bypass the
  equivalence contract, the autotuner, and the variant table every
  record embeds — and an SPMD program whose collectives differ between
  processes deadlocks the job. Register the collective as a variant
  (the `grad_reduce` reduce-scatter is the precedent) or move it into
  the step modules that own collective placement.

- `raw-clock` (error): a direct `time.time()` / `time.monotonic()` /
  `time.sleep()` CALL in the seamed protocol planes (`resilience/`,
  `serving_watch.py`). Those loops run under the bounded model checker
  (analysis pass 8) with a `VirtualClock`; a raw `time.*` call is a
  hidden real-time dependency the checker cannot own. Take a `clock`
  parameter (resilience/clock.py, default `SYSTEM_CLOCK`) instead.
  Naming a function without calling it (`sleep=time.sleep` defaults)
  stays legal; clock.py's delegating bodies carry suppressions.

Suppression: append `# velint: disable=RULE[,RULE2]` (or `disable=all`)
to the offending line. CI gate: `tools/velint.py --ci` compares against
the checked-in baseline (`tools/velint_baseline.json`) and fails only on
NEW findings — ratchet-only, never a flag day.

Pure stdlib `ast` — importable (and fast) without jax.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import asdict, dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

RULES: Dict[str, str] = {
    "hot-sync": "device->host sync (jax.device_get/.item()/np.asarray) "
                "inside a unit run()/xla_run() hot path",
    "jit-in-loop": "jax.jit constructed inside a for/while loop body",
    "trace-time": "time.time()/random.* inside a traced function "
                  "(freezes into the jaxpr at trace time)",
    "lock-no-with": "lock .acquire() with no `with` block and no "
                    "paired `finally: .release()`",
    "loader-thread": "thread/executor created in loader code by a "
                     "class with no stop() (stop_units teardown "
                     "contract)",
    "sync-feed": "host-blocking transfer (np.asarray/jax.device_get/"
                 "unsharded device_put) inside a step-driver loop — "
                 "feed batches through loader.device_feed.DeviceFeed",
    "stray-collective": "cross-replica collective (psum/pmean/"
                        "all_gather/psum_scatter) outside ops/variants "
                        "(the registry) or the fused/pipeline step "
                        "modules",
    "hot-metric": "metric record in a unit run()/traced function that "
                  "is not a pre-bound handle (registry name lookup per "
                  "record, or any record inside a traced fn — it fires "
                  "once at trace time)",
    "pallas-magic-number": "hard-coded block/tile constant inside a "
                           "Pallas kernel function body — a frozen "
                           "tuning axis the template config space "
                           "(ops/templates.py) cannot search",
    "raw-clock": "direct time.time()/time.monotonic()/time.sleep() in "
                 "a resilience/serving-watch control loop — go through "
                 "the resilience/clock.py seam so the model checker "
                 "and tests can own time",
}

#: registry lookup method names (telemetry/metrics.py): calling one
#: with a string name per record re-resolves the family in the hot path
_METRIC_LOOKUPS = ("counter", "gauge", "histogram")

#: record method names on metric handles; `.set` is deliberately NOT
#: here (too generic — Bool gates, ordinary setters)
_METRIC_RECORDS = ("inc", "observe", "set_total")

#: collective primitives the stray-collective rule watches
_COLLECTIVE_NAMES = ("psum", "pmean", "all_gather", "psum_scatter")

#: modules that legitimately place collectives: the registry (where a
#: collective is an equivalence-contracted, tunable variant) and the two
#: step builders that own collective placement for the whole program
_COLLECTIVE_HOMES = ("parallel/fused.py", "parallel/pipeline.py",
                     "ops/variants.py")


def _is_collective_home(path: str) -> bool:
    norm = path.replace("\\", "/")
    return any(norm.endswith(h) for h in _COLLECTIVE_HOMES)

#: call chains that create background threads (the loader-thread rule)
_THREAD_CTORS = ("threading.Thread", "Thread", "ThreadPoolExecutor",
                 "futures.ThreadPoolExecutor",
                 "concurrent.futures.ThreadPoolExecutor")


def _is_loader_path(path: str) -> bool:
    """Loader code = anything under a `loader/` directory or a file
    whose name contains "loader" (loader.py, image_loader.py)."""
    parts = re.split(r"[/\\]", path)
    return any(p == "loader" for p in parts[:-1]) \
        or "loader" in parts[-1].lower()

#: the pallas-magic-number rule: tile/block-shaped names assigned an
#: int literal inside a function body of a pallas kernel file bypass
#: the template config space. Module-level constants are EXEMPT — they
#: are the documented bounds/seeds of the space (pallas_kernels.py's
#: _LANE/_MIN_ROW_TILE/... block), as are signature defaults (the
#: incumbent seed values).
_TILE_NAME_RE = re.compile(r"tile|blk|block", re.IGNORECASE)


def _is_pallas_file(path: str) -> bool:
    return "pallas" in re.split(r"[/\\]", path)[-1].lower()

#: time.* calls the raw-clock rule bans in the seamed planes (the
#: protocol control loops the model checker re-executes): each one is a
#: hidden dependency on REAL time that a VirtualClock cannot own.
#: References that merely NAME a function (`sleep=time.sleep` signature
#: defaults, backoff.py's injectable idiom) are not calls and stay
#: legal — the caller decides what to inject.
_RAW_CLOCK_CALLS = ("time.time", "time.monotonic", "time.sleep",
                    "time.time_ns", "time.monotonic_ns")


def _is_clocked_path(path: str) -> bool:
    """The raw-clock rule's scope: the cluster/supervisor protocol
    plane (anything under `resilience/`) plus the serving-side watch
    loop and the fleet router — the code the model checker runs
    against a virtual clock. clock.py itself is IN scope and carries
    explicit suppressions: it is the one blessed home for the
    delegating time.* calls."""
    parts = re.split(r"[/\\]", path)
    return any(p == "resilience" for p in parts[:-1]) \
        or parts[-1] in ("serving_watch.py", "serving_router.py")

#: method names that ARE the per-minibatch hot path of a unit
_HOT_METHODS = ("run", "xla_run")

#: method names that are traced by construction (pure jnp model fns)
_TRACED_METHODS = ("fused_apply", "_apply", "_backward_model")

#: call names that take a function argument and trace it
_TRACING_CALLS = ("jit", "shard_map", "make_jaxpr", "grad",
                  "value_and_grad", "vjp", "checkpoint", "remat",
                  "eval_shape", "scan", "pmap", "vmap")

#: attribute-call names that make a loop a STEP-DRIVER loop (the
#: sync-feed rule): a for/while whose body dispatches train/eval steps
#: is the hot path the DeviceFeed exists for — host-blocking transfers
#: there serialize H2D against device compute
_STEP_DRIVER_CALLS = ("train", "train_accum", "train_repeat", "evaluate")

_SUPPRESS_RE = re.compile(r"#\s*velint:\s*disable=([\w\-,]+)")


@dataclass
class LintFinding:
    path: str
    line: int
    col: int
    rule: str
    message: str
    severity: str = "error"

    def as_dict(self) -> Dict[str, object]:
        return asdict(self)

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule}: {self.message}")


def _attr_chain(node: ast.AST) -> str:
    """Dotted name of an attribute/name expression ('' when dynamic)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, tree: ast.Module) -> None:
        self.path = path
        self.findings: List[LintFinding] = []
        self._loader_file = _is_loader_path(path)
        self._collective_home = _is_collective_home(path)
        self._pallas_file = _is_pallas_file(path)
        self._clocked_file = _is_clocked_path(path)
        self._func_depth = 0
        #: innermost-class stack of "defines a stop() method" flags
        self._class_stop: List[bool] = []
        self._class_depth = 0
        self._hot_depth = 0       # inside a run()/xla_run() method body
        self._traced_depth = 0    # inside a traced function body
        self._loop_depth = 0
        self._driver_depth = 0    # inside a step-driver loop body
        #: local function names passed into tracing calls, plus the ids
        #: of lambda nodes passed directly (`self.jit(lambda ...)`, the
        #: codebase's dominant traced idiom) — one pre-pass collects
        #: them so use-before-def order is fine
        self._traced_names, self._traced_lambdas = \
            self._collect_traced(tree)

    # -- pre-pass: which local defs / lambdas get traced ----------------------

    @staticmethod
    def _collect_traced(tree: ast.Module):
        names: set = set()
        lambdas: set = set()
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            leaf = chain.rsplit(".", 1)[-1] if chain else ""
            if leaf not in _TRACING_CALLS:
                continue
            for arg in list(node.args) + [kw.value for kw in
                                          node.keywords]:
                if isinstance(arg, ast.Name):
                    names.add(arg.id)
                elif isinstance(arg, ast.Lambda):
                    lambdas.add(id(arg))
        return names, lambdas

    # -- scope tracking -------------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class_depth += 1
        self._class_stop.append(any(
            isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n.name == "stop" for n in node.body))
        self.generic_visit(node)
        self._class_stop.pop()
        self._class_depth -= 1

    def visit_Module(self, node: ast.Module) -> None:
        self._check_acquire_release(node)
        self.generic_visit(node)

    def _visit_function(self, node) -> None:
        self._check_acquire_release(node)
        name = getattr(node, "name", "<lambda>")
        hot = (self._class_depth > 0 and name in _HOT_METHODS)
        traced = (name in _TRACED_METHODS or name in self._traced_names)
        self._hot_depth += hot
        self._traced_depth += traced
        self._func_depth += 1
        # a nested def is a NEW hot/traced scope only via its own match;
        # but code inside an enclosing hot/traced body stays flagged
        # (closures run where their caller runs)
        self.generic_visit(node)
        self._func_depth -= 1
        self._hot_depth -= hot
        self._traced_depth -= traced

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    def visit_Lambda(self, node: ast.Lambda) -> None:
        traced = id(node) in self._traced_lambdas
        self._traced_depth += traced
        self.generic_visit(node)
        self._traced_depth -= traced

    @staticmethod
    def _is_driver_loop(node) -> bool:
        """True when the loop body dispatches train/eval steps — an
        attribute call like `step.train(...)` anywhere inside (the
        sync-feed rule's scope)."""
        for child in node.body:
            for sub in ast.walk(child):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr in _STEP_DRIVER_CALLS:
                    return True
        return False

    def _visit_loop(self, node) -> None:
        # a For's iter evaluates ONCE — visit it outside the loop
        # context (other rules still see it); a While's test re-runs
        # every iteration, so it IS loop context
        it = getattr(node, "iter", None)
        if it is not None:
            self.visit(it)
        self._loop_depth += 1
        driver = self._is_driver_loop(node)
        self._driver_depth += driver
        test = getattr(node, "test", None)
        if test is not None:
            self.visit(test)
        for child in node.body:
            self.visit(child)
        self._loop_depth -= 1
        self._driver_depth -= driver
        for child in node.orelse:
            self.visit(child)

    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop
    visit_While = _visit_loop

    # -- the rules ------------------------------------------------------------

    def _emit(self, node: ast.AST, rule: str, message: str) -> None:
        self.findings.append(LintFinding(
            self.path, getattr(node, "lineno", 0),
            getattr(node, "col_offset", 0), rule, message))

    def _check_acquire_release(self, scope) -> None:
        """lock-no-with, the ONE acquire-release implementation
        (ISSUE 10): every `.acquire()` on a lock-named chain must be
        PAIRED with a `finally: <chain>.release()` that actually covers
        it — the acquire sits inside the try body, or the try/finally
        is the very next statement (optionally behind one `if got:`
        wrapper, the timeout-acquire idiom). A finally-release
        elsewhere in the function does NOT pair (the scope-global
        version silently passed `acquire(); work(); release()` whenever
        any other try/finally released the same lock). `with lock:`
        never parses to `.acquire()`, so the blessed idiom is naturally
        clean. Nested defs are each their own scope."""
        def release_chains(t: ast.Try) -> frozenset:
            out = set()
            for stmt in t.finalbody:
                for c in ast.walk(stmt):
                    if isinstance(c, ast.Call) \
                            and isinstance(c.func, ast.Attribute) \
                            and c.func.attr == "release":
                        out.add(_attr_chain(c.func.value))
            return frozenset(out)

        def acquires_in(node):
            """Acquire calls in `node`'s expression subtree (nested
            defs skipped)."""
            stack = [node]
            while stack:
                n = stack.pop()
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                    continue
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr == "acquire":
                    chain = _attr_chain(n.func.value)
                    if "lock" in chain.lower():
                        yield n, chain
                stack.extend(ast.iter_child_nodes(n))

        def next_pairs(nxt, chain) -> bool:
            """Does the FOLLOWING statement cover `chain`? The
            try/finally itself, or `if got:` whose body holds one (the
            timeout-acquire idiom)."""
            if isinstance(nxt, ast.Try) and chain in release_chains(nxt):
                return True
            if isinstance(nxt, ast.If):
                return any(isinstance(b, ast.Try)
                           and chain in release_chains(b)
                           for b in nxt.body)
            return False

        def emit(call, chain) -> None:
            self._emit(call, "lock-no-with",
                       f"`{chain}.acquire()` with no paired "
                       f"`finally: {chain}.release()` covering it: an "
                       "exception between acquire and release wedges "
                       "every later caller — use `with lock:` or "
                       "acquire-then-try/finally")

        def scan(stmts, covered: frozenset) -> None:
            for i, s in enumerate(stmts):
                nxt = stmts[i + 1] if i + 1 < len(stmts) else None
                if isinstance(s, ast.Try):
                    scan(s.body, covered | release_chains(s))
                    for h in s.handlers:
                        scan(h.body, covered)
                    scan(s.orelse, covered)
                    scan(s.finalbody, covered)
                elif isinstance(s, (ast.If, ast.While)):
                    for call, chain in acquires_in(s.test):
                        if chain not in covered:
                            emit(call, chain)
                    scan(s.body, covered)
                    scan(s.orelse, covered)
                elif isinstance(s, (ast.For, ast.AsyncFor)):
                    for call, chain in acquires_in(s.iter):
                        if chain not in covered:
                            emit(call, chain)
                    scan(s.body, covered)
                    scan(s.orelse, covered)
                elif isinstance(s, (ast.With, ast.AsyncWith)):
                    for item in s.items:
                        for call, chain in acquires_in(
                                item.context_expr):
                            if chain not in covered:
                                emit(call, chain)
                    scan(s.body, covered)
                elif isinstance(s, (ast.FunctionDef,
                                    ast.AsyncFunctionDef,
                                    ast.ClassDef)):
                    continue            # their own scope / class body
                else:
                    for call, chain in acquires_in(s):
                        if chain in covered \
                                or (nxt is not None
                                    and next_pairs(nxt, chain)):
                            continue
                        emit(call, chain)

        scan(getattr(scope, "body", []), frozenset())

    def _check_magic_tile(self, node, targets, value) -> None:
        """pallas-magic-number: `<something-tile/blk/block> = <int>`
        inside a function body of a pallas kernel file. Module-level
        constants (the space's documented bounds/seeds) and signature
        defaults (incumbent seeds) don't parse to this shape."""
        if not (self._pallas_file and self._func_depth):
            return
        if not isinstance(value, ast.Constant) \
                or not isinstance(value.value, int) \
                or isinstance(value.value, bool):
            return
        for t in targets:
            if isinstance(t, ast.Name) and _TILE_NAME_RE.search(t.id):
                self._emit(
                    node, "pallas-magic-number",
                    f"`{t.id} = {value.value}` hard-codes a block/tile "
                    "choice inside a kernel body: make it a parameter "
                    "fed from the template config space "
                    "(ops/templates.py) — or a module-level named "
                    "constant if it is a hardware bound, not a knob")

    def visit_Assign(self, node: ast.Assign) -> None:
        self._check_magic_tile(node, node.targets, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._check_magic_tile(node, [node.target], node.value)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        chain = _attr_chain(node.func)
        leaf = chain.rsplit(".", 1)[-1] if chain else ""

        if self._loader_file and chain in _THREAD_CTORS \
                and not (self._class_stop and self._class_stop[-1]):
            self._emit(node, "loader-thread",
                       f"`{chain}(...)` in loader code "
                       + ("by a class with no stop() method"
                          if self._class_stop else "at module scope")
                       + ": background produce threads must have a "
                         "stop/join path — Workflow teardown calls "
                         "every unit's stop() (stop_units contract)")

        if leaf in _COLLECTIVE_NAMES and not self._collective_home \
                and (chain == leaf
                     or chain.startswith(("lax.", "jax.lax."))):
            self._emit(node, "stray-collective",
                       f"`{chain}(...)` outside the lowering-variant "
                       "registry and the fused/pipeline step modules: "
                       "an ad-hoc collective bypasses the equivalence "
                       "contract, the autotuner and the variant table "
                       "— register it in ops/variants.py (grad_reduce "
                       "is the precedent) or place it in the step "
                       "builders that own collectives")

        if self._clocked_file and chain in _RAW_CLOCK_CALLS:
            self._emit(node, "raw-clock",
                       f"`{chain}()` in a resilience/serving-watch "
                       "control loop bypasses the injectable clock "
                       "seam: take a `clock` (resilience/clock.py, "
                       "default SYSTEM_CLOCK) and call "
                       f"`clock.{chain.split('.', 1)[1]}()` so the "
                       "model checker and tests can own time")

        if chain == "jax.jit" and self._loop_depth:
            self._emit(node, "jit-in-loop",
                       "jax.jit constructed inside a loop: a fresh "
                       "wrapper per iteration re-traces every pass — "
                       "hoist the jit out of the loop")

        if self._hot_depth:
            if chain == "jax.device_get":
                self._emit(node, "hot-sync",
                           "jax.device_get in a unit hot path blocks on "
                           "the device: keep results device-side "
                           "(set_devmem) until a boundary")
            elif leaf == "item" and not node.args and not node.keywords \
                    and isinstance(node.func, ast.Attribute):
                self._emit(node, "hot-sync",
                           ".item() in a unit hot path is a scalar "
                           "device sync per call")
            elif chain.startswith(("np.asarray", "numpy.asarray")):
                self._emit(node, "hot-sync",
                           "np.asarray in a unit hot path forces a "
                           "device->host transfer: keep results "
                           "device-side (set_devmem) until a boundary")

        # hot-metric (telemetry/metrics.py contract): in the per-
        # minibatch hot path a metric record must go through a handle
        # PRE-BOUND outside the method (step_handles()), never a
        # per-record registry name lookup; inside a TRACED function
        # even a pre-bound record is a bug — it fires once at trace
        # time and the jaxpr never records again
        if self._hot_depth or self._traced_depth:
            if leaf in _METRIC_LOOKUPS and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str) \
                    and isinstance(node.func, ast.Attribute):
                self._emit(node, "hot-metric",
                           f"`{chain or leaf}({node.args[0].value!r})`"
                           " resolves a metric family by name per "
                           "record in a hot/traced path: pre-bind the "
                           "handle outside (metrics.step_handles() is "
                           "the driver precedent)")
            elif leaf in _METRIC_RECORDS \
                    and isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Call):
                self._emit(node, "hot-metric",
                           f"chained metric record `...{leaf}()` on a "
                           "freshly looked-up handle in a hot/traced "
                           "path: pre-bind the handle outside the "
                           "method")
        if self._traced_depth and leaf in _METRIC_RECORDS \
                and isinstance(node.func, ast.Attribute) \
                and not isinstance(node.func.value, ast.Call):
            self._emit(node, "hot-metric",
                       f"metric record `{chain or leaf}()` inside a "
                       "TRACED function runs ONCE at trace time and "
                       "freezes out of the compiled step: record at "
                       "the driver/class-pass boundary instead")

        if self._driver_depth:
            if chain == "jax.device_get" \
                    or chain.startswith(("np.asarray", "numpy.asarray")):
                self._emit(node, "sync-feed",
                           f"`{chain}(...)` inside a step-driver loop "
                           "blocks the host on a device->host transfer "
                           "between dispatches: feed batches through "
                           "loader.device_feed.DeviceFeed (async "
                           "sharded put, one batch ahead) and sync "
                           "only at class-pass boundaries")
            elif chain == "jax.device_put" and len(node.args) < 2 \
                    and not node.keywords:
                self._emit(node, "sync-feed",
                           "unsharded jax.device_put of batch data "
                           "inside a step-driver loop: a bespoke "
                           "transfer path — use loader.device_feed."
                           "DeviceFeed, which puts to the step's "
                           "data-axis in_shardings one batch ahead")

        if self._traced_depth:
            if chain in ("time.time", "time.perf_counter",
                         "time.monotonic", "time.time_ns"):
                self._emit(node, "trace-time",
                           f"{chain}() inside a traced function runs "
                           "ONCE at trace time and freezes into the "
                           "jaxpr as a constant")
            elif chain.startswith(("random.", "np.random.",
                                   "numpy.random.")):
                self._emit(node, "trace-time",
                           f"{chain}() inside a traced function draws "
                           "ONCE at trace time (a frozen constant): use "
                           "jax.random with a carried key")
        self.generic_visit(node)


def _suppressed(finding: LintFinding, lines: Sequence[str]) -> bool:
    """True when the finding's line (or a comment-only line directly
    above it) carries a matching `# velint: disable=` marker."""
    if not 1 <= finding.line <= len(lines):
        return False
    candidates = [lines[finding.line - 1]]
    if finding.line >= 2 and lines[finding.line - 2].lstrip() \
            .startswith("#"):
        candidates.append(lines[finding.line - 2])
    for text in candidates:
        m = _SUPPRESS_RE.search(text)
        if m:
            rules = {r.strip() for r in m.group(1).split(",")}
            if "all" in rules or finding.rule in rules:
                return True
    return False


def lint_source(source: str, path: str = "<string>") -> List[LintFinding]:
    """Lint one source text; returns unsuppressed findings."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [LintFinding(path, e.lineno or 0, e.offset or 0,
                            "syntax-error", str(e))]
    linter = _Linter(path, tree)
    linter.visit(tree)
    lines = source.splitlines()
    return [f for f in linter.findings if not _suppressed(f, lines)]


def lint_file(path: str) -> List[LintFinding]:
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), path)


def iter_py_files(paths: Iterable[str]) -> List[str]:
    """Every .py under `paths` (files or directories), sorted —
    shared by velint and the concurrency/protocol passes so all the
    gates walk one file set."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files: List[str] = []
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"]
                files += [os.path.join(dirpath, fn)
                          for fn in sorted(filenames)
                          if fn.endswith(".py")]
            out += sorted(files)
        elif p.endswith(".py"):
            out.append(p)
    return out


def read_py_files(paths: Iterable[str]) -> Dict[str, str]:
    """{path: source} over every readable .py under `paths` — the one
    loader the whole-program passes (concurrency/protocol) share."""
    files: Dict[str, str] = {}
    for fn in iter_py_files(paths):
        try:
            with open(fn, encoding="utf-8") as f:
                files[fn] = f.read()
        except OSError:
            continue
    return files


def lint_paths(paths: Iterable[str],
               root: Optional[str] = None) -> List[LintFinding]:
    """Lint every .py under `paths` (files or directories). Reported
    paths are relative to `root` when given, so baselines are stable
    across checkouts."""
    findings: List[LintFinding] = []
    for fn in iter_py_files(paths):
        rel = os.path.relpath(fn, root) if root else fn
        for f in lint_file(fn):
            f.path = rel
            findings.append(f)
    return findings


# -- ratchet baseline ---------------------------------------------------------

def baseline_counts(findings: Iterable[LintFinding]
                    ) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for f in findings:
        key = f"{f.path}::{f.rule}"
        counts[key] = counts.get(key, 0) + 1
    return counts


def load_baseline(path: str) -> Dict[str, int]:
    """{"path::rule": count} — missing/corrupt baselines read as empty
    (the strictest gate), never as a crash."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        return {str(k): int(v)
                for k, v in data.get("counts", {}).items()}
    except (OSError, ValueError, AttributeError):
        return {}


def write_baseline(path: str,
                   findings: Iterable[LintFinding]) -> None:
    payload = {"comment": "velint ratchet baseline: pre-existing "
                          "finding counts per file::rule. The --ci gate "
                          "fails only when a count EXCEEDS its entry "
                          "here. Shrink it over time; never grow it.",
               "counts": baseline_counts(findings)}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")


def new_findings(findings: Sequence[LintFinding],
                 baseline: Dict[str, int]
                 ) -> Tuple[List[LintFinding], Dict[str, int]]:
    """Findings beyond the baseline's per-(file, rule) budget, plus the
    over-budget counts. Within a budget, which individual lines are
    'old' is unknowable (line numbers drift) — the ratchet is on
    counts."""
    budget = dict(baseline)
    fresh: List[LintFinding] = []
    over: Dict[str, int] = {}
    for f in findings:
        key = f"{f.path}::{f.rule}"
        if budget.get(key, 0) > 0:
            budget[key] -= 1
        else:
            fresh.append(f)
            over[key] = over.get(key, 0) + 1
    return fresh, over
