"""Protocol analysis (pass 5): HTTP endpoint contracts + thread-owner
teardown contract.

Five stdlib HTTP planes (web_status, cluster coordinator, mirror store,
serving, task_queue) share one hardening convention that PRs 4-7 each
re-derived by hand in review: a handler that READS a request body must
(a) verify the shared token (`http_util.check_shared_token`) and
(b) bound the body before `rfile.read`-ing it (413/400 on abuse, never
an unbounded read an attacker sizes for you). And every class that
spawns threads must expose the `stop()` teardown contract velint's
`loader-thread` rule enforces for loader code — generalized
project-wide here. This pass mechanizes all three as AST checks:

- `endpoint-unauthed` (error): a `do_*` method of a
  `BaseHTTPRequestHandler` subclass that (transitively, through the
  handler's own `self._helper()` methods) reads `self.rfile` without
  any `check_shared_token(...)` call on the way. The check passes
  trivially when no token is configured, so wiring it is free — the
  rule asks that the WIRING exist, the deployment decides the policy.
- `endpoint-unbounded-body` (error): a `self.rfile.read(...)` whose
  length argument is missing, or derives from `Content-Length` with no
  visible bound — no `min(...)` in its computation and no comparison
  (`if length > cap: ... return`) against it anywhere in the method.
  The blessed idioms (`min(int(cl), CAP)`; validate-then-read;
  chunked `read(min(1 << 20, remaining))`) are all recognized.
- `thread-no-stop` (error): a class (flattened over its bases) that
  constructs `threading.Thread`/`Timer`/`ThreadPoolExecutor` and
  defines no `stop()` method anywhere in the hierarchy. Loader paths
  are exempt — velint's `loader-thread` rule already owns those (one
  finding per bug, not two).

Known blind spots: token checks hidden behind non-`self` helper
functions other than `check_shared_token` itself are invisible (wrap
the shared helper instead); boundedness is recognized, not proven — a
`min(x, 2**62)` "bound" passes. Findings are `lint.LintFinding`
records: they ride `tools/velint.py --ci` (ratchet baseline) and honor
`# velint: disable=RULE` suppressions.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set

from veles_tpu.analysis.concurrency import (Project, _attr_chain,
                                            collect_project, flat_methods)
from veles_tpu.analysis.lint import (LintFinding, _suppressed,
                                     read_py_files)

RULES: Dict[str, str] = {
    "endpoint-unauthed": "HTTP handler reads the request body without "
                         "a check_shared_token() call",
    "endpoint-unbounded-body": "rfile.read() with no visible bound on "
                               "the Content-Length",
    "thread-no-stop": "class spawns threads/executors but defines no "
                      "stop() teardown (stop_units contract, "
                      "project-wide)",
}

_HANDLER_BASE = "BaseHTTPRequestHandler"
_THREAD_CTORS = ("Thread", "Timer", "ThreadPoolExecutor")
_AUTH_NAMES = ("check_shared_token",)


def _is_loader_path(path: str) -> bool:
    import re
    parts = re.split(r"[/\\]", path)
    return any(p == "loader" for p in parts[:-1]) \
        or "loader" in parts[-1].lower()


# -- endpoint contracts -------------------------------------------------------

def _handler_classes(tree: ast.Module) -> List[ast.ClassDef]:
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            bases = [_attr_chain(b).rsplit(".", 1)[-1]
                     for b in node.bases if _attr_chain(b)]
            if _HANDLER_BASE in bases:
                out.append(node)
    return out


def _own_calls(fn) -> List[ast.Call]:
    return [n for n in ast.walk(fn) if isinstance(n, ast.Call)]


def _rfile_reads(fn) -> List[ast.Call]:
    """`self.rfile.read(...)` call sites lexically in `fn`."""
    out = []
    for call in _own_calls(fn):
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr == "read" \
                and "rfile" in _attr_chain(call.func.value).split("."):
            out.append(call)
    return out


def _has_auth_call(fn) -> bool:
    for call in _own_calls(fn):
        leaf = _attr_chain(call.func).rsplit(".", 1)[-1]
        if leaf in _AUTH_NAMES:
            return True
    return False


def _self_callees(fn) -> Set[str]:
    out = set()
    for call in _own_calls(fn):
        if isinstance(call.func, ast.Attribute) \
                and isinstance(call.func.value, ast.Name) \
                and call.func.value.id == "self":
            out.add(call.func.attr)
    return out


def _bounded_names(fn) -> Set[str]:
    """Names the method visibly bounds: assigned through a `min(...)`,
    or appearing in any comparison (the validate-then-read idiom)."""
    bounded: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            if any(isinstance(c, ast.Call)
                   and _attr_chain(c.func).rsplit(".", 1)[-1] == "min"
                   for c in ast.walk(node.value)):
                bounded.add(node.targets[0].id)
        elif isinstance(node, ast.Compare):
            for c in ast.walk(node):
                if isinstance(c, ast.Name):
                    bounded.add(c.id)
    return bounded


def _read_is_bounded(call: ast.Call, bounded: Set[str]) -> bool:
    if not call.args:
        return False
    arg = call.args[0]
    if isinstance(arg, ast.Constant):
        return True
    for c in ast.walk(arg):
        if isinstance(c, ast.Call) \
                and _attr_chain(c.func).rsplit(".", 1)[-1] == "min":
            return True
        if isinstance(c, ast.Name) and c.id in bounded:
            return True
    return False


def endpoint_findings(tree: ast.Module, path: str) -> List[LintFinding]:
    out: List[LintFinding] = []
    for cls in _handler_classes(tree):
        methods = {m.name: m for m in cls.body
                   if isinstance(m, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}

        def closure(entry: str) -> Set[str]:
            seen: Set[str] = set()
            todo = [entry]
            while todo:
                m = todo.pop()
                if m in seen or m not in methods:
                    continue
                seen.add(m)
                todo += [c for c in _self_callees(methods[m])]
            return seen

        for name, fn in sorted(methods.items()):
            if not name.startswith("do_"):
                continue
            reach = closure(name)
            reads = [(methods[m], r) for m in sorted(reach)
                     for r in _rfile_reads(methods[m])]
            if not reads:
                continue
            if not any(_has_auth_call(methods[m]) for m in reach):
                out.append(LintFinding(
                    path, fn.lineno, fn.col_offset, "endpoint-unauthed",
                    f"{cls.name}.{name} reads the request body with no "
                    "check_shared_token() call on the path: every "
                    "body-accepting endpoint must verify the shared "
                    "token (http_util.check_shared_token — passes "
                    "trivially when no token is configured)"))
            for owner, read in reads:
                if not _read_is_bounded(read, _bounded_names(owner)):
                    out.append(LintFinding(
                        path, read.lineno, read.col_offset,
                        "endpoint-unbounded-body",
                        f"{cls.name}.{owner.name}: rfile.read() with "
                        "no visible bound on Content-Length — clamp "
                        "with min(length, CAP) or validate-then-413 "
                        "before reading (an unbounded read lets the "
                        "client size your allocation)"))
    return out


# -- thread-owner teardown ----------------------------------------------------

def thread_owner_findings(proj: Project) -> List[LintFinding]:
    out: List[LintFinding] = []
    for cm in proj.classes:
        if _is_loader_path(cm.path):
            continue        # velint loader-thread owns loader paths
        methods = flat_methods(cm, proj)
        if "stop" in methods:
            continue
        site = None
        for _name, (fn, fpath) in sorted(methods.items()):
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    leaf = _attr_chain(node.func).rsplit(".", 1)[-1]
                    if leaf in _THREAD_CTORS:
                        cand = (fpath, node.lineno, leaf)
                        if site is None or cand[:2] < site[:2]:
                            site = cand
        if site is not None:
            fpath, line, leaf = site
            out.append(LintFinding(
                fpath, line, 0, "thread-no-stop",
                f"{cm.name} constructs {leaf}(...) but defines no "
                "stop() anywhere in its hierarchy: thread owners must "
                "expose the stop()/join teardown contract (the "
                "project-wide generalization of velint's loader-thread "
                "rule)"))
    return out


# -- entry points -------------------------------------------------------------

def analyze_files(files: Dict[str, str]) -> List[LintFinding]:
    proj = collect_project(files)
    findings: List[LintFinding] = []
    for path in sorted(files):
        try:
            tree = ast.parse(files[path], filename=path)
        except SyntaxError:
            continue
        findings += endpoint_findings(tree, path)
    findings += thread_owner_findings(proj)
    out = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        lines = proj.lines.get(f.path)
        if lines is not None and _suppressed(f, lines):
            continue
        out.append(f)
    return out


def analyze_source(source: str,
                   path: str = "<module>") -> List[LintFinding]:
    return analyze_files({path: source})


def analyze_paths(paths: Sequence[str],
                  root: Optional[str] = None) -> List[LintFinding]:
    findings = analyze_files(read_py_files(paths))
    if root:
        for f in findings:
            f.path = os.path.relpath(f.path, root)
    return findings
