"""Static workflow-graph verifier (analysis pass 1 of 3).

Walks a CONSTRUCTED `Workflow` — no initialize, no run — and reports the
wiring mistakes that today surface as deep `AttributeError`s or hangs in
the middle of `Workflow.run()`:

- `dangling-alias` (error; warn for `late=True` links): a `link_attrs`
  alias whose target attribute does not exist on the source unit (first
  read inside run() would raise a bare AttributeError far from the
  wiring site). Links declared `late=True` expect their attribute to
  appear at initialize(), so pre-init verification only warns;
- `shadowed-alias` (warn): a linked name that a class attribute (or a
  stray instance attribute) shadows — `Unit.__getattr__` only resolves
  aliases when NORMAL lookup fails, so the alias silently never fires;
- `control-cycle` (error): a control-link cycle containing no OR-gate
  unit (`Repeater`): every member AND-waits on its in-links, including
  the cycle's own back-edge, so no pulse can ever complete the loop —
  the workflow hangs on first entry;
- `unreachable` (error): a unit wired into the control graph that no
  pulse path from `StartPoint` reaches (it never fires, and anything
  AND-gated on it never fires either);
- `endpoint-unreachable` (error): no pulse path from `StartPoint` to
  `EndPoint` — `run()` can only terminate via an explicit `stop()`;
- `read-before-write` (warn): a pulse-driven unit consumes an alias
  whose source unit participates in the control graph but can never
  fire before the consumer — the consumer reads whatever initialization
  left behind.

Workflows whose pulse graph is entirely unwired (fused-only containers,
bare test fixtures) skip the reachability rules: there is no schedule to
verify. The alias rules always run.

Entry points: `verify_workflow(workflow)` returns the findings;
`Workflow.initialize(verify="error"|"warn"|"off")` (default "warn") runs
the pass at initialization; `python -m veles_tpu --verify-workflow`
runs it from the CLI and exits nonzero on errors without training.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Set

from veles_tpu.analysis.findings import SEV_ERROR, SEV_WARN, Finding
from veles_tpu.units import Unit


class WorkflowVerifyError(RuntimeError):
    """Raised by `Workflow.initialize(verify="error")` when the graph
    verifier reports error-severity findings."""

    def __init__(self, findings: List[Finding]) -> None:
        self.findings = findings
        lines = "\n".join("  " + f.format() for f in findings)
        super().__init__(
            f"workflow verification failed with {len(findings)} "
            f"error(s):\n{lines}")


def _links_from(u) -> Dict:
    return u.__dict__.get("_links_from") or {}


def _links_to(u) -> Dict:
    return u.__dict__.get("_links_to") or {}


def _linked_attrs(u) -> Dict:
    return u.__dict__.get("_linked_attrs") or {}


def _participates(u) -> bool:
    """Unit is wired into the pulse graph (has any control link)."""
    return bool(_links_from(u)) or bool(_links_to(u))


def verify_workflow(workflow) -> List[Finding]:
    """Run every graph rule over `workflow`'s direct units; returns the
    findings (possibly empty). Pure inspection: never mutates the graph,
    never initializes or fires a unit."""
    units: List[Unit] = [u for u in getattr(workflow, "units", [])
                         if isinstance(u, Unit)]
    findings: List[Finding] = []
    findings += _check_aliases(units)
    if any(_participates(u) for u in units):
        findings += _check_reachability(workflow, units)
        findings += _check_cycles(units)
        findings += _check_read_before_write(units)
    return findings


# -- alias rules --------------------------------------------------------------

def _check_aliases(units: List[Unit]) -> List[Finding]:
    out: List[Finding] = []
    for u in units:
        for own, (src, remote) in _linked_attrs(u).items():
            site = (f"{getattr(u, 'name', u)}.{own} -> "
                    f"{getattr(src, 'name', src)}.{remote}")
            try:
                exists = hasattr(src, remote)
            except Exception:   # noqa: BLE001 — alias chains may cycle
                exists = False
            if not exists:
                late = own in (u.__dict__.get("_late_attrs") or ())
                out.append(Finding(
                    "dangling-alias",
                    SEV_WARN if late else SEV_ERROR, repr(u),
                    (f"late-bound alias {own!r} "
                     f"({type(src).__name__}.{remote}) not materialized "
                     "yet — fine before initialize(), stale if it "
                     "persists" if late else
                     f"linked attribute {own!r} aliases "
                     f"{type(src).__name__}.{remote}, which does not "
                     "exist on the source unit"), site))
            if own in u.__dict__ or hasattr(type(u), own):
                kind = ("class" if hasattr(type(u), own)
                        else "stray instance")
                out.append(Finding(
                    "shadowed-alias", SEV_WARN, repr(u),
                    f"linked attribute {own!r} is shadowed by a {kind} "
                    "attribute: normal lookup wins and the alias never "
                    "resolves", site))
    return out


# -- reachability / cycle rules ----------------------------------------------

def _reachable(roots) -> Set[Unit]:
    seen: Set[Unit] = set()
    stack = list(roots)
    while stack:
        u = stack.pop()
        if u in seen:
            continue
        seen.add(u)
        stack.extend(_links_to(u))
    return seen


def _check_reachability(workflow, units: List[Unit]) -> List[Finding]:
    out: List[Finding] = []
    start = getattr(workflow, "start_point", None)
    end = getattr(workflow, "end_point", None)
    if start is None:
        return out
    reach = _reachable([start])
    for u in units:
        if u is start or not _participates(u):
            continue
        if u not in reach:
            out.append(Finding(
                "unreachable", SEV_ERROR, repr(u),
                "wired into the control graph but no pulse path from "
                "StartPoint reaches it: it never fires, and every unit "
                "AND-gated on it is dead too"))
    if end is not None and end not in reach:
        out.append(Finding(
            "endpoint-unreachable", SEV_ERROR, repr(end),
            "no pulse path from StartPoint can ever fire EndPoint: "
            "run() only terminates via an explicit stop()"))
    return out


def _check_cycles(units: List[Unit]) -> List[Finding]:
    """Tarjan SCC (iterative) over the participating units; a cycle with
    no OR-gate member is an AND-gate deadlock."""
    nodes = [u for u in units if _participates(u)]
    index: Dict[Unit, int] = {}
    low: Dict[Unit, int] = {}
    on_stack: Set[Unit] = set()
    stack: List[Unit] = []
    sccs: List[List[Unit]] = []
    counter = [0]

    def strongconnect(root: Unit) -> None:
        work = [(root, iter(_links_to(root)))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(_links_to(w))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[v])
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w is v:
                        break
                sccs.append(scc)

    for u in nodes:
        if u not in index:
            strongconnect(u)

    out: List[Finding] = []
    for scc in sccs:
        cyclic = len(scc) > 1 or (scc and scc[0] in _links_to(scc[0]))
        if not cyclic:
            continue
        if any(getattr(u, "or_gate", False) for u in scc):
            continue    # a Repeater-style merge point breaks the wait
        members = ", ".join(sorted(str(getattr(u, "name", u))
                                   for u in scc))
        out.append(Finding(
            "control-cycle", SEV_ERROR, repr(scc[0]),
            "control-link cycle with no OR-gate (Repeater) member: "
            "every unit AND-waits on the cycle's own back-edge, so the "
            "loop can never complete a pulse", f"cycle: {members}"))
    return out


# -- data-flow rule -----------------------------------------------------------

def _check_read_before_write(units: List[Unit]) -> List[Finding]:
    out: List[Finding] = []
    memo: Dict[Unit, FrozenSet[Unit]] = {}

    def descendants(src: Unit) -> FrozenSet[Unit]:
        if src not in memo:
            memo[src] = frozenset(_reachable(list(_links_to(src))))
        return memo[src]

    for u in units:
        if not _links_from(u):
            continue    # not pulse-driven: scheduling is caller-defined
        for own, (src, remote) in _linked_attrs(u).items():
            if src is u or not isinstance(src, Unit):
                continue
            if not _participates(src):
                continue    # init-time data holder, written before run()
            if u not in descendants(src):
                out.append(Finding(
                    "read-before-write", SEV_WARN, repr(u),
                    f"consumes alias {own!r} from "
                    f"{getattr(src, 'name', src)}, but no pulse path "
                    "lets the source fire before this unit: the first "
                    "read sees initialization leftovers",
                    f"{getattr(u, 'name', u)}.{own} <- "
                    f"{getattr(src, 'name', src)}.{remote}"))
    return out
