"""Static analysis for veles_tpu: make wiring, tracing and hot-path
mistakes checkable BEFORE anything runs — on CPU, in CI.

Eight passes (docs/ANALYSIS.md has the full rule catalogue):

- `graph`  — workflow-graph verifier over a constructed `Workflow`
  (dangling/shadowed aliases, AND-gate cycles, unreachable units,
  read-before-write alias flows). Runs at `Workflow.initialize(verify=)`
  and via `python -m veles_tpu --verify-workflow`.
- `trace`  — jaxpr auditor over the fused/pipelined train step
  (dtype promotion, host syncs, dropped donation, sharding drift,
  retrace hazards). `jax.make_jaxpr` only: no compile, no devices.
- `lint`   — `velint`, the project AST lint (`tools/velint.py --ci` is
  the ratchet-only CI gate).
- `concurrency` — whole-program thread-root/race analysis, lock-order
  cycle detection, wait-under-lock (rides the velint gate).
- `protocol` — HTTP endpoint contracts (shared token, bounded bodies)
  and the project-wide thread-owner stop() teardown contract (rides
  the velint gate).
- `resources` — static VMEM/HBM footprint pass: kernel VMEM verdicts
  that PRUNE the budgeted search (`--verify-workflow=resources`), and
  the per-device workflow HBM model behind the launcher pre-flight,
  bench "memory" records and the serving capacity hint.
- `planner` — the whole-system performance model + budgeted config
  search (docs/PLANNER.md): predicted step time (compute roofline +
  wire-aware comms + feed) over (mesh, batch, ZeRO, wire, fusion),
  gated by the `resources` ledgers, behind `tools/plan.py`,
  `tools/ablate.py --plan` and bench's `predicted`/`pred_err`
  calibration block.
- `modelcheck` — bounded protocol model checker: exhaustive
  interleaving + fault-injection exploration of the REAL election /
  membership / hot-swap logic (resilience/cluster.py, serving_watch)
  under a simulated world and virtual clock, against the 8-invariant
  ledger in docs/RESILIENCE.md. Every violation carries a replayable
  counterexample schedule. `tools/modelcheck.py --ci` is the gate;
  `--verify-workflow=modelcheck` runs a small fixed-budget sweep.

`findings.Finding` is the shared record the workflow-facing passes
emit; `concurrency`/`protocol` emit `lint.LintFinding` so they share
velint's baseline and suppression machinery. `graph`/`lint`/
`concurrency`/`protocol` import without jax; `trace` is loaded lazily
so import-light consumers (the supervisor's exit report) can guard it.
"""

from __future__ import annotations

from veles_tpu.analysis import concurrency, protocol  # noqa: F401
from veles_tpu.analysis.findings import (SEV_ERROR, SEV_WARN,  # noqa: F401
                                         Finding, errors, summarize)
from veles_tpu.analysis.graph import (WorkflowVerifyError,  # noqa: F401
                                      verify_workflow)
from veles_tpu.analysis.lint import lint_paths, lint_source  # noqa: F401


def __getattr__(name: str):
    # trace imports jax; load it only when actually used. importlib, not
    # `from ... import trace`: the from-import re-enters THIS hook while
    # the submodule is still unimported and recurses.
    if name in ("audit_fused_step", "audit_workflow",
                "environment_findings", "trace"):
        import importlib
        trace = importlib.import_module("veles_tpu.analysis.trace")
        if name == "trace":
            return trace
        return getattr(trace, name)
    if name == "planner":
        # planner imports the ops registry (a jax MODULE import, no
        # backend); lazy for the same import-light consumers as trace
        import importlib
        return importlib.import_module("veles_tpu.analysis.planner")
    if name == "modelcheck":
        # jax-free but heavy on protocol modules (cluster, serving_gen,
        # serving_watch); lazy so `import veles_tpu.analysis` stays a
        # findings/lint-sized import for the supervisor's exit report
        import importlib
        return importlib.import_module("veles_tpu.analysis.modelcheck")
    raise AttributeError(name)
