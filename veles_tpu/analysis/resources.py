"""Static resource analyzer (analysis pass 6): memory, the resource
that actually bounds a TPU-native VELES.

Two ledgers over the shared `Finding` stream — the first analysis pass
whose findings feed the PERF machinery (the kernel search, the launcher,
serving capacity), not just CI:

1. **Kernel VMEM model.** Every generated Pallas point (ops/templates.py)
   carries a declarative `vmem_footprint(config, shapes, dtype)` rule —
   double-buffered in/out block bytes plus scratch, derived from the
   kernel's BlockSpecs in ops/pallas_kernels.py. Against the per-
   `device_kind` VMEM budget table below, an over-budget point is
   statically INFEASIBLE: the budgeted search (`ops.autotune.search_op`)
   skips it without timing it or burning budget (trial outcome
   ``pruned``), `_timed_trial` structurally refuses to time one
   (`InfeasibleCandidateError` — the `UngatedCandidateError` twin), and
   `apply_cached` refuses a cached winner whose footprint no longer fits
   the current device_kind. A candidate that would only fail minutes
   into an on-chip compile is rejected before a single trial
   (arxiv 2512.10977's "reject infeasible candidates before evaluation";
   arxiv 2203.04015's static pre-compile resource fitting).

2. **Workflow HBM model.** Params + the transient full-size gradient +
   the ZeRO-planned optimizer flat vectors (incl. the optional `ef`
   residual slot, 1/N per `mesh.zero_plan`) + an activation high-water
   estimate from a liveness walk over the UNJITTED `train_callable()`
   jaxpr + the DeviceFeed double-buffer batch bytes — resolved per
   device under the mesh plan and compared against the memstats device
   limit. Surfaced via ``--verify-workflow=resources``, the Launcher
   pre-flight in `_run_with_step` (warn at >80% of the limit, error
   above it with a per-component byte breakdown), bench records
   (``"memory"``), the supervisor exit report (predicted-vs-measured
   delta) and the serving ``/healthz`` capacity hint.

Two predicted numbers per device, because two different measurements
exist: ``resident`` (params + optimizer state + ef + feed batches — what
`jax.live_arrays()` sees between steps) and ``highwater`` (resident +
the traced step's liveness peak — what the allocator's
`peak_bytes_in_use` OOMs on). CPU meshes measure the first, TPUs the
second; predicted-vs-measured comparisons pair them accordingly.

Known blind spots (documented, not hidden): XLA fusion slack (the walk
counts jaxpr values, XLA fuses many away and materializes some
rematerializations instead), compute-dtype cast copies, gspmd TP param
sharding (params are modeled replicated), and in-kernel Pallas
temporaries beyond the declared blocks. The 25% acceptance tolerance
(tests/test_resources.py) is the empirical bound on the CPU mesh.

No jax at module scope: the budget tables and footprint parsing are
importable by jax-free consumers; every traced/measured path imports
lazily.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from veles_tpu.analysis.findings import SEV_ERROR, SEV_WARN, Finding

__all__ = [
    "VMEM_BUDGETS", "VMEM_BUDGET_ENV", "HBM_LIMIT_ENV",
    "InfeasibleCandidateError", "ResourcePreflightError",
    "vmem_budget", "device_limit", "kernel_footprint", "kernel_verdict",
    "shapes_from_signatures", "kernel_findings", "step_resource_report",
    "workflow_resource_findings", "preflight", "serving_capacity",
]

_log = logging.getLogger("veles.resources")

#: env override for the per-device VMEM budget (bytes) — `tools/
#: autotune.py --vmem-budget` sets it for what-if runs; tests pin it
VMEM_BUDGET_ENV = "VELES_VMEM_BUDGET"
#: env override for the per-device HBM limit (bytes) — CPU meshes have
#: no allocator limit, so tests/what-if runs pin one here
HBM_LIMIT_ENV = "VELES_HBM_LIMIT"
#: env gate: force the full (traced) pre-flight even with no known
#: device limit (the static resident model always runs)
PREFLIGHT_ENV = "VELES_RESOURCE_PREFLIGHT"

#: per-device_kind VMEM budget (bytes) a Pallas kernel's resident blocks
#: must fit in. Sources: the Pallas TPU pipelining docs (~16 MB/core on
#: v2-v4) and the v5e/v6e 128 MiB / v7x 64 MiB figures; a small reserve
#: for Mosaic's own scratch is deliberately NOT subtracted — the
#: footprint model under-counts in-kernel temporaries by about as much
#: (blind-spot note in the module docstring). Unknown kinds (CPU
#: interpret mode, GPUs) get None: no static budget, pruning inactive
#: unless the env override supplies one.
VMEM_BUDGETS: Dict[str, int] = {
    "TPU v2": 16 << 20,
    "TPU v3": 16 << 20,
    "TPU v4": 16 << 20,
    "TPU v4 lite": 16 << 20,
    "TPU v5": 128 << 20,
    "TPU v5p": 128 << 20,
    "TPU v5 lite": 128 << 20,
    "TPU v5e": 128 << 20,
    "TPU v6 lite": 128 << 20,
    "TPU v6e": 128 << 20,
    "TPU v7x": 64 << 20,
}

#: pre-flight warning threshold: predicted high-water above this
#: fraction of the device limit warns (above 1.0 errors)
NEAR_LIMIT_FRAC = 0.8


class InfeasibleCandidateError(RuntimeError):
    """Raised when something tries to TIME a generated candidate whose
    static VMEM footprint exceeds the device budget — the structural
    twin of templates.UngatedCandidateError: pruning is a hard gate,
    not a convention the search could drift past."""


class ResourcePreflightError(RuntimeError):
    """Predicted per-device high-water exceeds the device memory limit.
    Carries the full report so the launcher can print the per-component
    byte breakdown instead of an opaque 'would OOM'."""

    def __init__(self, message: str, report: Dict[str, Any]) -> None:
        super().__init__(message)
        self.report = report


# ===========================================================================
# Ledger 1: kernel VMEM footprints vs the device budget
# ===========================================================================


def vmem_budget(device_kind: Optional[str] = None,
                override: Optional[int] = None) -> Optional[int]:
    """The per-device VMEM budget (bytes) for `device_kind`, or None
    when no static budget exists (CPU interpret mode, unknown kinds).
    `override` (tools/autotune.py --vmem-budget) wins, then the env
    override, then the table."""
    if override is not None:
        return int(override)
    env = os.environ.get(VMEM_BUDGET_ENV)
    if env:
        try:
            return int(env)
        except ValueError:
            _log.warning("%s=%r is not an integer byte count; ignoring",
                         VMEM_BUDGET_ENV, env)
    if device_kind is None:
        return None
    return VMEM_BUDGETS.get(device_kind)


def _parse_point(op: str, name: Any):
    """(template, config) for a generated-variant NAME, or None for
    hand-written / foreign names (those carry no declarative footprint
    and are never pruned)."""
    from veles_tpu.ops import templates
    if not isinstance(name, str):
        return None
    for t in templates.templates_for(op):
        cfg = t.parse(name)
        if cfg is not None:
            return t, cfg
    return None


def kernel_footprint(op: str, name: Any,
                     shapes: Optional[Dict[str, Any]] = None,
                     dtype: Any = None) -> Optional[int]:
    """Static VMEM residency (bytes) of the named generated point at
    `shapes` (op-specific dims; missing keys fall back to the rule's
    canonical bench shapes — exactly what the microbench would run).
    None when the name is no template point or its template declares no
    footprint rule (non-Pallas ops): unknown is never pruned."""
    parsed = _parse_point(op, name)
    if parsed is None:
        return None
    t, cfg = parsed
    if t.vmem_footprint is None:
        return None
    return int(t.vmem_footprint(cfg, dict(shapes or {}), dtype))


def kernel_verdict(op: str, name: Any,
                   shapes: Optional[Dict[str, Any]] = None,
                   dtype: Any = None,
                   device_kind: Optional[str] = None,
                   budget: Optional[int] = None
                   ) -> Optional[Dict[str, Any]]:
    """None when the point fits (or nothing is known about it);
    otherwise {"footprint": bytes, "vmem_budget": bytes} — the ONE
    infeasibility rule the search's prune branch, `_timed_trial`'s hard
    gate and `apply_cached`'s refusal all share."""
    b = vmem_budget(device_kind, override=budget)
    if b is None:
        return None
    f = kernel_footprint(op, name, shapes=shapes, dtype=dtype)
    if f is None or f <= b:
        return None
    return {"footprint": f, "vmem_budget": b}


def shapes_from_signatures(op: str, sigs) -> Dict[str, Any]:
    """Footprint `shapes` for a workflow op from its autotune
    signatures (discover_tunables/discover_fusions payloads) — the
    WORST (largest) instance wins, since one registry selection covers
    every instance of the op."""
    out: Dict[str, Any] = {}
    for sig in sigs or ():
        if not isinstance(sig, dict):
            continue
        if op == "lrn_maxpool":
            # the pair signature joins both members: the LRN side
            # carries the activation geometry, the POOLING side the
            # window/stride the fused kernel would run — worst case =
            # the largest window with the smallest stride (biggest
            # padded recompute canvas)
            pool = (sig.get("maxpool") or {}).get("params") or {}
            if pool.get("ksize"):
                ks = tuple(int(v) for v in pool["ksize"])
                prev = out.get("ksize")
                out["ksize"] = ks if prev is None else \
                    tuple(max(a, b) for a, b in zip(prev, ks))
            if pool.get("stride"):
                st = tuple(int(v) for v in pool["stride"])
                prev = out.get("stride")
                out["stride"] = st if prev is None else \
                    tuple(min(a, b) for a, b in zip(prev, st))
            sig = sig.get("lrn") or {}
        ss = sig.get("sample_shape")
        if op in ("lrn", "lrn_maxpool") and ss:
            out["c"] = max(out.get("c", 0), int(ss[-1]))
            if len(ss) == 3:
                out["h"] = max(out.get("h", 0), int(ss[0]))
                out["w"] = max(out.get("w", 0), int(ss[1]))
        elif op == "flash_attn" and ss:
            out["s"] = max(out.get("s", 0), int(ss[0]))
            if sig.get("head_dim"):
                out["d"] = max(out.get("d", 0), int(sig["head_dim"]))
    return out


def kernel_findings(workflow=None,
                    sigs: Optional[Dict[str, List[Dict]]] = None,
                    device_kind: Optional[str] = None,
                    budget: Optional[int] = None,
                    dtype: Any = None) -> List[Finding]:
    """`vmem-over-budget` findings for every template op whose CURRENT
    registry selection is a generated point that cannot fit the device
    budget — the pass-6 form of 'this tree would fail at compile time
    on-chip'. Clean when no budget is known (pruning inactive) or every
    selection fits."""
    from veles_tpu.ops import templates, variants
    if sigs is None and workflow is not None:
        from veles_tpu.ops.autotune import (discover_fusions,
                                            discover_tunables)
        sigs = dict(discover_tunables(workflow))
        sigs.update(discover_fusions(workflow))
    out: List[Finding] = []
    for op in templates.template_ops():
        name = variants.effective(op)
        shapes = shapes_from_signatures(op, (sigs or {}).get(op))
        ver = kernel_verdict(op, name, shapes=shapes, dtype=dtype,
                             device_kind=device_kind, budget=budget)
        if ver is None:
            continue
        out.append(Finding(
            "vmem-over-budget", SEV_ERROR, f"{op}/{name}",
            f"selected generated point needs {ver['footprint']} B of "
            f"VMEM (double-buffered blocks + scratch at "
            f"{shapes or 'bench shapes'}) but the "
            f"{device_kind or 'configured'} budget is "
            f"{ver['vmem_budget']} B: the kernel would fail at compile "
            f"time on-chip — re-run the search (it prunes this point) "
            f"or pick a smaller tile",
            f"footprint {ver['footprint']}/{ver['vmem_budget']} B"))
    return out


# ===========================================================================
# Ledger 2: workflow HBM model vs the device memory limit
# ===========================================================================


def device_limit(limit: Optional[int] = None) -> Optional[int]:
    """Per-device HBM limit in bytes: explicit arg, env override
    (VELES_HBM_LIMIT — CPU meshes report no allocator limit), else the
    smallest `bytes_limit` the backend reports (parallel.memstats).
    None when nothing is known — the comparison half of the pass then
    degrades to a pure report."""
    if limit is not None:
        return int(limit)
    env = os.environ.get(HBM_LIMIT_ENV)
    if env:
        try:
            return int(env)
        except ValueError:
            _log.warning("%s=%r is not an integer byte count; ignoring",
                         HBM_LIMIT_ENV, env)
    from veles_tpu.parallel.memstats import device_memory_limits
    limits = device_memory_limits()
    return min(limits.values()) if limits else None


def _aval_bytes(var) -> int:
    aval = getattr(var, "aval", None)
    shape = getattr(aval, "shape", None)
    dt = getattr(aval, "dtype", None)
    if shape is None or dt is None:
        return 0
    try:
        width = np.dtype(dt).itemsize
    except TypeError:
        # extended dtypes (PRNG key avals) — itemsize when they expose
        # one, else a nominal word (they are tiny either way)
        width = int(getattr(dt, "itemsize", 4) or 4)
    return int(np.prod(shape, dtype=np.int64)) * width


def _liveness_highwater(jaxpr) -> int:
    """Peak bytes of eqn-produced values simultaneously live in one
    jaxpr — a topological liveness walk (def at the producing eqn, death
    after the last consumer; jaxpr outputs live to the end). Nested
    sub-jaxprs (scan/cond/pjit/shard_map bodies) contribute their own
    peak at the owning eqn — inside a dp-mode shard_map the shapes are
    already per-shard, so the estimate lands per DEVICE. Inputs and
    consts are excluded: the caller accounts them as the resident set
    (params, batch), so the walk measures exactly the transient step
    state (activations, grads, the new state before the old one dies)."""
    from veles_tpu.analysis.trace import _sub_jaxprs
    eqns = list(jaxpr.eqns)
    n = len(eqns)
    death: Dict[Any, int] = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            if not hasattr(v, "aval") or type(v).__name__ == "Literal":
                continue
            death[v] = i
    for v in jaxpr.outvars:
        death[v] = n
    alive: Dict[Any, int] = {}
    peak = 0
    for i, eqn in enumerate(eqns):
        inner = 0
        for sub in _sub_jaxprs(eqn.params):
            inner += _liveness_highwater(sub)
        out_b = sum(_aval_bytes(v) for v in eqn.outvars
                    if type(v).__name__ != "DropVar")
        peak = max(peak, sum(alive.values()) + inner + out_b)
        for v in eqn.outvars:
            if type(v).__name__ == "DropVar":
                continue
            if death.get(v, -1) > i:
                alive[v] = _aval_bytes(v)
        for v in eqn.invars:
            if type(v).__name__ == "Literal":
                continue
            if v in alive and death.get(v) == i:
                del alive[v]
    return peak


def _static_profile(step) -> Dict[str, Any]:
    """The step's static per-device component bytes: the FusedTrainStep
    publishes its own (`resource_profile` — params/grads/opt/ef under
    the ZeRO plan); anything else (pipeline steps) degrades to a
    params-derived model."""
    prof = getattr(step, "resource_profile", None)
    if prof is not None:
        return prof()
    params = 0
    for u in getattr(step, "forwards", ()):
        for a in u.param_arrays().values():
            if a:
                arr = np.asarray(a.mem)
                params += int(arr.size) * arr.itemsize
    return {"n_data_shards": 1, "params_bytes": params,
            "grads_bytes": params, "optimizer_state_bytes": params,
            "ef_bytes": 0, "zero_active": False}


def _nbytes(a) -> int:
    """Byte size WITHOUT materializing: jax and numpy arrays both
    expose .nbytes (no transfer); anything else converts."""
    nb = getattr(a, "nbytes", None)
    if nb is not None:
        return int(nb)
    return int(np.asarray(a).nbytes)


def _batch_bytes(x, y, w=None) -> int:
    total = _nbytes(x) + _nbytes(y)
    if w is not None:
        total += _nbytes(w)
    else:
        total += int(np.shape(x)[0]) * 4      # the all-ones pad mask
    return total


def step_resource_report(step, x, y, w=None, feed_batches: int = 2,
                         trace: bool = True) -> Dict[str, Any]:
    """The per-device HBM prediction for one built step at the given
    host batch shapes. Components (bytes/device):

    - ``params``: master weights, modeled replicated over the data axis;
    - ``grads``: the transient full-size per-shard gradient (static
      fallback only — the traced walk counts the real buffers);
    - ``optimizer_state``: momentum/Adam flat vectors, 1/N under the
      ZeRO plan (pad included — the plan's own rule);
    - ``ef``: the optional error-feedback residual slot, 1/N;
    - ``feed``: `feed_batches` device-resident batches (the DeviceFeed
      double buffer: the consumed batch + the prefetched one), sharded
      over the data axis;
    - ``activations``: the liveness-walk peak over the traced unjitted
      `train_callable()` (per-shard inside dp shard_map) — present only
      with `trace=True`.

    Returns the components plus ``resident_per_device`` (what
    live-array accounting sees between steps) and
    ``highwater_per_device`` (what the allocator peak sees mid-step)."""
    prof = _static_profile(step)
    n = max(1, int(prof.get("n_data_shards", 1)))
    batch_total = _batch_bytes(x, y, w)
    per_shard = batch_total // n if batch_total % n == 0 else batch_total
    components: Dict[str, int] = {
        "params": int(prof["params_bytes"]),
        "optimizer_state": int(prof["optimizer_state_bytes"]),
        "ef": int(prof.get("ef_bytes", 0)),
        "feed": int(max(1, feed_batches)) * per_shard,
    }
    resident = sum(components.values())
    report: Dict[str, Any] = {
        "schema": "veles-resources",
        "n_data_shards": n,
        "zero_active": bool(prof.get("zero_active")),
        "batch_bytes_per_device": per_shard,
        "feed_batches": int(max(1, feed_batches)),
        "components": components,
        "resident_per_device": resident,
    }
    traced = None
    if trace:
        traced = _traced_peak(step, x, y, w)
    if traced is not None:
        components["activations"] = traced
        report["highwater_per_device"] = resident + traced
        report["static_only"] = False
    else:
        # no trace: the transient estimate degrades to grads + the new
        # params copy (the two big known buffers the walk would count)
        est = int(prof["grads_bytes"]) + int(prof["params_bytes"])
        components["grads"] = int(prof["grads_bytes"])
        report["highwater_per_device"] = resident + est
        report["static_only"] = True
    return report


def _traced_peak(step, x, y, w=None) -> Optional[int]:
    """Liveness peak over the step's traced train callable, or None when
    the step offers no unjitted callable (make_jaxpr only: no compile,
    no devices — the jaxpr-auditor contract)."""
    callable_fn = getattr(step, "train_callable", None)
    if callable_fn is None:
        return None
    import jax
    x = np.asarray(x)
    y = np.asarray(y)
    if w is None:
        w = np.ones(np.shape(x)[0], np.float32)
    state = step.init_state()
    if hasattr(step, "_microbatch"):        # pipeline step
        xs, yb, wb = step._microbatch(x, y, w)
        args = (state, step._gid, xs, yb, wb)
    else:
        xb, yb = step._seq_xy(x, y)
        args = (state, xb, yb,
                step._weights_or_ones(np.asarray(w, np.float32),
                                      np.shape(x)[0]))
    closed = jax.make_jaxpr(callable_fn())(*args)
    return _liveness_highwater(closed.jaxpr)


def hbm_findings(report: Dict[str, Any],
                 limit: Optional[int]) -> List[Finding]:
    """`hbm-over-limit` / `hbm-near-limit` from a step report and a
    per-device limit (None = nothing to compare, no findings)."""
    if not limit:
        return []
    hw = int(report.get("highwater_per_device", 0))
    comps = ", ".join(f"{k}={v}" for k, v in
                      sorted(report.get("components", {}).items()))
    site = f"{hw}/{limit} B per device"
    if hw > limit:
        return [Finding(
            "hbm-over-limit", SEV_ERROR, "fused step",
            f"predicted per-device high-water {hw} B exceeds the device "
            f"memory limit {limit} B — this (model, mesh, batch, ZeRO) "
            f"combination would OOM after minutes of compile; "
            f"breakdown: {comps}", site)]
    if hw > NEAR_LIMIT_FRAC * limit:
        return [Finding(
            "hbm-near-limit", SEV_WARN, "fused step",
            f"predicted per-device high-water {hw} B is above "
            f"{int(NEAR_LIMIT_FRAC * 100)}% of the device memory limit "
            f"{limit} B; breakdown: {comps}", site)]
    return []


def workflow_resource_findings(workflow, step=None,
                               limit: Optional[int] = None,
                               vmem_budget_override: Optional[int] = None,
                               feed_batches: int = 2
                               ) -> Tuple[List[Finding], Dict[str, Any]]:
    """Pass-6 entry point for `--verify-workflow=resources`: build (or
    take) a fused step, run BOTH ledgers with the loader's real
    minibatch shapes, and return (findings, the per-component report).
    Initializes the workflow host-side when needed; traces, never
    compiles."""
    if not workflow.is_initialized:
        workflow.initialize(device=None, verify="off")
    if step is None:
        step = workflow.build_fused_step()
    loader = workflow.loader
    x = np.asarray(loader.minibatch_data.mem)
    y = np.asarray(loader.minibatch_labels.mem)
    wm = loader.minibatch_valid.mem
    w = (np.asarray(wm, np.float32) if wm is not None
         else np.ones(x.shape[0], np.float32))
    report = step_resource_report(step, x, y, w,
                                  feed_batches=feed_batches, trace=True)
    lim = device_limit(limit)
    report["limit_per_device"] = lim
    findings = hbm_findings(report, lim)
    import jax
    findings += kernel_findings(
        workflow, device_kind=jax.devices()[0].device_kind,
        budget=vmem_budget_override,
        dtype=getattr(step, "compute_dtype", None))
    return findings, report


def preflight(workflow, step, feed_ahead: Optional[int] = None,
              limit: Optional[int] = None) -> Dict[str, Any]:
    """Launcher pre-flight (called by `_run_with_step` before the first
    dispatch): the STATIC resident model always runs (cheap host-shape
    sums — it rides the heartbeat so the supervisor can report the
    predicted-vs-measured delta); the traced high-water walk runs only
    when a device limit is actually known (or VELES_RESOURCE_PREFLIGHT
    forces it) — there is nothing to compare against on a CPU mesh and
    the trace is not free. Warns above 80% of the limit; raises
    ResourcePreflightError (with the per-component breakdown) above
    it — failing in seconds instead of OOMing after minutes of
    compile."""
    loader = workflow.loader
    x = np.asarray(loader.minibatch_data.mem)
    y = np.asarray(loader.minibatch_labels.mem)
    feed_batches = 1 + (1 if feed_ahead is None else max(0,
                                                         int(feed_ahead)))
    lim = device_limit(limit)
    do_trace = bool(lim) or bool(os.environ.get(PREFLIGHT_ENV))
    report = step_resource_report(step, x, y, None,
                                  feed_batches=feed_batches,
                                  trace=do_trace)
    report["limit_per_device"] = lim
    if lim:
        hw = report["highwater_per_device"]
        comps = ", ".join(f"{k}={v}" for k, v in
                          sorted(report["components"].items()))
        if hw > lim:
            raise ResourcePreflightError(
                f"resource pre-flight: predicted per-device high-water "
                f"{hw} B exceeds the device memory limit {lim} B — "
                f"refusing to compile a step that would OOM; "
                f"breakdown: {comps}", report)
        if hw > NEAR_LIMIT_FRAC * lim:
            _log.warning(
                "resource pre-flight: predicted per-device high-water "
                "%d B is %.0f%% of the device limit %d B (%s)",
                hw, 100.0 * hw / lim, lim, comps)
    return report


def serving_capacity(workflow, max_batch: int) -> Dict[str, Any]:
    """The /healthz capacity hint (ROADMAP direction 2's capacity-
    planning primitive): model bytes + a per-batch forward activation
    estimate from the units' DECLARED output geometries (host shapes,
    no trace — /healthz must stay cheap), against the device limit when
    one is known. `headroom_batches` is how many max_batch forward
    rings fit in what the model leaves free — None when no limit is
    known (CPU)."""
    params = 0
    per_sample = 0
    for u in getattr(workflow, "forwards", ()):
        for a in u.param_arrays().values():
            if a:
                arr = np.asarray(a.mem)
                params += int(arr.size) * arr.itemsize
        out = getattr(u, "output", None)
        if out is not None and getattr(out, "shape", None):
            per_sample += int(np.prod(out.shape[1:],
                                      dtype=np.int64)) * 4
    loader = getattr(workflow, "loader", None)
    if loader is not None and getattr(loader, "minibatch_data", None):
        per_sample += int(np.prod(
            loader.minibatch_data.shape[1:], dtype=np.int64)) * 4
    batch_bytes = per_sample * int(max_batch)
    lim = device_limit()
    out: Dict[str, Any] = {
        "model_bytes": params,
        "batch_bytes": batch_bytes,
        "device_limit": lim,
    }
    if lim and batch_bytes:
        out["headroom_batches"] = max(0, (lim - params) // batch_bytes)
    else:
        out["headroom_batches"] = None
    return out
