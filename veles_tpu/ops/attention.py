"""Attention ops: single-device reference + sequence-parallel forms.

The reference framework (2015-era) has NO attention anywhere (SURVEY.md
§5.7); this module is a capability the TPU build adds because long-context
support is first-class here. Two sequence-parallel schemes are provided,
matching the two standard TPU recipes:

- **Ring attention** (`ring_attention`): Q stays sharded over the "seq"
  mesh axis; K/V shards rotate around the ring via `lax.ppermute` while a
  flash-style online softmax accumulates (m, l, o) — numerically identical
  to full attention, memory O(S_local), and the permute rides ICI
  neighbor links. Use when S is huge and heads are few.
- **Ulysses / all-to-all** (`ulysses_attention`): `all_to_all` swaps the
  sequence sharding for a head sharding, full-sequence attention runs per
  head group, then swaps back. Use when n_heads >= mesh axis.

Both run inside `shard_map` over a `Mesh` "seq" axis (parallel/mesh.py)
and degrade to plain attention on a 1-device axis. Tested against
`mha_forward` on the 8-device CPU mesh (tests/test_attention.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from veles_tpu._compat import axis_size as _axis_size

NEG_INF = -1e30


def mha_forward(q, k, v, scale: Optional[float] = None,
                causal: bool = False):
    """Plain multi-head attention. q/k/v: (B, S, H, D) -> (B, S, H, D).
    The single-device golden model for the parallel forms."""
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        q_idx = jnp.arange(q.shape[1])[:, None]
        k_idx = jnp.arange(k.shape[1])[None, :]
        s = jnp.where((k_idx <= q_idx)[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _block_accum(q, k, v, scale, mask, m, l, o):
    """One online-softmax accumulation step (flash-attention recurrence).
    q: (B,Sq,H,D), k/v: (B,Sk,H,D); m/l: (B,H,Sq), o: (B,Sq,H,D)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m_blk = s.max(axis=-1)                      # (B,H,Sq)
    m_new = jnp.maximum(m, m_blk)
    p = jnp.exp(s - m_new[..., None])           # (B,H,Sq,Sk)
    alpha = jnp.exp(m - m_new)                  # (B,H,Sq)
    l_new = l * alpha + p.sum(axis=-1)
    o_new = o * alpha.transpose(0, 2, 1)[..., None] \
        + jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return m_new, l_new, o_new


def ring_attention(q, k, v, axis_name: str,
                   scale: Optional[float] = None, causal: bool = False,
                   kv_block: Optional[int] = None,
                   kv_order: str = "fwd"):
    """Sequence-parallel attention over a ring. Call INSIDE shard_map with
    q/k/v sharded on the sequence dim: (B, S/n, H, D) per device.

    Per step, each device computes attention of its Q shard against the
    currently-held K/V shard, then passes the K/V shard to its ring
    neighbor (`ppermute`) — n steps see every KV shard exactly once. The
    online-softmax (m, l, o) carry makes the result bit-comparable to
    full attention regardless of arrival order.

    `kv_block` tiles WITHIN each hop: the held KV shard is consumed in
    blocks of that size by an inner `lax.scan` of the same flash
    recurrence, so the materialized score block is (B,H,Sq_local,
    kv_block) instead of (B,H,Sq_local,S_local) — the difference between
    fitting and not fitting long-context meshes in HBM. Each block step
    is `jax.checkpoint`-ed, so the backward recomputes scores/probs
    per block instead of storing them (flash-attention memory profile,
    differentiable end-to-end). None → min(S_local, 1024); a value that
    does not divide S_local falls back to one block per hop.

    `kv_block`/`kv_order` are the flash_attn search axes reaching the
    ring hop (MultiHeadAttention.ring_params wires the registry winner's
    blk_k/kv_order here): "rev" visits the held shard's inner blocks
    last-to-first — the online softmax is order-invariant, so the
    choice only probes prefetch/locality, exactly like the local
    kernel's kv_order axis."""
    if kv_order not in ("fwd", "rev"):
        raise ValueError(f"kv_order must be 'fwd'|'rev', got "
                         f"{kv_order!r}")
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, s_loc, h, _ = q.shape
    if kv_block is None:
        kv_block = min(s_loc, 1024)
    if s_loc % kv_block:
        kv_block = s_loc
    nb = s_loc // kv_block

    q_idx = my * s_loc + jnp.arange(s_loc)      # global Q positions

    # the carry must be device-varying from step 0 (shard_map vma typing:
    # it mixes with the varying K/V inside the loop). Deriving it from q
    # arithmetic inherits q's full varying-axis set, whatever outer mesh
    # axes the caller sharded over.
    zero_bhs = q[..., 0].transpose(0, 2, 1) * 0.0
    m0 = zero_bhs + jnp.asarray(NEG_INF, q.dtype)
    l0 = zero_bhs
    o0 = q * 0.0
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(t, carry):
        m, l, o, k_t, v_t = carry
        # after t rotations we hold the shard originally on (my - t) mod n
        src = (my - t) % n
        k0 = src * s_loc                      # global base of held shard
        if nb == 1:
            if causal:
                k_idx = k0 + jnp.arange(s_loc)
                mask = (k_idx[None, :] <= q_idx[:, None])[None, None]
            else:
                mask = None
            m, l, o = _block_accum(q, k_t, v_t, scale, mask, m, l, o)
        else:
            kr = jnp.moveaxis(
                k_t.reshape(b, nb, kv_block, h, d), 1, 0)
            vr = jnp.moveaxis(
                v_t.reshape(b, nb, kv_block, h, d), 1, 0)
            order = jnp.arange(nb)
            if kv_order == "rev":
                kr, vr, order = kr[::-1], vr[::-1], order[::-1]

            @jax.checkpoint
            def blk(c, xs):
                mc, lc, oc = c
                kb, vb, j = xs
                if causal:
                    k_idx = k0 + j * kv_block + jnp.arange(kv_block)
                    mask = (k_idx[None, :]
                            <= q_idx[:, None])[None, None]
                else:
                    mask = None
                return _block_accum(q, kb, vb, scale, mask,
                                    mc, lc, oc), None

            (m, l, o), _ = lax.scan(blk, (m, l, o), (kr, vr, order))
        k_t = lax.ppermute(k_t, axis_name, perm)
        v_t = lax.ppermute(v_t, axis_name, perm)
        return m, l, o, k_t, v_t

    m, l, o, _, _ = lax.fori_loop(0, n, body, (m0, l0, o0, k, v))
    return o / l.transpose(0, 2, 1)[..., None]


def ulysses_attention(q, k, v, axis_name: str,
                      scale: Optional[float] = None, causal: bool = False):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses scheme). Call
    INSIDE shard_map with q/k/v sequence-sharded (B, S/n, H, D); requires
    H divisible by the axis size. The all_to_all trades the sequence
    sharding for a head sharding, full-sequence attention runs on H/n
    local heads, and a second all_to_all restores the sequence sharding.
    """
    n = _axis_size(axis_name)

    def seq_to_heads(x):  # (B, S/n, H, D) -> (B, S, H/n, D)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):  # (B, S, H/n, D) -> (B, S/n, H, D)
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    oh = mha_forward(qh, kh, vh, scale, causal)
    return heads_to_seq(oh)
