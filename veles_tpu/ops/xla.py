"""jnp/lax implementations of the znicz ops — the TPU compute path.

Parity: replaces BOTH hand-written kernel families of the reference
(`veles/znicz/ocl/*.cl` and `veles/znicz/cuda/*.cu`) with XLA lowerings:
matmuls/convs hit the MXU via lax.dot_general/conv_general_dilated,
elementwise chains fuse into them, and backwards come from `jax.vjp` instead
of hand-derived kernels. Semantics match `ops.reference` exactly (tested by
tests/test_ops_equivalence.py; tolerance-based, SURVEY.md §4).

All functions are pure and jit-safe: static shapes, no Python control flow
on traced values.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

TANH_A = 1.7159
TANH_B = 0.6666

# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------


def act_forward(name: str, x):
    if name == "linear":
        return x
    if name == "tanh":
        return TANH_A * jnp.tanh(TANH_B * x)
    if name == "relu":  # reference smooth RELU = softplus
        return jax.nn.softplus(x)
    if name == "strictrelu":
        return jnp.maximum(x, 0.0)
    if name == "sigmoid":
        return jax.nn.sigmoid(x)
    if name == "log":
        return jnp.arcsinh(x)
    raise ValueError(f"unknown activation {name!r}")


def act_backward(name: str, y, err, x=None):
    """dL/dx from dL/dy and the forward OUTPUT y (input x only where the
    derivative needs it) — the reference's memory model: pre-activations
    are never retained. Mirrors ops.reference.act_backward; used inside
    the GD units' fused backward+update steps."""
    if name == "linear":
        return err
    if name == "tanh":
        return err * (TANH_B * (TANH_A - y * y / TANH_A))
    if name == "relu":
        return err * (1.0 - jnp.exp(-y))
    if name == "strictrelu":
        return err * (y > 0)
    if name == "sigmoid":
        return err * y * (1.0 - y)
    if name == "log":
        assert x is not None
        return err / jnp.sqrt(x * x + 1.0)
    raise ValueError(f"unknown activation {name!r}")


# ---------------------------------------------------------------------------
# fully connected
# ---------------------------------------------------------------------------


def all2all_forward(x, w, b, activation: str = "linear"):
    """y = act(x @ W + b). Flattens trailing dims of x (parity: All2All
    accepts image inputs). The matmul is the MXU hot path — callers feed
    bf16 inputs under mixed precision; accumulation stays f32."""
    x2 = x.reshape(x.shape[0], -1)
    return act_forward(activation, x2 @ w + b)


def softmax(x):
    return jax.nn.softmax(x, axis=-1)


def all2all_softmax_forward(x, w, b):
    """Fused linear+max-subtract+softmax (parity: All2AllSoftmax)."""
    x2 = x.reshape(x.shape[0], -1)
    return jax.nn.softmax(x2 @ w + b, axis=-1)


# ---------------------------------------------------------------------------
# convolution — NHWC/HWIO (TPU-native layouts)
# ---------------------------------------------------------------------------


def conv2d_forward(x, w, b, stride: Tuple[int, int] = (1, 1),
                   padding: Tuple[int, int] = (0, 0),
                   activation: str = "linear", s2d: bool = False,
                   acc: str = "native"):
    """acc="f32" pins the conv accumulator to f32
    (preferred_element_type) — a real axis only under a sub-f32 compute
    dtype, where it trades MXU-native accumulation for exactness; the
    "native" default keeps XLA's dtype-following rule (today's
    behavior). A generated conv_stem template axis (ops.templates)."""
    ph, pw = padding
    pet = jnp.float32 if acc == "f32" else None
    if s2d and stride[0] == stride[1] and stride[0] > 1:
        y = conv2d_space_to_depth(x, w, stride[0], (ph, pw), acc=acc)
    else:
        y = lax.conv_general_dilated(
            x, w, window_strides=stride, padding=[(ph, ph), (pw, pw)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=pet)
    if pet is not None:
        y = y.astype(x.dtype)
    return act_forward(activation, y + b)


def conv2d_space_to_depth(x, w, b_: int, padding: Tuple[int, int],
                          acc: str = "native"):
    """EXACT rewrite of a stride-b conv as a stride-1 conv on a
    space-to-depth-packed input — the classic TPU entry-conv trick for
    thin-channel inputs (AlexNet/ResNet stems: cin=3 fills 3/128 of an
    MXU tile; packing b×b stride blocks into channels yields cin·b² and
    a b×-smaller spatial extent, so the systolic array runs full tiles).

    Equivalence: pad H/W and the kernel up to multiples of b with zeros
    (zero taps read anything, contribute nothing), rearrange both input
    and kernel into (H/b, W/b, C·b²) blocks, convolve stride 1. Output
    matches lax.conv_general_dilated bit-for-math on the same dtype.
    """
    n, h, wdt, c = x.shape
    kh, kw, _, co = w.shape
    ph, pw = padding
    if ph or pw:
        x = jnp.pad(x, ((0, 0), (ph, ph), (pw, pw), (0, 0)))
        h, wdt = h + 2 * ph, wdt + 2 * pw
    # valid output extent of the ORIGINAL conv
    oh = (h - kh) // b_ + 1
    ow = (wdt - kw) // b_ + 1
    # pad kernel to multiples of b (zero taps), input so every tap exists
    kh2 = -(-kh // b_) * b_
    kw2 = -(-kw // b_) * b_
    need_h = (oh - 1) * b_ + kh2
    need_w = (ow - 1) * b_ + kw2
    x = jnp.pad(x, ((0, 0), (0, max(0, need_h - h)),
                    (0, max(0, need_w - wdt)), (0, 0)))
    w = jnp.pad(w, ((0, kh2 - kh), (0, kw2 - kw), (0, 0), (0, 0)))
    hb, wb = need_h // b_, need_w // b_
    # space-to-depth: (N, Hb, b, Wb, b, C) -> (N, Hb, Wb, b*b*C)
    xs = x[:, :hb * b_, :wb * b_, :].reshape(n, hb, b_, wb, b_, c)
    xs = xs.transpose(0, 1, 3, 2, 4, 5).reshape(n, hb, wb, b_ * b_ * c)
    # kernel: (kh2, kw2, C, O) -> (kh2/b, b, kw2/b, b, C, O) ->
    # (kh2/b, kw2/b, b*b*C, O), matching the input channel packing
    ws = w.reshape(kh2 // b_, b_, kw2 // b_, b_, c, co)
    ws = ws.transpose(0, 2, 1, 3, 4, 5).reshape(kh2 // b_, kw2 // b_,
                                                b_ * b_ * c, co)
    y = lax.conv_general_dilated(
        xs, ws, window_strides=(1, 1), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=(jnp.float32 if acc == "f32" else None))
    return y.astype(x.dtype) if acc == "f32" else y


def deconv2d_forward(x, w, stride: Tuple[int, int] = (1, 1),
                     padding: Tuple[int, int] = (0, 0),
                     out_hw: Optional[Tuple[int, int]] = None):
    """Transposed conv as the EXACT adjoint of conv2d_forward wrt its input
    (parity: Deconv, which the reference defined as the conv gradient).
    Strided conv output sizes are ambiguous under transposition, so we
    transpose the concrete forward conv for the requested `out_hw` — XLA
    lowers this to a single fractionally-strided conv."""
    n, oh, ow, oc = x.shape
    kh, kw, c, _ = w.shape
    sy, sx = stride
    ph, pw = padding
    if out_hw is None:
        out_hw = ((oh - 1) * sy + kh - 2 * ph, (ow - 1) * sx + kw - 2 * pw)
    in_shape = (n, out_hw[0], out_hw[1], c)

    def fwd(inp):
        return lax.conv_general_dilated(
            inp, w, window_strides=stride, padding=[(ph, ph), (pw, pw)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    transpose = jax.linear_transpose(
        fwd, jax.ShapeDtypeStruct(in_shape, x.dtype))
    (y,) = transpose(x)
    return y


def deconv2d_backward(x, w, err_y, stride: Tuple[int, int] = (1, 1),
                      padding: Tuple[int, int] = (0, 0)):
    """Gradient of deconv2d_forward via jax.vjp (replaces the reference's
    hand-written gd_deconv kernels; XLA emits the two convs directly).
    Returns (err_x, dW)."""
    _, vjp = jax.vjp(
        lambda xx, ww: deconv2d_forward(xx, ww, stride, padding,
                                        out_hw=err_y.shape[1:3]), x, w)
    return vjp(err_y)


def depool_forward(x, idx, out_shape: Tuple[int, ...]):
    """Scatter pooled values to their recorded winner offsets (adjoint of
    max pooling — autoencoder decoders; sentinel offsets drop)."""
    size = 1
    for s in out_shape:
        size *= s
    flat = jnp.zeros(size, x.dtype)
    flat = flat.at[idx.ravel()].add(x.ravel(), mode="drop")
    return flat.reshape(out_shape)


def depool_backward(err_y, idx):
    flat = jnp.asarray(err_y).ravel()
    return flat.at[idx.ravel()].get(mode="fill", fill_value=0.0
                                    ).reshape(idx.shape)


def cut_forward(x, crop: Tuple[int, int]):
    cy, cx = crop
    n, h, w, c = x.shape
    return x[:, cy:h - cy, cx:w - cx, :]


def cut_backward(err_y, x_shape: Tuple[int, ...], crop: Tuple[int, int]):
    cy, cx = crop
    pads = [(0, 0), (cy, cy), (cx, cx), (0, 0)]
    return jnp.pad(err_y, pads)


# ---------------------------------------------------------------------------
# pooling — ceil-mode windows (reference semantics: edge windows truncate)
# ---------------------------------------------------------------------------


def _ceil_pads(h, w, ky, kx, sy, sx):
    oh = -(-(h - ky) // sy) + 1 if h > ky else 1
    ow = -(-(w - kx) // sx) + 1 if w > kx else 1
    return oh, ow, (oh - 1) * sy + ky - h, (ow - 1) * sx + kx - w


def _flat_offsets(choice, n, h, w, c, oh, ow, stride, kx):
    """Flat offsets into an (n,h,w,c) input from per-window winner indices
    `choice` (index within the ky*kx window, shape (n,oh,ow,c)). THE offset
    convention: the backward scatter (pool_scatter) and the numpy golden
    twins in ops.reference must agree with this formula."""
    sy, sx = stride
    dy, dx = choice // kx, choice % kx
    ii = jnp.arange(oh)[None, :, None, None] * sy
    jj = jnp.arange(ow)[None, None, :, None] * sx
    nn = jnp.arange(n)[:, None, None, None]
    cc = jnp.arange(c)[None, None, None, :]
    return ((nn * h + (ii + dy)) * w + (jj + dx)) * c + cc


def maxpool_forward(x, ksize: Tuple[int, int], stride: Tuple[int, int],
                    use_abs: bool = False):
    """reduce_window max pooling. Init/pad values are HOST scalars on
    purpose: a jnp.array init becomes a traced constant under jit and
    breaks reverse-mode linearization of reduce_window (the fused train
    step differentiates through this)."""
    ky, kx = ksize
    sy, sx = stride
    n, h, w, c = x.shape
    _, _, eh, ew = _ceil_pads(h, w, ky, kx, sy, sx)
    pads = [(0, 0, 0), (0, eh, 0), (0, ew, 0), (0, 0, 0)]
    dt = np.dtype(x.dtype)
    if use_abs:
        # keep the signed value of the max-|·| element (MaxAbsPooling)
        xp = lax.pad(x, np.zeros((), dt)[()], pads)
        return lax.reduce_window(
            xp, np.zeros((), dt)[()],
            lambda a, b: jnp.where(jnp.abs(a) >= jnp.abs(b), a, b),
            (1, ky, kx, 1), (1, sy, sx, 1), "VALID")
    ninf = np.asarray(-np.inf, dt)[()]
    xp = lax.pad(x, ninf, pads)
    return lax.reduce_window(xp, ninf, lax.max,
                             (1, ky, kx, 1), (1, sy, sx, 1), "VALID")


def maxpool_forward_with_idx(x, ksize: Tuple[int, int],
                             stride: Tuple[int, int], use_abs: bool = False):
    """Max pooling that also records flat winner offsets into x (reference
    parity: the kernels emitted argmax offsets for the backward scatter).
    Patches-based — used by the granular MaxPooling unit; the fused path
    uses the reduce_window flavor above."""
    ky, kx = ksize
    sy, sx = stride
    n, h, w, c = x.shape
    _, _, eh, ew = _ceil_pads(h, w, ky, kx, sy, sx)
    patches = lax.conv_general_dilated_patches(
        x, (ky, kx), (sy, sx), padding=[(0, eh), (0, ew)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    oh, ow = patches.shape[1], patches.shape[2]
    p = patches.reshape(n, oh, ow, c, ky * kx)
    # mask out padded slots so they never win (pad fills with 0)
    in_h = jnp.arange(oh)[:, None, None] * sy + \
        (jnp.arange(ky * kx)[None, None, :] // kx)
    in_w = jnp.arange(ow)[None, :, None] * sx + \
        (jnp.arange(ky * kx)[None, None, :] % kx)
    valid = (in_h < h) & (in_w < w)          # (oh, ow, ky*kx)
    key = jnp.abs(p) if use_abs else p
    key = jnp.where(valid[None, :, :, None, :], key, -jnp.inf)
    choice = key.argmax(-1)
    y = jnp.take_along_axis(p, choice[..., None], -1)[..., 0]
    return y, _flat_offsets(choice, n, h, w, c, oh, ow, stride, kx)


def maxpool_forward_slices(x, ksize: Tuple[int, int],
                           stride: Tuple[int, int], use_abs: bool = False,
                           fold: str = "linear"):
    """Max pooling as a max-fold over the ky·kx SHIFTED STRIDED SLICES of
    the (−inf-padded) input — numerically identical to the reduce_window
    flavor, but reverse-mode differentiates into selects + zero-pads
    (elementwise, fusion-friendly) instead of XLA's select_and_scatter.
    Candidate lowering for the fused step's backward; A/B'd on chip via
    tools/ablate.py "slicepool" before becoming a default. Each window
    always covers ≥1 real pixel (ceil-mode pads only trailing edges), so
    the fill never wins a window: −inf for plain max; 0 for the abs
    flavor (|−inf| = +inf would win every edge window; |0| only ties an
    all-zero window, where keeping 0 is correct — same fill
    maxpool_forward uses).

    `fold` shapes the combine DAG — a generated maxpool template axis
    (ops.templates): "linear" folds slices left-to-right (a ky·kx-deep
    select chain in the backward), "tree" reduces them pairwise (a
    log-depth balanced select tree; same values — on the measure-zero
    abs-tie case the two may keep a different sign, exactly like any
    reduction-order change)."""
    ky, kx = ksize
    sy, sx = stride
    n, h, w, c = x.shape
    oh, ow, eh, ew = _ceil_pads(h, w, ky, kx, sy, sx)
    dt = np.dtype(x.dtype)
    fill = (np.zeros((), dt) if use_abs else np.asarray(-np.inf, dt))[()]
    xp = lax.pad(x, fill, [(0, 0, 0), (0, eh, 0), (0, ew, 0), (0, 0, 0)])

    def comb(a, b):
        if use_abs:
            return jnp.where(jnp.abs(a) >= jnp.abs(b), a, b)
        return jnp.maximum(a, b)

    slices = [
        lax.slice(xp, (0, dy, dx, 0),
                  (n, dy + (oh - 1) * sy + 1,
                   dx + (ow - 1) * sx + 1, c),
                  (1, sy, sx, 1))
        for dy in range(ky) for dx in range(kx)]
    if fold == "tree":
        while len(slices) > 1:
            slices = [comb(slices[i], slices[i + 1])
                      if i + 1 < len(slices) else slices[i]
                      for i in range(0, len(slices), 2)]
        return slices[0]
    out = slices[0]
    for s in slices[1:]:
        out = comb(out, s)
    return out


def pool_scatter(err_y, idx, x_shape):
    """Backward scatter shared by max/maxabs/stochastic pooling: route err
    to the recorded winners; out-of-range sentinel offsets drop."""
    size = 1
    for s in x_shape:
        size *= s
    flat = jnp.zeros(size, err_y.dtype)
    flat = flat.at[idx.ravel()].add(err_y.ravel(), mode="drop")
    return flat.reshape(x_shape)


def avgpool_forward(x, ksize: Tuple[int, int], stride: Tuple[int, int]):
    """Mean over the *unpadded* window contents (matches the golden model's
    truncated edge windows)."""
    ky, kx = ksize
    sy, sx = stride
    n, h, w, c = x.shape
    _, _, eh, ew = _ceil_pads(h, w, ky, kx, sy, sx)
    pads = [(0, 0, 0), (0, eh, 0), (0, ew, 0), (0, 0, 0)]
    zero = np.zeros((), np.dtype(x.dtype))[()]  # host scalar: stays a
    # compile-time constant so reverse-mode through reduce_window works
    # under jit (see maxpool_forward)
    xp = lax.pad(x, zero, pads)
    ssum = lax.reduce_window(xp, zero, lax.add,
                             (1, ky, kx, 1), (1, sy, sx, 1), "VALID")
    ones = lax.pad(jnp.ones_like(x), zero, pads)
    cnt = lax.reduce_window(ones, zero, lax.add,
                            (1, ky, kx, 1), (1, sy, sx, 1), "VALID")
    return ssum / cnt


def stochastic_pool_forward_with_idx(x, key, ksize: Tuple[int, int],
                                     stride: Tuple[int, int]):
    """Stochastic pooling (Zeiler & Fergus; reference StochasticPooling):
    sample a window element with probability proportional to its positive
    magnitude; falls back to 0 where the window is all-nonpositive.

    Also returns flat winner offsets into x (same convention as the
    reference's max-pooling offsets; `x.size` marks dead all-nonpositive
    windows — scatter with mode="drop" ignores them), so the paired GD unit
    can route gradients without re-sampling."""
    ky, kx = ksize
    sy, sx = stride
    n, h, w, c = x.shape
    # same ceil-mode window geometry as max/avg pooling (truncated edge
    # windows), so the three pooling flavors are drop-in interchangeable
    _, _, eh, ew = _ceil_pads(h, w, ky, kx, sy, sx)
    patches = lax.conv_general_dilated_patches(
        x, (ky, kx), (sy, sx), padding=[(0, eh), (0, ew)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    oh, ow = patches.shape[1], patches.shape[2]
    # patches: (N, OH, OW, C*ky*kx) with feature dim ordered (C, ky*kx)
    p = patches.reshape(n, oh, ow, c, ky * kx)
    pos = jnp.maximum(p, 0.0)
    tot = pos.sum(-1, keepdims=True)
    probs = jnp.where(tot > 0, pos / jnp.maximum(tot, 1e-30), 0.0)
    g = jax.random.gumbel(key, p.shape, p.dtype)
    logp = jnp.where(probs > 0, jnp.log(jnp.maximum(probs, 1e-30)), -jnp.inf)
    choice = (logp + g).argmax(-1)
    picked = jnp.take_along_axis(p, choice[..., None], -1)[..., 0]
    alive = tot[..., 0] > 0
    y = jnp.where(alive, picked, 0.0)
    idx = _flat_offsets(choice, n, h, w, c, oh, ow, stride, kx)
    return y, jnp.where(alive, idx, x.size)


def stochastic_pool_forward(x, key, ksize: Tuple[int, int],
                            stride: Tuple[int, int]):
    return stochastic_pool_forward_with_idx(x, key, ksize, stride)[0]


# ---------------------------------------------------------------------------
# LRN
# ---------------------------------------------------------------------------


def _lrn_band(c: int, n: int):
    """(C, C) 0/1 band matrix: band[i, j] = |i−j| ≤ n//2. Hoisted to a
    compile-time constant by XLA (C ≤ a few hundred for LRN nets)."""
    i = np.arange(c)
    return jnp.asarray(
        (np.abs(i[:, None] - i[None, :]) <= n // 2), np.float32)


def _lrn_window_sum(a, n: int):
    """±half across-channel window sum as a BANDED MATMUL on the MXU:
    a @ B with B the 0/1 band matrix. The r3 shifted-adds lowering (pad+
    slice per tap) left ~20 intermediate tensors the compiler would not
    fuse — r4's on-chip ablation measured LRN at 37% of the AlexNet step,
    i.e. HBM-bound, not compute-bound. As a dot, the window costs
    negligible MXU FLOPs (C·C per element-row, C∈{96,256}), the x²
    producer fuses into the operand read, ONE output hits HBM, and the
    f32 accumulator is numerically better than chained low-precision
    adds. The symmetric window is SELF-ADJOINT: its vjp/transpose is
    itself (used by the closed-form backward below).

    Shifted-adds kept as fallback for C too large for a band constant."""
    c = a.shape[-1]
    if c <= 4096:
        # accumulate in ≥f32 (f64 inputs keep f64 — the finite-difference
        # gradcheck runs under enable_x64)
        acc = a.dtype if a.dtype in (jnp.float32, jnp.float64) \
            else jnp.float32
        out = lax.dot_general(
            a, _lrn_band(c, n).astype(acc),
            (((a.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=acc)
        return out.astype(a.dtype)
    half = n // 2
    zeros = [(0, 0)] * (a.ndim - 1)
    out = a
    for d in range(1, half + 1):
        out = out + jnp.pad(a[..., d:], zeros + [(0, d)]) \
            + jnp.pad(a[..., :-d], zeros + [(d, 0)])
    return out


def _pow_neg_quarters(s, beta: float):
    """s^(-beta). When 4·beta is a small integer (AlexNet's beta=0.75 →
    q=3), decompose into sqrt/rsqrt + multiplies: s^(-q/4) as products of
    squarings of s^(-1/4)=sqrt(rsqrt(s)). The VPU has fast sqrt/rsqrt;
    the generic pow lowers to exp(−beta·log s) — two transcendentals over
    the full activation, measured as a large slice of the AlexNet step
    (tools/ablate.py r4: LRN was 37% of the step with the pow form)."""
    q4 = 4.0 * beta
    q = int(round(q4))
    if abs(q4 - q) < 1e-12 and 1 <= q <= 16:
        t = lax.sqrt(lax.rsqrt(s))        # s^(-1/4)
        out = None
        while q:
            if q & 1:
                out = t if out is None else out * t
            q >>= 1
            if q:
                t = t * t
        return out
    return s ** (-beta)


def lrn_forward(x, k: float = 2.0, alpha: float = 1e-4, beta: float = 0.75,
                n: int = 5, cache_bwd: bool = False):
    """AlexNet-style across-channel LRN: y = x·(k + α·W(x²))^(−β) with W
    the ±half shifted-add window (odd n only — even n would silently
    widen to n+1 taps; the Pallas and C++ twins share the ±half
    semantics, so all three agree only for odd n).

    custom-VJP: backward is the closed form
        err_x = g·d − 2αβ · x · W(g·x·d/s),  d = s^(−β)
    (W self-adjoint). Two residual policies, same math:
    - cache_bwd=False (default): recompute s and d from x in the
      backward — no residual memory beyond x, but the bwd pays a second
      window dot (W(x²)) plus the pow chain;
    - cache_bwd=True: stash d and s from the forward — bwd drops to ONE
      window dot and zero pow at the cost of two activation-sized
      residuals (the ROOFLINE.md "cache the forward window-dot" attack;
      whether the HBM saved beats the residual traffic is an on-chip
      A/B, tools/ablate_lrn.py)."""
    if n % 2 == 0:
        raise ValueError(f"LRN window n must be odd, got {n}")
    if cache_bwd:
        return _lrn_cvjp_cached(x, k, alpha, beta, n)
    return _lrn_cvjp(x, k, alpha, beta, n)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _lrn_cvjp(x, k, alpha, beta, n):
    s = k + alpha * _lrn_window_sum(x * x, n)
    return x * _pow_neg_quarters(s, beta)


def _lrn_fwd_rule(x, k, alpha, beta, n):
    return _lrn_cvjp(x, k, alpha, beta, n), x


def _lrn_bwd_rule(k, alpha, beta, n, x, g):
    s = k + alpha * _lrn_window_sum(x * x, n)
    d = _pow_neg_quarters(s, beta)
    core = _lrn_window_sum(g * x * d / s, n)
    return (g * d - (2.0 * alpha * beta) * x * core,)


_lrn_cvjp.defvjp(_lrn_fwd_rule, _lrn_bwd_rule)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _lrn_cvjp_cached(x, k, alpha, beta, n):
    s = k + alpha * _lrn_window_sum(x * x, n)
    return x * _pow_neg_quarters(s, beta)


def _lrn_fwd_rule_cached(x, k, alpha, beta, n):
    s = k + alpha * _lrn_window_sum(x * x, n)
    d = _pow_neg_quarters(s, beta)
    return x * d, (x, d, s)


def _lrn_bwd_rule_cached(k, alpha, beta, n, res, g):
    x, d, s = res
    core = _lrn_window_sum(g * x * d / s, n)
    return (g * d - (2.0 * alpha * beta) * x * core,)


_lrn_cvjp_cached.defvjp(_lrn_fwd_rule_cached, _lrn_bwd_rule_cached)


# ---------------------------------------------------------------------------
# dropout
# ---------------------------------------------------------------------------


def make_dropout_mask(key, shape, drop_prob: float, dtype=jnp.float32,
                      impl: str = "auto"):
    """Pre-scaled dropout mask (values 0 or 1/keep).

    impl="auto": on accelerators the bits come from the hardware
    `rng_bit_generator` (XLA RBG) instead of threefry — measured 4× less
    wall-clock per (512, 4096) mask on v5e (r4; dropout was ~7% of the
    AlexNet step under threefry, whose per-word rotate chains are VPU
    serial work). Still counter-based and deterministic per key on a
    given backend, but the mask STREAM differs from threefry's —
    trajectories are reproducible per backend, not bit-identical across
    impls (the reference had the same split between its xorshift device
    kernel and numpy host RNG). "threefry"/"rbg" force an impl; CPU
    defaults to threefry so golden tests are impl-stable."""
    keep = 1.0 - drop_prob
    use_rbg = (impl == "rbg"
               or (impl == "auto" and jax.default_backend() != "cpu"))
    if use_rbg and keep < 1.0:
        try:
            kd = jax.random.key_data(key)
        except TypeError:            # raw uint32 key array
            kd = jnp.asarray(key)
        kd = kd.astype(jnp.uint32).reshape(-1)
        rk = jnp.concatenate([kd, kd, kd, kd])[:4]   # RBG wants u32[4]
        _, bits = lax.rng_bit_generator(rk, shape, dtype=jnp.uint32)
        thr = np.uint32(min(keep * 2.0 ** 32, 2.0 ** 32 - 1))
        return (bits < thr).astype(dtype) / np.asarray(keep, dtype)[()]
    return ((jax.random.uniform(key, shape) < keep).astype(dtype)
            / np.asarray(keep, dtype)[()])


def dropout_forward(x, mask):
    return x * mask


# ---------------------------------------------------------------------------
# evaluators / losses
# ---------------------------------------------------------------------------


def softmax_ce(probs, labels, n_classes: int, weights=None):
    """Mirror of reference.softmax_ce on device: returns (loss, err wrt
    logits, n_err, confusion). All jit-safe. `weights` (N,) are sample
    weights (the Loader's pad mask): zero-weight rows contribute nothing
    to any metric — exact epoch metrics at any minibatch size with
    static shapes. weights=None == all-ones (the legacy mean forms)."""
    n = probs.shape[0]
    onehot = jax.nn.one_hot(labels, n_classes, dtype=probs.dtype)
    eps = jnp.finfo(probs.dtype).tiny
    picked = jnp.take_along_axis(probs, labels[:, None], 1)[:, 0]
    logs = -jnp.log(jnp.maximum(picked, eps))
    pred = probs.argmax(axis=1)
    wrong = pred != labels
    if weights is None:
        loss = logs.mean()
        err = (probs - onehot) / jnp.asarray(n, probs.dtype)
        n_err = wrong.sum()
        conf_inc = jnp.ones_like(labels, jnp.int32)
    else:
        w = weights.astype(probs.dtype)
        wsum = jnp.maximum(w.sum(), eps)
        loss = (logs * w).sum() / wsum
        err = (probs - onehot) * w[:, None] / wsum
        n_err = (wrong & (w > 0)).sum()
        conf_inc = (w > 0).astype(jnp.int32)
    confusion = jnp.zeros((n_classes, n_classes), jnp.int32
                          ).at[labels, pred].add(conf_inc)
    return loss, err, n_err, confusion


def ce_loss_from_logits(logits, labels, n_classes: int, weights=None,
                        denom=None):
    """Scalar CE loss from logits — the form jax.grad differentiates in the
    fused train step (log-softmax for stability). Accepts any leading
    dims: (N, C) classifier logits, or (N, S, C) per-token LM logits with
    (N, S) labels (mean over all tokens). `weights` must broadcast to the
    label shape; `denom` overrides the normalizer (the fused sharded step
    passes the GLOBAL psum'd weight sum so per-shard partial losses sum
    to the exact global weighted mean)."""
    logits = logits.reshape(-1, logits.shape[-1])
    flat = labels.reshape(-1)
    logp = jax.nn.log_softmax(logits, axis=-1)
    picked = jnp.take_along_axis(logp, flat[:, None], 1)[:, 0]
    if weights is None:
        return -picked.mean()
    w = jnp.broadcast_to(weights, labels.shape).reshape(-1)
    w = w.astype(picked.dtype)
    d = w.sum() if denom is None else denom
    return -(picked * w).sum() / jnp.maximum(d, 1e-9)


def mse(y, target, weights=None, denom=None):
    """(mean-over-batch MSE, err wrt y); `weights` (N,) sample weights,
    `denom` the (global) weight-sum normalizer as in ce_loss_from_logits."""
    n = y.shape[0]
    diff = y - target
    if weights is None:
        return (diff * diff).sum() / n, 2.0 * diff / jnp.asarray(n, y.dtype)
    wb = weights.astype(y.dtype).reshape((n,) + (1,) * (y.ndim - 1))
    d = weights.astype(y.dtype).sum() if denom is None else denom
    d = jnp.maximum(d, 1e-9)
    return (wb * diff * diff).sum() / d, 2.0 * diff * wb / d


# ---------------------------------------------------------------------------
# Kohonen SOM
# ---------------------------------------------------------------------------


def kohonen_forward(x, w):
    d2 = (x * x).sum(1)[:, None] - 2.0 * x @ w.T + (w * w).sum(1)[None, :]
    return d2.argmin(axis=1)


def kohonen_update(x, w, grid, lr, sigma):
    """Sequential-over-samples SOM update as a lax.scan (the update is
    order-dependent by definition; scan keeps it on-device and compiled —
    parity: KohonenTrainer)."""
    grid = jnp.asarray(grid)

    def step(w, xi):
        d2 = ((w - xi[None, :]) ** 2).sum(1)
        win = d2.argmin()
        gd2 = ((grid - grid[win]) ** 2).sum(1)
        h = jnp.exp(-gd2 / (2.0 * sigma * sigma)).astype(w.dtype)
        return w + lr * h[:, None] * (xi[None, :] - w), None

    w_new, _ = lax.scan(step, w, x)
    return w_new


# ---------------------------------------------------------------------------
# RBM
# ---------------------------------------------------------------------------


def rbm_cd1(v0, w, bv, bh, key):
    h0p = jax.nn.sigmoid(v0 @ w + bh)
    h0 = (jax.random.uniform(key, h0p.shape) < h0p).astype(v0.dtype)
    v1p = jax.nn.sigmoid(h0 @ w.T + bv)
    h1p = jax.nn.sigmoid(v1p @ w + bh)
    n = v0.shape[0]
    dw = (v0.T @ h0p - v1p.T @ h1p) / n
    dbv = (v0 - v1p).mean(axis=0)
    dbh = (h0p - h1p).mean(axis=0)
    return dw, dbv, dbh


# ---------------------------------------------------------------------------
# LSTM
# ---------------------------------------------------------------------------


def lstm_step(x, h, c, wx, wh, b):
    z = x @ wx + h @ wh + b
    hsz = h.shape[1]
    i, f, g, o = (z[:, k * hsz:(k + 1) * hsz] for k in range(4))
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    return h_new, c_new


@partial(jax.jit, static_argnames=())
def lstm_scan(xs, h0, c0, wx, wh, b):
    """Unroll over time with lax.scan (parity: the reference unrolled time
    steps in the unit graph on host — SURVEY.md §5.7; scan is the TPU way).
    xs: (T, N, D) -> hs: (T, N, H)."""

    def step(carry, x):
        h, c = carry
        h, c = lstm_step(x, h, c, wx, wh, b)
        return (h, c), h

    (h, c), hs = lax.scan(step, (h0, c0), xs)
    return hs, h, c
