"""Weight-update rules (parity: reference `GradientDescentBase` in
`veles/znicz/nn_units.py`: learning rate, momentum (`gradient_moment`),
L1/L2 weight decay, per-layer lr/decay multipliers).

Pure pytree-in/pytree-out functions so the whole update fuses into the
compiled train step (the reference ran a separate weight-update kernel per
layer; XLA fuses ours into the backward pass — and on multi-chip the update
runs sharded, see veles_tpu/parallel).

ZeRO update sharding (arxiv 2004.13336, parallel.mesh.zero_plan): the
per-leaf rules are factored out (`sgd_leaf`/`adam_leaf`) so the replicated
update and the shard-local 1/N-slice update are the SAME math applied to
different slices — equivalence between the two paths is structural, not
hoped-for. `sgd_init`/`adam_init` take the plan and then allocate only
flat (padded,) state vectors; the caller shards them over the data axis
(each device ends up holding one `local`-sized slice).
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np


class SGDConfig(NamedTuple):
    lr: float = 0.01
    momentum: float = 0.0          # reference: gradient_moment
    weight_decay: float = 0.0      # L2 (reference: weights_decay)
    l1_decay: float = 0.0          # L1 (reference: l1_vs_l2 blend split out)
    lr_bias_mult: float = 2.0      # reference: bias lr multiplier convention


def sgd_leaf_lr(cfg: SGDConfig, ndim: int, lr_scale=1.0,
                key: Optional[str] = None,
                mults: Optional[Dict[str, float]] = None):
    """Effective lr for ONE leaf: schedule scale, per-key multiplier
    (reference per-layer lr_mult), and the bias convention — 1-D leaves
    get the bias multiplier. `ndim` is the leaf's ORIGINAL rank, so a
    ZeRO-flattened slice still resolves the same lr as its unflattened
    twin."""
    lr = cfg.lr * lr_scale
    if mults and key in mults:
        lr = lr * mults[key]
    if ndim == 1 and cfg.lr_bias_mult != 1.0:
        lr = lr * cfg.lr_bias_mult
    return lr


def sgd_leaf(p, g, v, cfg: SGDConfig, lr):
    """v ← μ·v − lr·(g + λ2·w + λ1·sign(w));  w ← w + v — one leaf (or
    one ZeRO slice of a leaf; `lr` is already fully resolved)."""
    reg = g
    if cfg.weight_decay:
        reg = reg + cfg.weight_decay * p
    if cfg.l1_decay:
        reg = reg + cfg.l1_decay * jnp.sign(p)
    v_new = cfg.momentum * v - lr * reg
    return p + v_new, v_new


def sgd_init(params: Any, plan: Any = None) -> Any:
    """Velocity pytree, zeros like params. With a ZeRO `plan`
    (parallel.mesh.zero_plan) each leaf becomes a flat (padded,) zeros
    vector instead — HOST-side numpy, so no full-size leaf ever touches
    a device: the caller's sharded device_put is the first (and only)
    device allocation, and each replica materializes just its 1/N
    slice. A full-size jnp.zeros here would spike the default device by
    the whole optimizer state at init — exactly the memory ZeRO exists
    to save."""
    if plan is None:
        return jax.tree_util.tree_map(jnp.zeros_like, params)
    return jax.tree_util.tree_map(
        lambda a, lp: np.zeros((lp.padded,), a.dtype), params, plan)


def sgd_update(params: Any, grads: Any, velocity: Any, cfg: SGDConfig,
               lr_scale: float = 1.0,
               mults: Optional[Dict[str, float]] = None):
    """v ← μ·v − lr·(g + λ2·w + λ1·sign(w));  w ← w + v.

    `lr_scale` implements LR schedules (lr_adjust unit) without retracing:
    it is a traced scalar. `mults` maps top-level param-tree keys to lr
    multipliers (reference per-layer lr_mult)."""

    def upd(path, p, g, v):
        key = path[0].key if path and hasattr(path[0], "key") else None
        lr = sgd_leaf_lr(cfg, p.ndim, lr_scale=lr_scale, key=key,
                         mults=mults)
        return sgd_leaf(p, g, v, cfg, lr)

    flat = jax.tree_util.tree_map_with_path(upd, params, grads, velocity)
    new_params = jax.tree_util.tree_map(lambda t: t[0], flat,
                                        is_leaf=lambda t: isinstance(t, tuple))
    new_vel = jax.tree_util.tree_map(lambda t: t[1], flat,
                                     is_leaf=lambda t: isinstance(t, tuple))
    return new_params, new_vel


class AdamConfig(NamedTuple):
    lr: float = 1e-3
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8
    weight_decay: float = 0.0


def adam_init(params: Any, plan: Any = None) -> Any:
    """Adam state; with a ZeRO `plan`, m/v become flat (padded,) zeros
    (the caller shards them — see sgd_init). The step counter `t` stays
    a replicated scalar: it is the same on every shard by construction."""
    def zeros():
        return sgd_init(params, plan=plan)
    return {"m": zeros(), "v": zeros(), "t": jnp.zeros((), jnp.int32)}


def adam_step_factors(cfg: AdamConfig, t):
    """Bias-correction denominators for step `t` (already incremented)."""
    b1t = 1.0 - cfg.b1 ** t.astype(jnp.float32)
    b2t = 1.0 - cfg.b2 ** t.astype(jnp.float32)
    return b1t, b2t


def adam_leaf(p, g, m, v, cfg: AdamConfig, b1t, b2t, lr):
    """One leaf (or one ZeRO slice) of the Adam rule; `lr` is the
    schedule-scaled cfg.lr, `b1t`/`b2t` come from adam_step_factors."""
    if cfg.weight_decay:
        g = g + cfg.weight_decay * p
    m_new = cfg.b1 * m + (1 - cfg.b1) * g
    v_new = cfg.b2 * v + (1 - cfg.b2) * g * g
    step = lr * (m_new / b1t) / (jnp.sqrt(v_new / b2t) + cfg.eps)
    return p - step, m_new, v_new


def adam_update(params: Any, grads: Any, state: Any, cfg: AdamConfig,
                lr_scale: float = 1.0):
    t = state["t"] + 1
    b1t, b2t = adam_step_factors(cfg, t)

    def upd(p, g, m, v):
        return adam_leaf(p, g, m, v, cfg, b1t, b2t, cfg.lr * lr_scale)

    triples = jax.tree_util.tree_map(upd, params, grads, state["m"], state["v"])
    pick = lambda i: jax.tree_util.tree_map(  # noqa: E731
        lambda t_: t_[i], triples, is_leaf=lambda t_: isinstance(t_, tuple))
    return pick(0), {"m": pick(1), "v": pick(2), "t": t}
