"""Weight-update rules (parity: reference `GradientDescentBase` in
`veles/znicz/nn_units.py`: learning rate, momentum (`gradient_moment`),
L1/L2 weight decay, per-layer lr/decay multipliers).

Pure pytree-in/pytree-out functions so the whole update fuses into the
compiled train step (the reference ran a separate weight-update kernel per
layer; XLA fuses ours into the backward pass — and on multi-chip the update
runs sharded, see veles_tpu/parallel).
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp


class SGDConfig(NamedTuple):
    lr: float = 0.01
    momentum: float = 0.0          # reference: gradient_moment
    weight_decay: float = 0.0      # L2 (reference: weights_decay)
    l1_decay: float = 0.0          # L1 (reference: l1_vs_l2 blend split out)
    lr_bias_mult: float = 2.0      # reference: bias lr multiplier convention


def sgd_init(params: Any) -> Any:
    """Velocity pytree, zeros like params."""
    return jax.tree_util.tree_map(jnp.zeros_like, params)


def sgd_update(params: Any, grads: Any, velocity: Any, cfg: SGDConfig,
               lr_scale: float = 1.0,
               mults: Optional[Dict[str, float]] = None):
    """v ← μ·v − lr·(g + λ2·w + λ1·sign(w));  w ← w + v.

    `lr_scale` implements LR schedules (lr_adjust unit) without retracing:
    it is a traced scalar. `mults` maps top-level param-tree keys to lr
    multipliers (reference per-layer lr_mult)."""

    def upd(path, p, g, v):
        lr = cfg.lr * lr_scale
        if mults:
            key = path[0].key if path and hasattr(path[0], "key") else None
            if key in mults:
                lr = lr * mults[key]
        # bias convention: 1-D params get the bias multiplier
        if p.ndim == 1 and cfg.lr_bias_mult != 1.0:
            lr = lr * cfg.lr_bias_mult
        reg = g
        if cfg.weight_decay:
            reg = reg + cfg.weight_decay * p
        if cfg.l1_decay:
            reg = reg + cfg.l1_decay * jnp.sign(p)
        v_new = cfg.momentum * v - lr * reg
        return p + v_new, v_new

    flat = jax.tree_util.tree_map_with_path(upd, params, grads, velocity)
    new_params = jax.tree_util.tree_map(lambda t: t[0], flat,
                                        is_leaf=lambda t: isinstance(t, tuple))
    new_vel = jax.tree_util.tree_map(lambda t: t[1], flat,
                                     is_leaf=lambda t: isinstance(t, tuple))
    return new_params, new_vel


class AdamConfig(NamedTuple):
    lr: float = 1e-3
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8
    weight_decay: float = 0.0


def adam_init(params: Any) -> Any:
    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
    return {"m": zeros,
            "v": jax.tree_util.tree_map(jnp.zeros_like, params),
            "t": jnp.zeros((), jnp.int32)}


def adam_update(params: Any, grads: Any, state: Any, cfg: AdamConfig,
                lr_scale: float = 1.0):
    t = state["t"] + 1
    b1t = 1.0 - cfg.b1 ** t.astype(jnp.float32)
    b2t = 1.0 - cfg.b2 ** t.astype(jnp.float32)

    def upd(p, g, m, v):
        if cfg.weight_decay:
            g = g + cfg.weight_decay * p
        m_new = cfg.b1 * m + (1 - cfg.b1) * g
        v_new = cfg.b2 * v + (1 - cfg.b2) * g * g
        step = cfg.lr * lr_scale * (m_new / b1t) / (
            jnp.sqrt(v_new / b2t) + cfg.eps)
        return p - step, m_new, v_new

    triples = jax.tree_util.tree_map(upd, params, grads, state["m"], state["v"])
    pick = lambda i: jax.tree_util.tree_map(  # noqa: E731
        lambda t_: t_[i], triples, is_leaf=lambda t_: isinstance(t_, tuple))
    return pick(0), {"m": pick(1), "v": pick(2), "t": t}
