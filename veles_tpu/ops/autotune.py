"""Persistent autotuner over the lowering-variant registry.

For each tunable op a workflow actually contains, time every registered
candidate lowering IN-GRAPH — a short donated `train_repeat` microbench of
the whole fused step, the same scanned hot loop bench.py measures — pick
the fastest, `variants.select()` it, and persist the decision in an
on-disk JSON cache keyed by (device_kind, op, shapes, dtypes,
params-hash, compute_dtype). A cache hit selects the stored winner with
ZERO tuning cost; corrupt or missing cache files degrade to re-tuning,
never to an error. On CPU the pallas candidates run in interpret mode, so
the whole subsystem is tier-1-testable without a chip.

Entry points: `autotune_workflow(wf)` (also exposed as
`StandardWorkflow.autotune()` and the CLI's `--autotune`), and
`tools/autotune.py` for the flagship AlexNet step — the systematic
replacement for the hand-flipped `tools/ablate.py` / `ablate_lrn.py`
one-offs.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional

from veles_tpu.logger import Logger
from veles_tpu.ops import variants

__all__ = ["AutotuneCache", "autotune_workflow", "discover_tunables",
           "op_cache_key", "default_cache_path"]


def default_cache_path() -> str:
    return (os.environ.get("VELES_AUTOTUNE_CACHE")
            or os.path.join(os.path.expanduser("~"), ".cache",
                            "veles_tpu", "autotune.json"))


class AutotuneCache(Logger):
    """On-disk JSON decision cache. Flat {key: record} mapping; records
    carry the winning variant plus the timings that chose it. A corrupt
    or unreadable file behaves as empty (the tuner re-times and the next
    `put` rewrites it atomically)."""

    VERSION = 1

    def __init__(self, path: Optional[str] = None) -> None:
        super().__init__()
        self.path = path or default_cache_path()
        self._data: Optional[Dict[str, Any]] = None

    def _load(self) -> Dict[str, Any]:
        if self._data is not None:
            return self._data
        try:
            with open(self.path) as f:
                raw = json.load(f)
            entries = raw.get("entries")
            if raw.get("version") != self.VERSION \
                    or not isinstance(entries, dict):
                raise ValueError("unrecognized cache layout")
            self._data = entries
        except FileNotFoundError:
            self._data = {}
        except (OSError, ValueError, AttributeError) as e:
            self.warning("autotune cache %s unreadable (%s): re-tuning",
                         self.path, e)
            self._data = {}
        return self._data

    def get(self, key: str) -> Optional[Dict[str, Any]]:
        rec = self._load().get(key)
        return dict(rec) if isinstance(rec, dict) else None

    def put(self, key: str, record: Dict[str, Any]) -> None:
        data = self._load()
        data[key] = record
        tmp = f"{self.path}.tmp.{os.getpid()}"
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump({"version": self.VERSION, "entries": data}, f,
                      indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)   # atomic: readers never see a torn file


def _resolve_compute_dtype(compute_dtype: Any) -> Any:
    """None means 'whatever the fused step would use' — resolve it the
    same way FusedTrainStep does (root.common.precision_type), so cache
    keys agree between a tuner passing None and a run passing None."""
    if compute_dtype is not None:
        return compute_dtype
    try:
        from veles_tpu.config import root
        pt = getattr(root.common, "precision_type", None)
    except Exception:  # noqa: BLE001
        pt = None
    return pt if pt and pt != "float32" else None


def op_cache_key(device_kind: str, op: str, signatures: List[Dict],
                 compute_dtype: Any = None) -> str:
    """One key per (device, op, workflow-op-configuration). The signature
    list covers EVERY instance of the op in the workflow (two LRN layers
    with different shapes are one joint decision — the registry selection
    is global per op), canonicalized so dict ordering can't split keys."""
    blob = json.dumps(signatures, sort_keys=True, default=str)
    h = hashlib.sha256(blob.encode()).hexdigest()[:16]
    cd = str(compute_dtype) if compute_dtype is not None else "f32"
    return f"{device_kind}|{op}|{cd}|{h}"


def discover_tunables(wf) -> Dict[str, List[Dict]]:
    """{op: [signature, ...]} for every tunable op present in the
    workflow. Units opt in by exposing `variant_signature()` (returning
    None when not tunable in this configuration — e.g. an explicit
    per-layer override, or a conv the s2d rewrite can't apply to)."""
    found: Dict[str, List[Dict]] = {}
    for u in getattr(wf, "forwards", ()):
        op = getattr(u, "variant_op", None)
        sig_fn = getattr(u, "variant_signature", None)
        if op is None or sig_fn is None:
            continue
        sig = sig_fn()
        if sig is not None:
            found.setdefault(op, []).append(sig)
    return found


def _sync(state) -> None:
    """Device barrier that works through the remote PJRT tunnel: fetch one
    scalar (block_until_ready is not a reliable barrier there — bench.py
    protocol)."""
    import numpy as np
    for layer in state["params"]:
        for a in layer.values():
            np.asarray(a[(0,) * getattr(a, "ndim", 0)])
            return


def _time_variant(wf, mesh, compute_dtype, steps: int, repeats: int,
                  batch: Optional[int]) -> float:
    """Seconds per training step for the CURRENT registry selection:
    build a fresh fused step (the selection is read at trace time), warm
    it, then time `train_repeat` — one dispatch per window, donated
    state, synthetic device-resident batch (nothing host-side in the
    measurement)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    loader = wf.loader
    b = int(batch or loader.minibatch_data.shape[0])
    in_shape = (b,) + tuple(loader.minibatch_data.shape[1:])
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    x = jax.jit(lambda k: jax.random.normal(k, in_shape, jnp.float32))(k1)
    lbl = np.asarray(loader.minibatch_labels.mem)
    # flat (N*S,) sequence labels reveal tokens-per-sample as the row
    # blow-up over the loader's minibatch
    tokens = max(1, lbl.shape[0] // loader.minibatch_data.shape[0])
    if np.issubdtype(lbl.dtype, np.integer):
        hi = max(2, int(getattr(wf, "n_classes", 0) or lbl.max() + 1))
        y = jax.jit(lambda k: jax.random.randint(
            k, (b * tokens,), 0, hi))(k2)
    else:
        y = jax.jit(lambda k: jax.random.normal(
            k, (b,) + lbl.shape[1:], jnp.float32))(k2)

    step = wf.build_fused_step(mesh=mesh, compute_dtype=compute_dtype)
    state = step.init_state()
    state, _ = step.train_repeat(state, x, y, steps)   # compile + warm
    _sync(state)
    best = float("inf")
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        state, _ = step.train_repeat(state, x, y, steps)
        _sync(state)
        best = min(best, time.perf_counter() - t0)
    return best / steps


def apply_cached(wf, *, compute_dtype=None,
                 cache: Optional[AutotuneCache] = None,
                 cache_path: Optional[str] = None) -> Dict[str, str]:
    """Select previously persisted winners for this workflow's tunable
    ops WITHOUT any timing (cache hits only; misses keep the current
    selection). The cheap way for bench/serving runs to inherit a
    tuning session's decisions. Returns {op: variant} of what applied."""
    import jax

    if not getattr(wf, "is_initialized", False):
        wf.initialize(device=None)
    cache = cache or AutotuneCache(cache_path)
    device_kind = jax.devices()[0].device_kind
    compute_dtype = _resolve_compute_dtype(compute_dtype)
    applied: Dict[str, str] = {}
    for op, sigs in discover_tunables(wf).items():
        hit = cache.get(op_cache_key(device_kind, op, sigs, compute_dtype))
        if hit is not None and variants.has(op, hit.get("variant")):
            variants.select(op, hit["variant"])
            applied[op] = hit["variant"]
    return applied


def autotune_workflow(wf, *, mesh=None, compute_dtype=None,
                      steps: int = 4, repeats: int = 2,
                      batch: Optional[int] = None,
                      cache: Optional[AutotuneCache] = None,
                      cache_path: Optional[str] = None,
                      force: bool = False,
                      ops: Optional[List[str]] = None
                      ) -> Dict[str, Dict[str, Any]]:
    """Tune every tunable op the workflow contains; leave the winners
    selected in the registry; return a per-op report:

        {op: {"variant": name, "source": "cache"|"tuned",
              "timings_s": {...}(tuned only), "key": cache-key}}

    Ops are tuned sequentially, each candidate timed with every OTHER op
    held at its current selection. `force=True` re-times cache hits.
    """
    import jax

    if not getattr(wf, "is_initialized", False):
        wf.initialize(device=None)
    cache = cache or AutotuneCache(cache_path)
    device_kind = jax.devices()[0].device_kind
    compute_dtype = _resolve_compute_dtype(compute_dtype)
    on_cpu = jax.default_backend() == "cpu"
    tunables = discover_tunables(wf)
    if ops:
        tunables = {k: v for k, v in tunables.items() if k in ops}
    report: Dict[str, Dict[str, Any]] = {}
    ctx = variants.pallas_interpret() if on_cpu \
        else contextlib.nullcontext()
    with ctx:
        for op in sorted(tunables):
            key = op_cache_key(device_kind, op, tunables[op],
                               compute_dtype)
            hit = None if force else cache.get(key)
            if hit is not None and variants.has(op, hit.get("variant")):
                variants.select(op, hit["variant"])
                report[op] = {"variant": hit["variant"],
                              "source": "cache", "key": key}
                continue
            cands = [v.name for v in variants.variants_for(op)
                     if v.tunable
                     and (not v.pallas or variants.pallas_ok())]
            prev = variants.selected(op)
            timings: Dict[str, Any] = {}
            for name in cands:
                variants.select(op, name)
                try:
                    timings[name] = _time_variant(
                        wf, mesh, compute_dtype, steps, repeats, batch)
                except Exception as e:  # noqa: BLE001 — one broken
                    # candidate (e.g. a pallas kernel a backend rejects)
                    # must not abort the whole tune
                    timings[name] = f"error: {e!s:.200}"
            ok = {k: v for k, v in timings.items()
                  if isinstance(v, float)}
            if not ok:
                # nothing measurable: restore the pre-tune state
                if prev is None:
                    variants.clear_selection(op)
                else:
                    variants.select(op, prev)
                report[op] = {"variant": variants.effective(op),
                              "source": "error", "timings_s": timings,
                              "key": key}
                continue
            winner = min(ok, key=ok.get)
            variants.select(op, winner)
            rounded = {k: (round(v, 6) if isinstance(v, float) else v)
                       for k, v in timings.items()}
            cache.put(key, {"variant": winner, "timings_s": rounded,
                            "device_kind": device_kind,
                            "steps": steps, "tuned_at": time.time()})
            report[op] = {"variant": winner, "source": "tuned",
                          "timings_s": rounded, "key": key}
    return report
