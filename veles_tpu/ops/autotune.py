"""Persistent autotuner over the lowering-variant registry, plus the
budgeted search over GENERATED candidates (ops.templates).

Two tiers, one cache:

1. Flat enumeration (PR 2): for each tunable op a workflow contains,
   time every registered hand-written candidate IN-GRAPH — a short
   donated `train_repeat` microbench of the whole fused step, the same
   scanned hot loop bench.py measures — pick the fastest.
2. Budgeted search (`budget=N` / CLI `--autotune-budget N`): ops with a
   registered `KernelTemplate` get coordinate descent over the template
   config space, seeded from the hand-written incumbents, spending a
   trial budget ordered by the per-op cost shares in LAYER_PROFILE.json
   (tools/layer_profile.py — where the roofline gap lives). Every
   generated candidate must carry a PASSING ops.reference equivalence
   record (ops.templates ledger) BEFORE it is timeable — `_timed_trial`
   refuses ungated candidates structurally. Trials route through the
   telemetry plane: `veles_autotune_trials_total{op,outcome}` and a
   per-trial span when `--trace` is live.

Decisions persist in an on-disk JSON cache keyed by (device_kind, op,
config-hash, compute_dtype), schema-versioned: a mismatched or corrupt
cache logs once and re-tunes, never errors. A cache hit selects the
stored winner with ZERO timing cost (generated winners re-materialize
from their name). On CPU the pallas candidates run in interpret mode, so
the whole subsystem — search included — is tier-1-testable without a
chip.

Entry points: `autotune_workflow(wf)` (= `StandardWorkflow.autotune()` =
CLI `--autotune [--autotune-budget N]`), `search_workflow` (budgeted
search incl. ops below the unit graph: flash_attn, sgd_update), and
`tools/autotune.py [--budget N]` for the flagship AlexNet step.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import logging
import os
import time
from typing import Any, Callable, Dict, List, Optional

from veles_tpu.logger import Logger
from veles_tpu.ops import variants

__all__ = ["AutotuneCache", "autotune_workflow", "discover_tunables",
           "discover_fusions", "op_cache_key", "default_cache_path",
           "search_workflow", "search_op", "priority_order",
           "default_profile_path"]


def default_cache_path() -> str:
    return (os.environ.get("VELES_AUTOTUNE_CACHE")
            or os.path.join(os.path.expanduser("~"), ".cache",
                            "veles_tpu", "autotune.json"))


class AutotuneCache(Logger):
    """On-disk JSON decision cache. Flat {key: record} mapping; records
    carry the winning variant plus the timings (and, for searched ops,
    the trial trace) that chose it. The file is explicitly schema-tagged
    (`{"schema": ..., "version": ...}`): a corrupt file, an unknown
    schema or a version skew (old cache under new code or vice versa)
    logs ONCE and behaves as empty — the tuner re-times and the next
    `put` rewrites the file atomically at the current version. Never an
    error."""

    SCHEMA = "veles-autotune"
    VERSION = 2

    def __init__(self, path: Optional[str] = None) -> None:
        super().__init__()
        self.path = path or default_cache_path()
        self._data: Optional[Dict[str, Any]] = None

    def _load(self) -> Dict[str, Any]:
        if self._data is not None:
            return self._data
        try:
            with open(self.path) as f:
                raw = json.load(f)
            entries = raw.get("entries")
            if raw.get("schema", self.SCHEMA) != self.SCHEMA \
                    or raw.get("version") != self.VERSION \
                    or not isinstance(entries, dict):
                raise ValueError(
                    f"schema/version skew (want {self.SCHEMA} "
                    f"v{self.VERSION}, file says "
                    f"{raw.get('schema', '<none>')} "
                    f"v{raw.get('version')})")
            self._data = entries
        except FileNotFoundError:
            self._data = {}
        except (OSError, ValueError, AttributeError) as e:
            # once per cache object: _data caches the empty dict, so a
            # long tuning session doesn't spam this per get()
            self.warning("autotune cache %s unreadable (%s): re-tuning",
                         self.path, e)
            self._data = {}
        return self._data

    def get(self, key: str) -> Optional[Dict[str, Any]]:
        rec = self._load().get(key)
        return dict(rec) if isinstance(rec, dict) else None

    def put(self, key: str, record: Dict[str, Any]) -> None:
        data = self._load()
        data[key] = record
        tmp = f"{self.path}.tmp.{os.getpid()}"
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump({"schema": self.SCHEMA, "version": self.VERSION,
                       "entries": data}, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)   # atomic: readers never see a torn file


def _resolve_compute_dtype(compute_dtype: Any) -> Any:
    """None means 'whatever the fused step would use' — resolve it the
    same way FusedTrainStep does (root.common.precision_type), so cache
    keys agree between a tuner passing None and a run passing None."""
    if compute_dtype is not None:
        return compute_dtype
    try:
        from veles_tpu.config import root
        pt = getattr(root.common, "precision_type", None)
    except Exception:  # noqa: BLE001
        pt = None
    return pt if pt and pt != "float32" else None


def op_cache_key(device_kind: str, op: str, signatures: List[Dict],
                 compute_dtype: Any = None) -> str:
    """One key per (device, op, workflow-op-configuration). The signature
    list covers EVERY instance of the op in the workflow (two LRN layers
    with different shapes are one joint decision — the registry selection
    is global per op), canonicalized so dict ordering can't split keys."""
    blob = json.dumps(signatures, sort_keys=True, default=str)
    h = hashlib.sha256(blob.encode()).hexdigest()[:16]
    cd = str(compute_dtype) if compute_dtype is not None else "f32"
    return f"{device_kind}|{op}|{cd}|{h}"


def discover_tunables(wf) -> Dict[str, List[Dict]]:
    """{op: [signature, ...]} for every tunable op present in the
    workflow. Units opt in by exposing `variant_signature()` (returning
    None when not tunable in this configuration — e.g. an explicit
    per-layer override, or a conv the s2d rewrite can't apply to)."""
    found: Dict[str, List[Dict]] = {}
    for u in getattr(wf, "forwards", ()):
        op = getattr(u, "variant_op", None)
        sig_fn = getattr(u, "variant_signature", None)
        if op is None or sig_fn is None:
            continue
        sig = sig_fn()
        if sig is not None:
            found.setdefault(op, []).append(sig)
    return found


def discover_fusions(wf) -> Dict[str, List[Dict]]:
    """{fusion_op: [signature, ...]} for every adjacent unit pair a
    fusion template could claim in this workflow (today: lrn followed by
    a max pooling — max flavor, no per-layer overrides on either side;
    the same gate FusedTrainStep.fusion_pairs applies at trace time).
    The signature joins BOTH members' variant signatures, so a fused
    winner's cache key covers the pair's full configuration."""
    found: Dict[str, List[Dict]] = {}
    fwds = list(getattr(wf, "forwards", ()))
    for a, b in zip(fwds, fwds[1:]):
        if getattr(a, "variant_op", None) != "lrn" \
                or getattr(b, "variant_op", None) != "maxpool" \
                or getattr(b, "use_abs", False):
            continue
        if getattr(a, "variant_override", None) is not None \
                or getattr(b, "variant_override", None) is not None:
            continue
        sig_a = a.variant_signature() if hasattr(a, "variant_signature") \
            else None
        sig_b = b.variant_signature() if hasattr(b, "variant_signature") \
            else None
        if sig_a is None or sig_b is None:
            continue
        found.setdefault("lrn_maxpool", []).append(
            {"lrn": sig_a, "maxpool": sig_b})
    return found


@contextlib.contextmanager
def _suspend_fusions(op: str):
    """While a MEMBER op's candidates time, any fusion op claiming it
    stands down: with a fused winner selected the pair is claimed and
    flipping the member's lowering would never change the traced
    program — every candidate would time within noise and a
    noise-picked "winner" would persist under the member's cache key.
    The member's decision is what the UNFUSED trace uses, so it is
    timed unfused; the fusion selection is restored even on error."""
    from veles_tpu.ops import templates
    suspended: Dict[str, str] = {}
    for fop in templates.template_ops():
        if op in templates.fusion_members(fop):
            prev = variants.selected(fop)
            if prev is not None:
                suspended[fop] = prev
            variants.clear_selection(fop)
    try:
        yield
    finally:
        for fop, prev in suspended.items():
            variants.select(fop, prev)


def _sync(state) -> None:
    """Device barrier that works through the remote PJRT tunnel: fetch one
    scalar (block_until_ready is not a reliable barrier there — bench.py
    protocol)."""
    import numpy as np
    for layer in state["params"]:
        for a in layer.values():
            np.asarray(a[(0,) * getattr(a, "ndim", 0)])
            return


def _time_variant(wf, mesh, compute_dtype, steps: int, repeats: int,
                  batch: Optional[int]) -> float:
    """Seconds per training step for the CURRENT registry selection:
    build a fresh fused step (the selection is read at trace time), warm
    it, then time `train_repeat` — one dispatch per window, donated
    state, synthetic device-resident batch (nothing host-side in the
    measurement)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    loader = wf.loader
    b = int(batch or loader.minibatch_data.shape[0])
    in_shape = (b,) + tuple(loader.minibatch_data.shape[1:])
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    x = jax.jit(lambda k: jax.random.normal(k, in_shape, jnp.float32))(k1)
    lbl = np.asarray(loader.minibatch_labels.mem)
    # flat (N*S,) sequence labels reveal tokens-per-sample as the row
    # blow-up over the loader's minibatch
    tokens = max(1, lbl.shape[0] // loader.minibatch_data.shape[0])
    if np.issubdtype(lbl.dtype, np.integer):
        hi = max(2, int(getattr(wf, "n_classes", 0) or lbl.max() + 1))
        y = jax.jit(lambda k: jax.random.randint(
            k, (b * tokens,), 0, hi))(k2)
    else:
        y = jax.jit(lambda k: jax.random.normal(
            k, (b,) + lbl.shape[1:], jnp.float32))(k2)

    step = wf.build_fused_step(mesh=mesh, compute_dtype=compute_dtype)
    state = step.init_state()
    state, _ = step.train_repeat(state, x, y, steps)   # compile + warm
    _sync(state)
    best = float("inf")
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        state, _ = step.train_repeat(state, x, y, steps)
        _sync(state)
        best = min(best, time.perf_counter() - t0)
    return best / steps


def apply_cached(wf, *, compute_dtype=None,
                 cache: Optional[AutotuneCache] = None,
                 cache_path: Optional[str] = None) -> Dict[str, str]:
    """Select previously persisted winners for this workflow's tunable
    ops WITHOUT any timing (cache hits only; misses keep the current
    selection). The cheap way for bench/serving runs to inherit a
    tuning session's decisions — searched winners included: per op the
    SEARCHED key (workflow sigs + template space signature) is probed
    first, then the flat-tuner key, and the template-only ops below the
    unit graph (flash_attn, sgd_update) apply by their space key.
    Generated winners re-materialize from their cached name. Returns
    {op: variant} of what applied."""
    import jax

    from veles_tpu.ops import templates

    if not getattr(wf, "is_initialized", False):
        wf.initialize(device=None)
    cache = cache or AutotuneCache(cache_path)
    device_kind = jax.devices()[0].device_kind
    compute_dtype = _resolve_compute_dtype(compute_dtype)
    keys: Dict[str, List[str]] = {}
    # fusion ops (lrn_maxpool) key like workflow ops: their adjacent-
    # pair signatures join the probe so a searched fused winner applies
    tunables = dict(discover_tunables(wf))
    tunables.update(discover_fusions(wf))
    for op, sigs in tunables.items():
        ks = []
        space = templates.space_signature(op)
        if space:
            ks.append(op_cache_key(device_kind, op, sigs + space,
                                   compute_dtype))
        ks.append(op_cache_key(device_kind, op, sigs, compute_dtype))
        keys[op] = ks
    for op in templates.template_ops():
        sig_fn = EXTRA_OP_SIGS.get(op)
        base = sig_fn() if sig_fn else []
        keys.setdefault(op, [op_cache_key(
            device_kind, op, base + templates.space_signature(op),
            compute_dtype)])
    from veles_tpu.analysis import resources as vres
    applied: Dict[str, str] = {}
    for op, ks in keys.items():
        for key in ks:
            hit = cache.get(key)
            if hit is None or not variants.has(op, hit.get("variant")):
                continue
            # cache-refusal rule (ISSUE 14): a persisted winner whose
            # static VMEM footprint no longer fits THIS device_kind's
            # budget (the cache may have been tuned on a roomier chip,
            # or the budget overridden for a what-if run) is refused —
            # the current selection stands rather than selecting a
            # point that would fail at compile time on-chip
            ver = vres.kernel_verdict(
                op, hit["variant"],
                shapes=vres.shapes_from_signatures(op, tunables.get(op)),
                dtype=compute_dtype, device_kind=device_kind)
            if ver is not None:
                logging.getLogger("veles.autotune").warning(
                    "autotune cache: refusing %s winner %r — VMEM "
                    "footprint %d B exceeds the %s budget %d B",
                    op, hit["variant"], ver["footprint"], device_kind,
                    ver["vmem_budget"])
                continue
            variants.select(op, hit["variant"])
            applied[op] = hit["variant"]
            break
    return applied


def autotune_workflow(wf, *, mesh=None, compute_dtype=None,
                      steps: int = 4, repeats: int = 2,
                      batch: Optional[int] = None,
                      cache: Optional[AutotuneCache] = None,
                      cache_path: Optional[str] = None,
                      force: bool = False,
                      ops: Optional[List[str]] = None,
                      budget: Optional[int] = None,
                      profile_path: Optional[str] = None
                      ) -> Dict[str, Dict[str, Any]]:
    """Tune every tunable op the workflow contains; leave the winners
    selected in the registry; return a per-op report:

        {op: {"variant": name, "source": "cache"|"tuned"|"searched",
              "timings_s": {...}(tuned only), "key": cache-key}}

    Ops are tuned sequentially, each candidate timed with every OTHER op
    held at its current selection. `force=True` re-times cache hits.

    With `budget=N` (CLI `--autotune-budget N`), ops that have a
    registered template (ops.templates) switch from flat enumeration to
    the budgeted coordinate-descent search over GENERATED candidates,
    priority-ordered and budget-weighted by the per-op cost shares in
    LAYER_PROFILE.json; ops without a template keep the enumeration.
    """
    import jax

    if not getattr(wf, "is_initialized", False):
        wf.initialize(device=None)
    cache = cache or AutotuneCache(cache_path)
    device_kind = jax.devices()[0].device_kind
    compute_dtype = _resolve_compute_dtype(compute_dtype)
    on_cpu = jax.default_backend() == "cpu"
    tunables = discover_tunables(wf)
    if ops:
        tunables = {k: v for k, v in tunables.items() if k in ops}
    report: Dict[str, Dict[str, Any]] = {}
    searchable: List[str] = []
    if budget:
        from veles_tpu.ops import templates
        searchable = [op for op in tunables
                      if templates.templates_for(op)
                      and op in templates.CONTRACTS]
        if (not ops or "sgd_update" in ops) \
                and "sgd_update" in templates.CONTRACTS \
                and any(not getattr(g, "optimizer", "sgd") == "adam"
                        for g in getattr(wf, "gds", ())):
            # the fused step's SGD leg resolves the sgd_update registry
            # op (FusedTrainStep._sgd_variant), so its template space
            # belongs in this workflow's search even though no forward
            # unit names it — timed via the template microbench. An
            # explicit `ops` restriction that omits it still wins.
            searchable.append("sgd_update")
        if (not ops or "grad_reduce" in ops) \
                and "grad_reduce" in templates.CONTRACTS \
                and len(jax.devices()) > 1:
            # the dp-mode ZeRO update (on by default) resolves the
            # grad_reduce registry op, so its wire/geometry space rides
            # the budget too — microbench-timed over this host's link
            # geometry, cache-keyed by it (EXTRA_OP_SIGS). Skipped on a
            # single-device host (no axis to exchange over — the
            # microbench would time a degenerate identity) and under an
            # explicit `ops` restriction that omits it.
            searchable.append("grad_reduce")
        for fop in discover_fusions(wf):
            # cross-op fusion spaces (lrn_maxpool): searchable exactly
            # when the workflow contains a claimable adjacent pair —
            # timed IN-GRAPH (selecting a fused point changes what
            # FusedTrainStep traces for the pair)
            if (not ops or fop in ops) and fop in templates.CONTRACTS \
                    and fop not in searchable:
                searchable.append(fop)
    if searchable:
        # ONE search implementation: delegate the template-backed ops
        # to search_workflow (priority order, budget split, in-graph
        # timing) instead of re-implementing its loop here
        report.update(search_workflow(
            wf, ops=searchable, budget=budget, cache=cache,
            compute_dtype=compute_dtype, profile_path=profile_path,
            mesh=mesh, steps=steps, repeats=repeats, batch=batch,
            force=force))
    ctx = variants.pallas_interpret() if on_cpu \
        else contextlib.nullcontext()
    with ctx:
        for op in sorted(set(tunables) - set(searchable)):
            key = op_cache_key(device_kind, op, tunables[op],
                               compute_dtype)
            hit = None if force else cache.get(key)
            if hit is not None and variants.has(op, hit.get("variant")):
                variants.select(op, hit["variant"])
                report[op] = {"variant": hit["variant"],
                              "source": "cache", "key": key}
                continue
            # the flat enumeration is the CLOSED hand-written set:
            # generated (template-materialized) variants only enter
            # through the budgeted search, never the enumeration — a
            # prior search in this process must not widen this path
            cands = [v.name for v in variants.variants_for(op)
                     if v.tunable and not v.generated
                     and (not v.pallas or variants.pallas_ok())]
            prev = variants.selected(op)
            timings: Dict[str, Any] = {}
            with _suspend_fusions(op):
                for name in cands:
                    variants.select(op, name)
                    try:
                        timings[name] = _time_variant(
                            wf, mesh, compute_dtype, steps, repeats,
                            batch)
                    except Exception as e:  # noqa: BLE001 — one broken
                        # candidate (e.g. a pallas kernel a backend
                        # rejects) must not abort the whole tune
                        timings[name] = f"error: {e!s:.200}"
            ok = {k: v for k, v in timings.items()
                  if isinstance(v, float)}
            if not ok:
                # nothing measurable: restore the pre-tune state
                if prev is None:
                    variants.clear_selection(op)
                else:
                    variants.select(op, prev)
                report[op] = {"variant": variants.effective(op),
                              "source": "error", "timings_s": timings,
                              "key": key}
                continue
            winner = min(ok, key=ok.get)
            variants.select(op, winner)
            rounded = {k: (round(v, 6) if isinstance(v, float) else v)
                       for k, v in timings.items()}
            cache.put(key, {"variant": winner, "timings_s": rounded,
                            "device_kind": device_kind,
                            "steps": steps, "tuned_at": time.time()})
            report[op] = {"variant": winner, "source": "tuned",
                          "timings_s": rounded, "key": key}
    return report


# ===========================================================================
# Budgeted search over generated candidates (ops.templates)
# ===========================================================================


def link_geometry_signature() -> List[Dict]:
    """Cache-key payload for cross-device collective ops (grad_reduce):
    the link geometry. A winner tuned on one (hosts x local) topology
    must not silently apply to another — the ISSUE-12 contract that the
    autotune cache is keyed by device/mesh shape for the collective
    family."""
    import jax

    from veles_tpu.ops import variants
    n = len(jax.devices())
    h, loc = variants.grad_reduce_geometry(n)
    return [{"link_geometry": {
        "n_devices": n, "n_processes": jax.process_count(),
        "hosts": h, "local": loc}}]


#: per-op extra cache-key signatures beyond the workflow's op configs —
#: consulted by search_workflow AND apply_cached so a searched winner's
#: key and a later run's probe can never disagree
EXTRA_OP_SIGS: Dict[str, Callable[[], List[Dict]]] = {
    "grad_reduce": link_geometry_signature,
}


def default_profile_path() -> str:
    return os.environ.get("VELES_LAYER_PROFILE_PATH",
                          "LAYER_PROFILE.json")


def priority_order(ops: List[str],
                   profile_path: Optional[str] = None
                   ) -> List[tuple]:
    """[(op, share), ...] most-expensive-first, from the per-op cost
    shares tools/layer_profile.py persists (LAYER_PROFILE.json, env
    VELES_LAYER_PROFILE_PATH; on chip the PR-7 `--profile-window`
    capture feeds the same file). Ops the profile doesn't name keep
    their relative order with share 0 — no profile degrades to the
    given order, never to an error. This is how the budget is spent on
    the ops that own the roofline gap (ROOFLINE.md)."""
    shares: Dict[str, float] = {}
    path = profile_path or default_profile_path()
    try:
        with open(path) as f:
            prof = json.load(f)
        raw = prof.get("ops", {})
        shares = {str(k): float(v) for k, v in raw.items()
                  if isinstance(v, (int, float))}
    except (OSError, ValueError, AttributeError):
        pass

    def share_of(op: str) -> float:
        """A PURE fusion op (lrn_maxpool) is charged against the
        COMBINED share of its member ops — the profile attributes time
        per member (tools/layer_profile.py splits any fused kernel's
        time back), so the pair's candidate budget reflects everything
        a fused winner would replace."""
        from veles_tpu.ops import templates
        s = shares.get(op, 0.0)
        for m in templates.fusion_members(op):
            s += shares.get(m, 0.0)
        return s

    return sorted(((op, share_of(op)) for op in ops),
                  key=lambda kv: -kv[1])


def incumbent_floor(op: str) -> int:
    """Per-op minimum trials: every hand-written incumbent plus at
    least one generated point. Without this, an op with 2+ incumbents
    (flash_attn: xla_mha + pallas) at a zero profile share would spend
    its whole floor on incumbents and never probe its space."""
    hand = [v for v in variants.variants_for(op)
            if v.tunable and not v.generated]
    return len(hand) + 1


def allocate_budget(ordered: List[tuple], budget: int,
                    floors: Optional[Dict[str, int]] = None
                    ) -> Dict[str, int]:
    """Split a total trial budget across ops proportionally to their
    profile shares, with a per-op floor (`floors`, default 2; the
    search passes `incumbent_floor`) so a zero-share op still gets its
    incumbents timed AND at least one generated point probed."""
    if not ordered:
        return {}

    def floor_of(op: str) -> int:
        return max(1, (floors or {}).get(op, 2))

    total_share = sum(s for _, s in ordered)
    out: Dict[str, int] = {}
    remaining = budget - sum(floor_of(op) for op, _ in ordered)
    if remaining < 0:
        # budget too small to floor everyone: highest-share ops win
        left = budget
        for op, _ in ordered:
            out[op] = min(floor_of(op), left)
            left -= out[op]
        return out
    for op, share in ordered:
        frac = (share / total_share) if total_share > 0 \
            else 1.0 / len(ordered)
        out[op] = floor_of(op) + int(remaining * frac)
    # hand leftover integer-division trials to the highest-share op
    leak = budget - sum(out.values())
    if leak > 0:
        out[ordered[0][0]] += leak
    return out


def _trials_counter():
    """veles_autotune_trials_total{op,outcome} on the one PR-7 metrics
    registry; lazily bound (the search is not a hot path — velint's
    hot-metric rule does not apply here)."""
    from veles_tpu.telemetry import metrics as tm
    return tm.default_registry().counter(
        "veles_autotune_trials_total",
        "budgeted-search candidate evaluations by outcome "
        "(timed / equiv_fail / error / pruned)",
        labelnames=("op", "outcome"))


def _prune_verdict(op: str, template, cfg, shapes, compute_dtype,
                   vbudget: Optional[int]) -> Optional[Dict[str, Any]]:
    """The search's static-infeasibility pre-check (ISSUE 14,
    analysis/resources.py): None when the point fits (or no budget /
    footprint rule exists), else {"footprint", "vmem_budget"}. A
    module-level seam on purpose — the ledger-bypass property test
    monkeypatches it away and asserts `_timed_trial`'s independent
    re-check still refuses to time the point."""
    if vbudget is None or template.vmem_footprint is None:
        return None
    try:
        f = int(template.vmem_footprint(cfg, dict(shapes or {}),
                                        compute_dtype))
    except Exception:  # noqa: BLE001 — a broken rule must degrade to
        return None    # "unknown, don't prune", never abort the search
    if f > vbudget:
        return {"footprint": f, "vmem_budget": vbudget}
    return None


def search_op(op: str, *, budget: int,
              cache: Optional[AutotuneCache] = None,
              cache_path: Optional[str] = None,
              compute_dtype: Any = None,
              force: bool = False, repeats: int = 2,
              workflow_sigs: Optional[List[Dict]] = None,
              in_graph_timer: Optional[Callable[[], float]] = None,
              vmem_shapes: Optional[Dict[str, Any]] = None,
              vmem_budget: Optional[int] = None) -> Dict[str, Any]:
    """Budgeted coordinate-descent search over one op's candidate set:
    the hand-written tunable variants first (the incumbents), then the
    template config space, moving one axis at a time from the template
    seed. Every candidate is gated through the ops.reference equivalence
    ledger BEFORE timing — `_timed_trial` raises on an ungated name, so
    the search is structurally unable to time an unverified point.
    Winner is selected in the registry and persisted (with the full
    trial trace) under the same per-(device_kind, op, config-hash,
    compute_dtype) key family as the flat tuner.

    `in_graph_timer` times the CURRENT registry selection inside the
    caller's fused step (the PR-2 protocol — pass a closure over
    `_time_variant`); without one, the template's microbench times the
    candidate's `apply` directly (ops below the unit graph: flash_attn,
    sgd_update)."""
    import jax

    from veles_tpu.analysis import resources as vres
    from veles_tpu.ops import templates
    cache = cache or AutotuneCache(cache_path)
    device_kind = jax.devices()[0].device_kind
    compute_dtype = _resolve_compute_dtype(compute_dtype)
    sigs = list(workflow_sigs or []) + templates.space_signature(op)
    key = op_cache_key(device_kind, op, sigs, compute_dtype)
    hit = None if force else cache.get(key)
    if hit is not None and variants.has(op, hit.get("variant")):
        # the same cache-refusal rule as apply_cached (the budget is
        # NOT part of the cache key): a winner persisted under a
        # roomier budget must not short-circuit a tightened re-run —
        # fall through to the search, which prunes the point
        ver = vres.kernel_verdict(op, hit["variant"],
                                  shapes=vmem_shapes,
                                  dtype=compute_dtype,
                                  device_kind=device_kind,
                                  budget=vmem_budget)
        if ver is None:
            variants.select(op, hit["variant"])
            return {"variant": hit["variant"], "source": "cache",
                    "key": key, "trials": 0}
        logging.getLogger("veles.autotune").warning(
            "autotune cache: refusing %s winner %r — VMEM footprint "
            "%d B exceeds the %s budget %d B; re-searching", op,
            hit["variant"], ver["footprint"], device_kind,
            ver["vmem_budget"])
    if budget < 1:
        # a too-small total budget can allocate an op zero trials:
        # that is a SKIP (current selection stands), not an error —
        # the tool's report must not read like a failed tune
        return {"variant": variants.effective(op), "source": "skipped",
                "key": key, "trials": 0, "trace": [], "budget": budget}

    from veles_tpu.telemetry import tracer as vtrace
    counter = _trials_counter()
    prev = variants.selected(op)
    timings: Dict[str, float] = {}
    trace: List[Dict[str, Any]] = []
    state = {"trials": 0}
    #: per-device VMEM budget for static pruning (analysis pass 6):
    #: None (CPU / unknown device_kind, no override) = pruning inactive
    vbudget = vres.vmem_budget(device_kind, override=vmem_budget)
    pruned: set = set()

    def _timed_trial(name: str) -> float:
        """Time ONE gated candidate. The ledger check is the structural
        gate: no passing equivalence record, no timing — ever. The VMEM
        verdict is its twin (ISSUE 14): an over-budget point is refused
        HERE, independently of the prune branch, so a bypassed prune
        can never reach the timing path."""
        if not templates.passed(op, name):
            raise templates.UngatedCandidateError(
                f"{op}/{name}: refusing to time a candidate with no "
                "passing ops.reference equivalence record")
        ver = vres.kernel_verdict(op, name, shapes=vmem_shapes,
                                  dtype=compute_dtype, budget=vbudget)
        if ver is not None:
            raise vres.InfeasibleCandidateError(
                f"{op}/{name}: refusing to time a candidate whose "
                f"static VMEM footprint ({ver['footprint']} B) exceeds "
                f"the device budget ({ver['vmem_budget']} B)")
        if in_graph_timer is not None:
            variants.select(op, name)
            return in_graph_timer()
        return templates.bench_candidate(
            op, variants.get(op, name).apply, repeats)

    def trial(name: str) -> Optional[float]:
        """Evaluate one candidate (gate, then time). None = skipped
        (dup / budget exhausted / failed); seconds otherwise. Every
        evaluation — including equivalence failures — consumes budget:
        the budget bounds WORK, not successes."""
        if name in timings \
                or any(t["variant"] == name for t in trace):
            return timings.get(name)
        if state["trials"] >= budget:
            return None
        state["trials"] += 1
        rec: Dict[str, Any] = {"variant": name}
        with vtrace.span(f"autotune.trial:{op}/{name}", "autotune"):
            try:
                eq = templates.check_equivalence(op, name)
                if eq["status"] != "pass":
                    rec.update(outcome="equiv_fail",
                               error=eq.get("error", ""))
                    counter.labels(op=op, outcome="equiv_fail").inc()
                else:
                    t = _timed_trial(name)
                    timings[name] = t
                    rec.update(outcome="timed", time_s=round(t, 6))
                    counter.labels(op=op, outcome="timed").inc()
            except (templates.UngatedCandidateError,
                    vres.InfeasibleCandidateError):
                raise   # structural bug, never swallowed as a trial error
            except Exception as e:  # noqa: BLE001 — one broken candidate
                # (a backend-rejected kernel) must not abort the search
                rec.update(outcome="error", error=f"{e!s:.200}")
                counter.labels(op=op, outcome="error").inc()
        trace.append(rec)
        return timings.get(name)

    # 1. incumbents: the hand-written tunable variants seed the search
    for v in variants.variants_for(op):
        if v.tunable and not v.generated \
                and (not v.pallas or variants.pallas_ok()):
            trial(v.name)

    # 2. coordinate descent per template, from the template's seed.
    # Under microbench timing, configs that alias to the SAME effective
    # kernel at the bench shapes (template.bench_key — flash's fit()
    # clamp) are skipped after the first: the budget times distinct
    # kernels and the cached winner names a config that truly executed.
    seen_bench: Dict[Any, str] = {}

    def gen_trial(t, cfg) -> Optional[float]:
        name = t.name(cfg)
        if name in pruned:
            return None
        # static VMEM pruning (ISSUE 14): an over-budget point is
        # statically infeasible — skipped WITHOUT timing it or burning
        # budget, logged per point (the PR-8 no-silent-caps rule) and
        # counted as outcome="pruned" on the trials metric
        ver = _prune_verdict(op, t, cfg, vmem_shapes, compute_dtype,
                             vbudget)
        if ver is not None:
            pruned.add(name)
            counter.labels(op=op, outcome="pruned").inc()
            trace.append({"variant": name, "outcome": "pruned", **ver})
            logging.getLogger("veles.autotune").info(
                "pruned %s/%s: VMEM footprint %d B > %s budget %d B "
                "(never timed, no budget spent)", op, name,
                ver["footprint"], device_kind, ver["vmem_budget"])
            return None
        if in_graph_timer is None and t.bench_key is not None:
            bk = t.bench_key(cfg)
            if seen_bench.setdefault(bk, name) != name:
                return None          # aliases an already-tried point
        return trial(name)

    for t in templates.templates_for(op):
        cur = dict(t.seed)
        best_t = gen_trial(t, cur)
        improved = True
        while improved and state["trials"] < budget:
            improved = False
            for axis in t.axes:
                if state["trials"] >= budget:
                    break
                best_choice = cur[axis.name]
                for c in axis.choices:
                    if c == best_choice:
                        continue
                    tt = gen_trial(t, {**cur, axis.name: c})
                    if tt is not None and (best_t is None
                                           or tt < best_t):
                        best_t, improved = tt, True
                        cur[axis.name] = c
        # descent converged: spend the REMAINING budget exploring
        # still-unseen points of the space in deterministic order (the
        # budget bounds work; leaving trials unspent would just narrow
        # coverage for free) — duplicates/aliases skip without cost
        for cfg in t.configs():
            if state["trials"] >= budget:
                break
            gen_trial(t, cfg)

    if not timings:
        if prev is None:
            variants.clear_selection(op)
        else:
            variants.select(op, prev)
        return {"variant": variants.effective(op), "source": "error",
                "trace": trace, "key": key, "trials": state["trials"]}

    winner = min(timings, key=timings.get)
    variants.select(op, winner)
    win_v = variants.get(op, winner)
    cfg = None
    if win_v.generated:
        for t in templates.templates_for(op):
            cfg = t.parse(winner)
            if cfg is not None:
                break
    record = {
        "variant": winner, "config": cfg,
        "timings_s": {k: round(v, 6) for k, v in timings.items()},
        "trace": trace,
        "equivalence": {t_["variant"]: ("fail" if t_["outcome"]
                                        == "equiv_fail" else "pass")
                        for t_ in trace
                        if t_["outcome"] != "pruned"},
        "pruned": sorted(pruned),
        "budget": budget, "trials": state["trials"],
        "timer": "in_graph" if in_graph_timer is not None
        else "microbench",
        "device_kind": device_kind, "repeats": repeats,
        "tuned_at": time.time(),
    }
    cache.put(key, record)
    return {**record, "source": "searched", "key": key}


def search_workflow(wf=None, *, ops: Optional[List[str]] = None,
                    budget: int = 32,
                    cache: Optional[AutotuneCache] = None,
                    cache_path: Optional[str] = None,
                    compute_dtype: Any = None,
                    profile_path: Optional[str] = None,
                    mesh=None, steps: int = 4, repeats: int = 2,
                    batch: Optional[int] = None,
                    force: bool = False,
                    vmem_budget: Optional[int] = None
                    ) -> Dict[str, Dict[str, Any]]:
    """Budgeted search across every template-backed op: workflow ops
    (lrn, …) time IN-GRAPH through `wf`'s fused step, ops below the unit
    graph (flash_attn, sgd_update) through their template microbench.
    Priority order and budget split come from LAYER_PROFILE.json. The
    per-op reports include the full trial trace; winners are selected
    and persisted like any autotune decision."""
    import jax

    from veles_tpu.ops import templates
    cache = cache or AutotuneCache(cache_path)
    # an explicitly EMPTY ops list means "search nothing" (an --ops
    # restriction that names no template op) — only None means "all"
    all_ops = templates.template_ops() if ops is None else list(ops)
    all_ops = [op for op in all_ops
               if templates.templates_for(op)
               and op in templates.CONTRACTS]
    wf_sigs: Dict[str, List[Dict]] = {}
    if wf is not None:
        if not getattr(wf, "is_initialized", False):
            wf.initialize(device=None)
        wf_sigs = discover_tunables(wf)
        # adjacent fused pairs are in-graph-timeable too: a selected
        # fused point changes what the step traces for the pair
        wf_sigs.update(discover_fusions(wf))
    #: ops the WORKFLOW names (in-graph-timeable) — before the extra
    #: signatures below widen wf_sigs for cache-keying only
    discovered = set(wf_sigs)
    for op, sig_fn in EXTRA_OP_SIGS.items():
        if op in all_ops:
            wf_sigs.setdefault(op, sig_fn())
    on_cpu = jax.default_backend() == "cpu"
    ordered = priority_order(all_ops, profile_path)
    # MEMBER ops tune before their fusion op (stable: share order kept
    # within each group): the fusion decision then competes against
    # tuned member lowerings, not their defaults
    ordered.sort(key=lambda kv: bool(templates.fusion_members(kv[0])))
    shares = allocate_budget(
        ordered, budget,
        floors={op: incumbent_floor(op) for op, _ in ordered})
    report: Dict[str, Dict[str, Any]] = {}
    ctx = variants.pallas_interpret() if on_cpu \
        else contextlib.nullcontext()
    with ctx:
        for op, share in ordered:
            timer = None
            if wf is not None and op in discovered:
                timer = (lambda: _time_variant(
                    wf, mesh, compute_dtype, steps, repeats, batch))
            from veles_tpu.analysis import resources as vres
            with _suspend_fusions(op):   # see the contextmanager's doc
                report[op] = search_op(
                    op, budget=shares[op], cache=cache,
                    compute_dtype=compute_dtype, force=force,
                    repeats=repeats, workflow_sigs=wf_sigs.get(op),
                    in_graph_timer=timer,
                    # static VMEM pruning evaluates each point at the
                    # WORKFLOW's shapes when the op is in-graph (the
                    # kernel a winner would actually trace), else at
                    # the microbench's canonical shapes
                    vmem_shapes=vres.shapes_from_signatures(
                        op, wf_sigs.get(op)),
                    vmem_budget=vmem_budget)
            report[op]["priority_share"] = share
    return report
