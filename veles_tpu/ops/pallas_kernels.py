"""Pallas TPU kernels for ops where manual fusion/control beats stock XLA.

SURVEY.md §7 lists the candidates: LRN backward (two sliding window sums +
elementwise chain — one VMEM pass here vs several XLA reduce_windows),
the fused SGD/momentum update (single read-modify-write over params), and
flash-attention-style blocks (the ring already handles cross-chip; this
kernel is the intra-chip tile loop).

Every kernel has a lax twin in ops.xla / ops.attention — these are
drop-in replacements gated by `available()`, and tests run them in
interpreter mode on CPU against the golden models, so correctness is
pinned even where no TPU is attached (SURVEY.md §4 strategy).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_FORCE_INTERPRET = False  # tests set this on CPU

# ---------------------------------------------------------------------------
# Tuning-axis defaults and hardware bounds. Every per-kernel block/tile
# choice below is a PARAMETER fed from the template config spaces in
# ops/templates.py (the budgeted autotuner searches them); these module
# constants are the documented seeds/bounds of those spaces, not
# per-call-site magic numbers (velint rule `pallas-magic-number` keeps it
# that way).
# ---------------------------------------------------------------------------

#: VPU/MXU lane width — hardware-fixed, NOT a tuning axis
_LANE = 128
#: f32 min sublane tile: the floor every row blocking is clamped to
_MIN_ROW_TILE = 8
#: LRN row-tile heuristic bounds: start at the min sublane tile, stop
#: growing at ~1MB VMEM blocks (see _lrn_call docstring)
_LRN_TILE_MAX = 4096
_LRN_VMEM_BLOCK_BYTES = 1 << 20
#: fused-SGD row blocking seed (the pre-search hand-written value)
_SGD_ROW_TILE = 8
#: fused LRN+maxpool sample tile seed: SAMPLES per VMEM block (each
#: "row" of this kernel's grid is one sample's whole (H, W, C) band —
#: the pooling windows never cross it); 2 keeps AlexNet-L1 blocks near
#: the ~1MB LRN heuristic
_LRN_POOL_ROW_TILE = 2
#: flash-attention block seeds (tuned by hand on v5e 2026-07-29; the
#: search explores the full blk_q x blk_k x kv_order space around them)
_FLASH_BLK_Q = 512
_FLASH_BLK_K = 1024


def flash_fit_block(s: int, blk: int) -> int:
    """The block size `flash_attention_pallas` ACTUALLY runs for a
    requested `blk` at sequence length `s`: shrink to the largest
    power-of-two divisor of S so any S % 128 == 0 sequence works (e.g.
    S=4608 gets blk_k=512). Shared by the kernel wrapper, the search's
    bench-alias key and the static VMEM footprint model
    (ops/templates.py) — the pruned geometry IS the traced geometry."""
    blk = min(blk, s)
    while blk > 128 and s % blk:
        blk //= 2
    return blk


def available() -> bool:
    """True when the default backend can run compiled Pallas TPU kernels."""
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # noqa: BLE001
        return False


def _interpret() -> bool:
    return _FORCE_INTERPRET or not available()


def _pad_rows(x2, row_tile: int):
    rows = x2.shape[0]
    pad = (-rows) % row_tile
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    return x2, rows


# ---------------------------------------------------------------------------
# fused SGD + momentum + weight decay (one VMEM pass over 3 buffers)
# ---------------------------------------------------------------------------


def _sgd_kernel(p_ref, g_ref, v_ref, scal_ref, p_out, v_out):
    lr = scal_ref[0]
    mom = scal_ref[1]
    wd = scal_ref[2]
    g = g_ref[:] + wd * p_ref[:]
    v_new = mom * v_ref[:] - lr * g
    v_out[:] = v_new
    p_out[:] = p_ref[:] + v_new


def sgd_update_pallas(p, g, v, lr, momentum=0.0, weight_decay=0.0,
                      row_tile: int = _SGD_ROW_TILE):
    """Returns (p_new, v_new). Shapes arbitrary; computed as a flattened
    (rows, 128) grid with one row-block per program. `row_tile` is the
    row blocking (a searched tuning axis — ops/templates.py); the
    scalars may be traced (the fused step passes a scheduled lr)."""
    shape, dtype = p.shape, p.dtype
    n = p.size
    cols = _LANE
    rows = -(-n // cols)
    row_tile = max(_MIN_ROW_TILE, int(row_tile))
    padded = rows + ((-rows) % row_tile)

    def flat(a):
        a = a.ravel()
        a = jnp.pad(a, (0, padded * cols - n))
        return a.reshape(padded, cols).astype(jnp.float32)

    p2, g2, v2 = flat(p), flat(g), flat(v)
    scal = jnp.stack([jnp.asarray(lr, jnp.float32),
                      jnp.asarray(momentum, jnp.float32),
                      jnp.asarray(weight_decay, jnp.float32)])
    grid = (padded // row_tile,)
    spec = pl.BlockSpec((row_tile, cols), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    p_new, v_new = pl.pallas_call(
        _sgd_kernel,
        out_shape=(jax.ShapeDtypeStruct((padded, cols), jnp.float32),) * 2,
        grid=grid,
        in_specs=[spec, spec, spec,
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=(spec, spec),
        interpret=_interpret(),
    )(p2, g2, v2, scal)
    return (p_new.ravel()[:n].reshape(shape).astype(dtype),
            v_new.ravel()[:n].reshape(shape).astype(dtype))


# ---------------------------------------------------------------------------
# LRN forward + backward: both sliding channel-window sums in one pass
# ---------------------------------------------------------------------------


def _window_sum(a, half: int):
    """±half across-channel window sum on a (rows, C) VMEM block."""
    out = a
    for d in range(1, half + 1):
        out = out + jnp.pad(a[:, d:], ((0, 0), (0, d))) \
            + jnp.pad(a[:, :-d], ((0, 0), (d, 0)))
    return out


# s^(−β) via sqrt/rsqrt products instead of exp/log — the SAME routine
# the XLA lowering uses, imported so both lowerings share numerics
from veles_tpu.ops.xla import _pow_neg_quarters as _pow_neg  # noqa: E402


def _lrn_fwd_kernel(x_ref, y_ref, *, half: int, k: float, alpha: float,
                    beta: float):
    x = x_ref[:].astype(jnp.float32)
    ssum = _window_sum(x * x, half)
    y_ref[:] = (x * _pow_neg(k + alpha * ssum, beta)).astype(y_ref.dtype)


def _lrn_bwd_kernel(x_ref, e_ref, out_ref, *, half: int, k: float,
                    alpha: float, beta: float):
    x = x_ref[:].astype(jnp.float32)
    err = e_ref[:].astype(jnp.float32)
    s = k + alpha * _window_sum(x * x, half)
    d = _pow_neg(s, beta)                     # s^(−β)
    tsum = _window_sum(err * x * d / s, half)  # W(g·x·s^(−β−1))
    out_ref[:] = (err * d
                  - 2.0 * alpha * beta * x * tsum).astype(out_ref.dtype)


def _lrn_row_tile(n_rows: int, c: int, itemsize: int) -> int:
    """The hand-written heuristic: grow the tile until blocks reach
    ~1MB of VMEM. Conv-activation LRN inputs have a few hundred thousand
    rows (AlexNet L1: 1024·55·55), so a min-sublane tile dies of grid
    overhead (measured 3.5× slower than XLA); large tiles amortize it."""
    rt = _MIN_ROW_TILE
    while rt < _LRN_TILE_MAX and rt * 2 <= max(n_rows, _MIN_ROW_TILE) \
            and rt * 2 * c * itemsize <= _LRN_VMEM_BLOCK_BYTES:
        rt *= 2
    return rt


def _lrn_call(kernel, args, c: int, k, alpha, beta, n: int,
              row_tile: Optional[int] = None, io_dtype: str = "native"):
    """Common wrapper: flatten leading dims to rows, one row-block per
    program, full channel width per block (windows stay in-block).

    HBM traffic is the whole game (LRN is bandwidth-bound). The two
    tuning axes the search owns (ops/templates.py):
    - `row_tile`: rows per block; None = the ~1MB-VMEM heuristic
      (_lrn_row_tile), which is the hand-written incumbent.
    - `io_dtype`: "native" moves blocks in the caller's dtype (bf16
      under the fused step — HALF the bytes of the old force-f32
      wrapper) and promotes to f32 only inside VMEM; "f32" stages
      f32 blocks through HBM (more traffic, no in-kernel casts).
    Scalars are compile-time constants (lets the pow decompose into
    sqrt/rsqrt — see _pow_neg)."""
    x = args[0]
    rows_shape = x.shape[:-1]
    blk_dt = jnp.float32 if io_dtype == "f32" else x.dtype
    x2s = [a.reshape(-1, c).astype(blk_dt) for a in args]
    n_rows = x2s[0].shape[0]
    if row_tile is None:
        itemsize = max(jnp.dtype(blk_dt).itemsize, 2)
        row_tile = _lrn_row_tile(n_rows, c, itemsize)
    row_tile = max(_MIN_ROW_TILE, int(row_tile))
    x2s_p, rows = zip(*(_pad_rows(a, row_tile) for a in x2s))
    padded = x2s_p[0].shape[0]
    spec = pl.BlockSpec((row_tile, c), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        functools.partial(kernel, half=n // 2, k=float(k),
                          alpha=float(alpha), beta=float(beta)),
        out_shape=jax.ShapeDtypeStruct((padded, c), blk_dt),
        grid=(padded // row_tile,),
        in_specs=[spec] * len(x2s_p),
        out_specs=spec,
        interpret=_interpret(),
    )(*x2s_p)
    return out[:rows[0]].reshape(rows_shape + (c,)).astype(x.dtype)


def lrn_forward_pallas(x, k: float = 2.0, alpha: float = 1e-4,
                       beta: float = 0.75, n: int = 5,
                       row_tile: Optional[int] = None,
                       io_dtype: str = "native"):
    return _lrn_call(_lrn_fwd_kernel, (x,), x.shape[-1], k, alpha, beta,
                     n, row_tile=row_tile, io_dtype=io_dtype)


def lrn_backward_pallas(x, err_y, k: float = 2.0, alpha: float = 1e-4,
                        beta: float = 0.75, n: int = 5,
                        row_tile: Optional[int] = None,
                        io_dtype: str = "native"):
    return _lrn_call(_lrn_bwd_kernel, (x, err_y), x.shape[-1],
                     k, alpha, beta, n, row_tile=row_tile,
                     io_dtype=io_dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6))
def lrn_pallas(x, k: float = 2.0, alpha: float = 1e-4, beta: float = 0.75,
               n: int = 5, row_tile: Optional[int] = None,
               io_dtype: str = "native"):
    """Differentiable fused LRN: Pallas forward AND backward (one VMEM
    pass each vs several XLA reduce_windows). Measured on v5e 2026-07-29:
    LRN was ~26% of the AlexNet fused-step time on the XLA path.
    `row_tile`/`io_dtype` are the searched tuning axes (both passes use
    the same point — one decision per candidate)."""
    return lrn_forward_pallas(x, k, alpha, beta, n, row_tile, io_dtype)


def _lrn_fwd_rule(x, k, alpha, beta, n, row_tile, io_dtype):
    return lrn_forward_pallas(x, k, alpha, beta, n, row_tile, io_dtype), x


def _lrn_bwd_rule(k, alpha, beta, n, row_tile, io_dtype, x, g):
    return (lrn_backward_pallas(x, g, k, alpha, beta, n, row_tile,
                                io_dtype),)


lrn_pallas.defvjp(_lrn_fwd_rule, _lrn_bwd_rule)


# ---------------------------------------------------------------------------
# fused LRN + maxpool: one VMEM pass over the shared activation
# (searched cross-op fusion, ops/templates.py `lrn_maxpool`). LRN and the
# pooling that follows it both stream the SAME activation rows — composed
# they read it from HBM twice (and write the LRN intermediate once);
# fused, each (row_tile, H, W, C) sample band is loaded once, normalized
# and pooled in VMEM, and only the pooled output returns to HBM.
# ---------------------------------------------------------------------------


def _window_sum_last(a, half: int):
    """±half across-channel window sum over the LAST axis of an N-d
    block (the 4-D twin of `_window_sum`)."""
    zeros = [(0, 0)] * (a.ndim - 1)
    out = a
    for d in range(1, half + 1):
        out = out + jnp.pad(a[..., d:], zeros + [(0, d)]) \
            + jnp.pad(a[..., :-d], zeros + [(d, 0)])
    return out


def _pool_out_hw(h: int, w: int, ky: int, kx: int, sy: int, sx: int):
    """Ceil-mode pooled extent (edge windows truncate — the one
    geometry every maxpool golden/lowering/unit shares)."""
    oh = -(-(h - ky) // sy) + 1 if h > ky else 1
    ow = -(-(w - kx) // sx) + 1 if w > kx else 1
    return oh, ow


def _pool_pad_hw(y, ky: int, kx: int, sy: int, sx: int, fill):
    """Pad the spatial axes of (nt, H, W, C) so every ceil-mode window
    is fully resident; returns (padded, oh, ow)."""
    _, h, w, _ = y.shape
    oh, ow = _pool_out_hw(h, w, ky, kx, sy, sx)
    hp = (oh - 1) * sy + ky
    wp = (ow - 1) * sx + kx
    y = jnp.pad(y, ((0, 0), (0, hp - h), (0, wp - w), (0, 0)),
                constant_values=fill)
    return y, oh, ow


def _pool_window_slices(yp, ky, kx, sy, sx, oh, ow):
    """The ky·kx shifted strided views of the padded block — one per
    window tap, each (nt, oh, ow, C), in window scan order (the order
    ties break by, matching the goldens' argmax)."""
    return [yp[:, dy:dy + (oh - 1) * sy + 1:sy,
               dx:dx + (ow - 1) * sx + 1:sx, :]
            for dy in range(ky) for dx in range(kx)]


def _dilate_hw(a, sy: int, sx: int):
    """Stride-dilate the two spatial axes (value at (i, j) lands at
    (i·sy, j·sx)) via interleave-with-zeros — stack+reshape only, no
    scatter (Mosaic-friendly)."""
    nt, oh, ow, c = a.shape
    if sy > 1:
        z = jnp.zeros_like(a)
        a = jnp.stack([a] + [z] * (sy - 1), axis=2) \
            .reshape(nt, oh * sy, ow, c)
    if sx > 1:
        z = jnp.zeros_like(a)
        a = jnp.stack([a] + [z] * (sx - 1), axis=3) \
            .reshape(nt, a.shape[1], ow * sx, c)
    return a


def _place_hw(a, dy: int, dx: int, hp: int, wp: int):
    """Embed a dilated contribution at spatial offset (dy, dx) of an
    (hp, wp) canvas (pad, then crop the zero interleave tail)."""
    a = jnp.pad(a, ((0, 0), (dy, max(0, hp - dy - a.shape[1])),
                    (dx, max(0, wp - dx - a.shape[2])), (0, 0)))
    return a[:, :hp, :wp, :]


def _lrn_pool_fwd_kernel(x_ref, y_ref, *, half: int, k: float,
                         alpha: float, beta: float, ky: int, kx: int,
                         sy: int, sx: int):
    x = x_ref[...].astype(jnp.float32)
    s = k + alpha * _window_sum_last(x * x, half)
    y = x * _pow_neg(s, beta)
    yp, oh, ow = _pool_pad_hw(y, ky, kx, sy, sx, -jnp.inf)
    out = None
    for sl in _pool_window_slices(yp, ky, kx, sy, sx, oh, ow):
        out = sl if out is None else jnp.maximum(out, sl)
    y_ref[...] = out.astype(y_ref.dtype)


def _lrn_pool_bwd_kernel(x_ref, g_ref, dx_ref, *, half: int, k: float,
                         alpha: float, beta: float, ky: int, kx: int,
                         sy: int, sx: int):
    """One-pass backward of the composed pair: recompute the LRN output,
    route the pooled error to each window's FIRST max (the goldens' and
    select_and_scatter's tie rule — equality routing alone would send a
    tied window's gradient to every tied element, e.g. post-ReLU zeros),
    then the closed-form LRN backward — all on the resident block."""
    x = x_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    s = k + alpha * _window_sum_last(x * x, half)
    d = _pow_neg(s, beta)
    y = x * d
    yp, oh, ow = _pool_pad_hw(y, ky, kx, sy, sx, -jnp.inf)
    hp, wp = yp.shape[1], yp.shape[2]
    slices = _pool_window_slices(yp, ky, kx, sy, sx, oh, ow)
    m = slices[0]
    for sl in slices[1:]:
        m = jnp.maximum(m, sl)
    n_taps = ky * kx
    win = None
    for lin, sl in enumerate(slices):
        cand = jnp.where(sl == m, jnp.int32(lin), jnp.int32(n_taps))
        win = cand if win is None else jnp.minimum(win, cand)
    g_lrn_p = None
    for lin, (dy, dx) in enumerate((dy, dx) for dy in range(ky)
                                   for dx in range(kx)):
        placed = _place_hw(
            _dilate_hw(jnp.where(win == lin, g, 0.0), sy, sx),
            dy, dx, hp, wp)
        g_lrn_p = placed if g_lrn_p is None else g_lrn_p + placed
    g_lrn = g_lrn_p[:, :x.shape[1], :x.shape[2], :]
    tsum = _window_sum_last(g_lrn * x * d / s, half)
    dx_ref[...] = (g_lrn * d
                   - (2.0 * alpha * beta) * x * tsum).astype(dx_ref.dtype)


def _lrn_pool_call(kernel, args, out_hwc, k, alpha, beta, n: int,
                   ksize, stride, row_tile: Optional[int],
                   io_dtype: str):
    """Common wrapper: grid over SAMPLE tiles (each program owns
    `row_tile` whole (H, W, C) bands, so both the channel window and the
    pooling windows stay in-block). `row_tile`/`io_dtype` are the
    searched axes (ops/templates.py), exactly the LRN pair's."""
    x = args[0]
    nb = x.shape[0]
    blk_dt = jnp.float32 if io_dtype == "f32" else x.dtype
    rt = max(1, int(row_tile if row_tile is not None
                    else _LRN_POOL_ROW_TILE))
    rt = min(rt, max(nb, 1))
    pad = (-nb) % rt
    xs = []
    for a in args:
        a = a.astype(blk_dt)
        if pad:
            a = jnp.pad(a, ((0, pad),) + ((0, 0),) * (a.ndim - 1))
        xs.append(a)
    in_specs = [pl.BlockSpec((rt,) + a.shape[1:],
                             lambda i: (i, 0, 0, 0),
                             memory_space=pltpu.VMEM) for a in xs]
    out = pl.pallas_call(
        functools.partial(kernel, half=n // 2, k=float(k),
                          alpha=float(alpha), beta=float(beta),
                          ky=int(ksize[0]), kx=int(ksize[1]),
                          sy=int(stride[0]), sx=int(stride[1])),
        out_shape=jax.ShapeDtypeStruct((nb + pad,) + out_hwc, blk_dt),
        grid=((nb + pad) // rt,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((rt,) + out_hwc, lambda i: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(*xs)
    return out[:nb].astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6, 7,
                                                    8))
def lrn_maxpool_pallas(x, k: float = 2.0, alpha: float = 1e-4,
                       beta: float = 0.75, n: int = 5,
                       ksize=(3, 3), stride=(2, 2),
                       row_tile: Optional[int] = None,
                       io_dtype: str = "native"):
    """Differentiable fused LRN→maxpool: ONE row-streaming Pallas pass
    per direction over the shared (N, H, W, C) activation (fwd:
    normalize + pool in VMEM; bwd: recompute + first-max error routing +
    closed-form LRN backward). Ceil-mode pooling geometry, max flavor
    only (maxabs pairs stay composed). Gated by the COMPOSED
    ops.reference golden (`lrn_maxpool_forward/backward`) through the
    equivalence ledger before the search may time it."""
    oh, ow = _pool_out_hw(x.shape[1], x.shape[2], ksize[0], ksize[1],
                          stride[0], stride[1])
    return _lrn_pool_call(_lrn_pool_fwd_kernel, (x,),
                          (oh, ow, x.shape[3]), k, alpha, beta, n,
                          ksize, stride, row_tile, io_dtype)


def _lrn_pool_fwd_rule(x, k, alpha, beta, n, ksize, stride, row_tile,
                       io_dtype):
    return lrn_maxpool_pallas(x, k, alpha, beta, n, ksize, stride,
                              row_tile, io_dtype), x


def _lrn_pool_bwd_rule(k, alpha, beta, n, ksize, stride, row_tile,
                       io_dtype, x, g):
    return (_lrn_pool_call(_lrn_pool_bwd_kernel, (x, g),
                           tuple(x.shape[1:]), k, alpha, beta, n,
                           ksize, stride, row_tile, io_dtype),)


lrn_maxpool_pallas.defvjp(_lrn_pool_fwd_rule, _lrn_pool_bwd_rule)


# ---------------------------------------------------------------------------
# blocked (flash-style) attention: tile over KV inside one chip
# ---------------------------------------------------------------------------


def _flash_kernel(q_ref, k_ref, v_ref, *refs, scale: float, causal: bool,
                  reverse_kv: bool = False, dropped: bool = False):
    """Grid (B·H, q_blocks, k_blocks) with KV innermost: each step streams
    ONE (blk_k, d) K/V tile through VMEM (O(blk) footprint — long-context
    safe) and folds it into the online-softmax scratch; the last KV step
    writes the normalized output block plus the per-row logsumexp (the
    backward's softmax residual). `reverse_kv` visits KV tiles
    last-to-first (the index map streams tile nk−1−t at step t) — the
    online softmax is order-invariant, so numerics match to fp rounding;
    the axis exists for the search to probe prefetch locality. With
    `dropped` (the searched `drop` fusion axis, ops/templates.py) a
    pre-scaled dropout mask streams as a fourth input blocked like Q and
    multiplies the OUTPUT block in the same final write — the composed
    path's extra HBM round trip over the attention output disappears."""
    if dropped:
        mk_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
    else:
        o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    # the KV tile actually resident this step (≠ ki under reverse_kv)
    kt = (nk - 1 - ki) if reverse_kv else ki
    q = q_ref[0]                      # (blk_q, d)
    kb = k_ref[0]                     # (blk_k, d)
    vb = v_ref[0]
    blk_q, blk_k = q.shape[0], kb.shape[0]

    @pl.when(ki == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, -1e30)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def compute():
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_idx = qi * blk_q \
                + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
            k_idx = kt * blk_k \
                + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(k_idx <= q_idx, s, -1e30)
        m = m_scr[:]
        m_new = jnp.maximum(m, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            # a row whose visited tiles are ALL masked so far has
            # m_new == -1e30, where exp(s - m_new) = 1, not 0 — only
            # reachable under reverse_kv (forward order always sees the
            # k_idx == q_idx entry first); guard is free under fwd
            p = jnp.where(s <= -1e29, 0.0, p)
        a = jnp.exp(m - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_scr[:] * a + p.sum(axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * a \
            + jnp.dot(p, vb, preferred_element_type=jnp.float32)

    if causal:
        # a KV tile whose first key is beyond this Q tile's last query is
        # fully masked — skip its two dots entirely (~half the grid at
        # large S; this is the hot path the kernel exists for)
        pl.when(kt * blk_k <= qi * blk_q + blk_q - 1)(compute)
    else:
        compute()

    @pl.when(ki == nk - 1)
    def _():
        o = acc_scr[:] / l_scr[:]
        if dropped:
            o = o * mk_ref[0].astype(jnp.float32)
        o_ref[0] = o
        lse_ref[0] = m_scr[:] + jnp.log(l_scr[:])


def _flash_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref,
                     dq_ref, dq_scr, *, scale: float, causal: bool):
    """dQ with the SAME grid/streaming as the forward (KV innermost):
    recompute P = exp(S·scale − lse) per tile from the saved logsumexp,
    dS = P ⊙ (dO·Vᵀ − D), dQ += dS·K·scale. O(blk) VMEM footprint."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    q = q_ref[0]
    kb = k_ref[0]
    vb = v_ref[0]
    blk_q, blk_k = q.shape[0], kb.shape[0]

    @pl.when(ki == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def compute():
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_idx = qi * blk_q \
                + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
            k_idx = ki * blk_k \
                + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(k_idx <= q_idx, s, -1e30)
        p = jnp.exp(s - lse_ref[0])                       # (blk_q, blk_k)
        dp = jnp.dot(do_ref[0], vb.T,
                     preferred_element_type=jnp.float32)  # (blk_q, blk_k)
        ds = p * (dp - di_ref[0]) * scale
        dq_scr[:] = dq_scr[:] + jnp.dot(
            ds, kb, preferred_element_type=jnp.float32)

    if causal:
        pl.when(ki * blk_k <= qi * blk_q + blk_q - 1)(compute)
    else:
        compute()

    @pl.when(ki == nk - 1)
    def _():
        dq_ref[0] = dq_scr[:]


def _flash_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref,
                      dk_ref, dv_ref, dk_scr, dv_scr, *,
                      scale: float, causal: bool):
    """dK/dV with the transposed streaming order — grid (B·H, k_blocks,
    q_blocks), Q innermost: each KV tile stays VMEM-resident while Q/dO
    tiles stream past. dV += Pᵀ·dO, dK += dSᵀ·Q·scale."""
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)
    q = q_ref[0]
    kb = k_ref[0]
    vb = v_ref[0]
    blk_q, blk_k = q.shape[0], kb.shape[0]

    @pl.when(qi == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def compute():
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_idx = qi * blk_q \
                + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
            k_idx = ki * blk_k \
                + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(k_idx <= q_idx, s, -1e30)
        p = jnp.exp(s - lse_ref[0])
        do = do_ref[0]
        dv_scr[:] = dv_scr[:] + jnp.dot(
            p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - di_ref[0]) * scale
        dk_scr[:] = dk_scr[:] + jnp.dot(
            ds.T, q, preferred_element_type=jnp.float32)

    if causal:
        # a Q tile entirely BEFORE this KV tile contributes nothing
        pl.when(qi * blk_q + blk_q - 1 >= ki * blk_k)(compute)
    else:
        compute()

    @pl.when(qi == nq - 1)
    def _():
        dk_ref[0] = dk_scr[:]
        dv_ref[0] = dv_scr[:]


def _qspec(blk_q, d):
    return pl.BlockSpec((1, blk_q, d), lambda bh, i, t: (bh, i, 0),
                        memory_space=pltpu.VMEM)


def _kspec(blk_k, d):
    return pl.BlockSpec((1, blk_k, d), lambda bh, i, t: (bh, t, 0),
                        memory_space=pltpu.VMEM)


def _flash_fwd_core(qf, kf, vf, scale, causal, blk_q, blk_k,
                    kv_order: str = "fwd", mask=None):
    """(B·H, S, D) f32 in -> (out, lse); lse is (B·H, S, 1). `kv_order`
    "rev" streams KV tiles last-to-first (searched axis). `mask` (same
    shape as qf, pre-scaled 0-or-1/keep) applies dropout to the output
    block inside the kernel's final write (searched `drop` axis)."""
    bh, s, d = qf.shape
    rev = kv_order == "rev"
    nk = s // blk_k
    kernel = functools.partial(_flash_kernel, scale=scale, causal=causal,
                               reverse_kv=rev, dropped=mask is not None)
    if rev:
        kvspec = pl.BlockSpec((1, blk_k, d),
                              lambda b, i, t: (b, nk - 1 - t, 0),
                              memory_space=pltpu.VMEM)
    else:
        kvspec = _kspec(blk_k, d)
    in_specs = [_qspec(blk_q, d), kvspec, kvspec]
    args = [qf, kf, vf]
    if mask is not None:
        in_specs.append(_qspec(blk_q, d))
        args.append(mask)
    out, lse = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((bh, s, d), jnp.float32),
                   jax.ShapeDtypeStruct((bh, s, 1), jnp.float32)),
        grid=(bh, s // blk_q, nk),
        in_specs=in_specs,
        out_specs=(_qspec(blk_q, d), _qspec(blk_q, 1)),
        scratch_shapes=[
            pltpu.VMEM((blk_q, 1), jnp.float32),   # running max
            pltpu.VMEM((blk_q, 1), jnp.float32),   # running denom
            pltpu.VMEM((blk_q, d), jnp.float32),   # unnormalized out
        ],
        interpret=_interpret(),
    )(*args)
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attn(qf, kf, vf, scale, causal, blk_q, blk_k, kv_order):
    return _flash_fwd_core(qf, kf, vf, scale, causal, blk_q, blk_k,
                           kv_order)[0]


def _flash_attn_fwd(qf, kf, vf, scale, causal, blk_q, blk_k, kv_order):
    out, lse = _flash_fwd_core(qf, kf, vf, scale, causal, blk_q, blk_k,
                               kv_order)
    return out, (qf, kf, vf, out, lse)


def _flash_attn_bwd(scale, causal, blk_q, blk_k, kv_order, res, do):
    qf, kf, vf, out, lse = res
    do = do.astype(jnp.float32)
    # D_i = rowsum(dO ⊙ O) — the softmax-jacobian diagonal term; tiny
    # elementwise reduce, XLA fuses it, no kernel needed
    di = jnp.sum(do * out, axis=-1, keepdims=True)        # (bh, s, 1)
    return _flash_bwd_pallas(qf, kf, vf, do, lse, di, scale, causal,
                             blk_q, blk_k)


def _flash_bwd_pallas(qf, kf, vf, do, lse, di, scale, causal,
                      blk_q, blk_k):
    """The two backward pallas_calls (dQ, then dK/dV on the transposed
    grid) — shared by the plain and dropout-fused custom-VJP pairs."""
    bh, s, d = qf.shape
    lspec = pl.BlockSpec((1, blk_q, 1), lambda b, i, t: (b, i, 0),
                         memory_space=pltpu.VMEM)
    dq = pl.pallas_call(
        functools.partial(_flash_dq_kernel, scale=scale, causal=causal),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), jnp.float32),
        grid=(bh, s // blk_q, s // blk_k),
        in_specs=[_qspec(blk_q, d), _kspec(blk_k, d), _kspec(blk_k, d),
                  _qspec(blk_q, d), lspec, lspec],
        out_specs=_qspec(blk_q, d),
        scratch_shapes=[pltpu.VMEM((blk_q, d), jnp.float32)],
        interpret=_interpret(),
    )(qf, kf, vf, do, lse, di)
    # transposed grid: KV outer, Q inner (indices (b, t, i) name the
    # (kv, q) block pair, so the q-side specs index with the LAST axis)
    qspec_t = pl.BlockSpec((1, blk_q, d), lambda b, t, i: (b, i, 0),
                           memory_space=pltpu.VMEM)
    kspec_t = pl.BlockSpec((1, blk_k, d), lambda b, t, i: (b, t, 0),
                           memory_space=pltpu.VMEM)
    lspec_t = pl.BlockSpec((1, blk_q, 1), lambda b, t, i: (b, i, 0),
                           memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_flash_dkv_kernel, scale=scale, causal=causal),
        out_shape=(jax.ShapeDtypeStruct((bh, s, d), jnp.float32),) * 2,
        grid=(bh, s // blk_k, s // blk_q),
        in_specs=[qspec_t, kspec_t, kspec_t, qspec_t, lspec_t, lspec_t],
        out_specs=(kspec_t, kspec_t),
        scratch_shapes=[pltpu.VMEM((blk_k, d), jnp.float32),
                        pltpu.VMEM((blk_k, d), jnp.float32)],
        interpret=_interpret(),
    )(qf, kf, vf, do, lse, di)
    return dq, dk, dv


_flash_attn.defvjp(_flash_attn_fwd, _flash_attn_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_attn_drop(qf, kf, vf, mf, scale, causal, blk_q, blk_k,
                     kv_order):
    """Dropout-fused flash attention: the pre-scaled mask multiplies the
    output block inside the forward kernel's final write."""
    return _flash_fwd_core(qf, kf, vf, scale, causal, blk_q, blk_k,
                           kv_order, mask=mf)[0]


def _flash_attn_drop_fwd(qf, kf, vf, mf, scale, causal, blk_q, blk_k,
                         kv_order):
    out, lse = _flash_fwd_core(qf, kf, vf, scale, causal, blk_q, blk_k,
                               kv_order, mask=mf)
    return out, (qf, kf, vf, mf, out, lse)


def _flash_attn_drop_bwd(scale, causal, blk_q, blk_k, kv_order, res, g):
    qf, kf, vf, mf, out_m, lse = res
    g = g.astype(jnp.float32)
    # grad wrt the UNMASKED attention output is dO = g ⊙ mask (dropout
    # backward); the softmax-jacobian diagonal D = rowsum(dO ⊙ O) equals
    # rowsum(g ⊙ O·mask), so the MASKED output the forward saved feeds
    # it directly — no unmasked residual needed
    do = g * mf
    di = jnp.sum(g * out_m, axis=-1, keepdims=True)
    dq, dk, dv = _flash_bwd_pallas(qf, kf, vf, do, lse, di, scale,
                                   causal, blk_q, blk_k)
    # the mask is RNG output, nothing upstream consumes its gradient
    return dq, dk, dv, jnp.zeros_like(mf)


_flash_attn_drop.defvjp(_flash_attn_drop_fwd, _flash_attn_drop_bwd)


def flash_attention_pallas(q, k, v, scale: Optional[float] = None,
                           causal: bool = False, blk_q: int = _FLASH_BLK_Q,
                           blk_k: int = _FLASH_BLK_K,
                           kv_order: str = "fwd", drop_mask=None):
    """Intra-chip blocked attention, DIFFERENTIABLE (custom-VJP pair of
    Pallas kernels). q/k/v: (B, S, H, D) -> (B, S, H, D). Requires
    S % 128 == 0 (pad upstream). Grid (B·H, S/blk_q, S/blk_k), KV
    innermost, so the (S, S) score matrix never materializes — O(S·D)
    memory instead of O(S²). The backward is recompute-based: the forward
    saves only the per-row logsumexp; dQ streams KV tiles (same grid as
    forward), dK/dV streams Q tiles on the transposed grid. Forward block
    defaults tuned on v5e (2026-07-29: 22 ms vs 51 ms for the XLA einsum
    path at B1·S16384·H8·D64 causal — 2.3× — while small-S workloads
    should just use ops.attention). `blk_q`/`blk_k`/`kv_order` are the
    searched tuning axes (ops/templates.py); kv_order applies to the
    forward's KV streaming (the backward keeps its own fixed orders).
    `drop_mask` ((B, S, H, D), pre-scaled 0-or-1/keep — the dropout
    registry op's output) fuses the dropout over the attention output
    into the kernel's final write (the searched `drop` axis; gated by
    the composed `ops.reference.attn_dropout_forward` golden)."""
    b, s, h, d = q.shape
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    blk_q, blk_k = flash_fit_block(s, blk_q), flash_fit_block(s, blk_k)
    assert s % blk_q == 0 and s % blk_k == 0, \
        f"seq len {s} must be divisible by 128 (got blocks {blk_q},{blk_k})"

    def heads_first(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    if drop_mask is None:
        out = _flash_attn(heads_first(q).astype(jnp.float32),
                          heads_first(k).astype(jnp.float32),
                          heads_first(v).astype(jnp.float32),
                          float(scale), causal, blk_q, blk_k, kv_order)
    else:
        out = _flash_attn_drop(
            heads_first(q).astype(jnp.float32),
            heads_first(k).astype(jnp.float32),
            heads_first(v).astype(jnp.float32),
            heads_first(jnp.asarray(drop_mask)).astype(jnp.float32),
            float(scale), causal, blk_q, blk_k, kv_order)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3).astype(q.dtype)
