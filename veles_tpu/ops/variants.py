"""Lowering-variant registry: every tunable op's candidate lowerings.

The round-4 headline (+43–51% samples/s) came entirely from swapping op
lowerings — banded-matmul LRN, the s2d conv stem — yet each variant was a
hand-flipped class attribute (`LRNormalizerForward.prefer_pallas`,
`MaxPooling.lowering`, conv `s2d`) exercised only by one-off scripts when
a chip happened to be up. This module makes the choice systematic, the
same way VELES solved kernel selection with its per-backend unit registry
(SURVEY.md §4) and TorchInductor solves it with autotuned lowering choice
plus a persistent cache (Ansel et al., PAPERS.md):

- every tunable op registers its NAMED candidate lowerings here, each
  carrying an equivalence contract against `ops.reference` (enforced by
  tests/test_variants_autotune.py: fwd AND bwd, Pallas via interpret
  mode on CPU);
- units consult `resolve()` at fused-step trace time instead of reading
  scattered class attributes (those attributes survive as deprecation
  shims that write through to `select()`);
- the autotuner (`ops.autotune`, `tools/autotune.py`, `--autotune`)
  times candidates in-graph and persists the winner; `selection_table()`
  is embedded into bench records and the supervisor's exit report so a
  measured number always names the lowerings that produced it.

Adding a variant is ONE `register()` call (see docs/AUTOTUNE.md) — it is
then automatically equivalence-tested, tunable, cacheable and reported.

This module imports no jax at module scope on purpose: the resilience
supervisor (import-light by design) reads `selection_table()` for its
exit report.
"""

from __future__ import annotations

import contextlib
import threading
import warnings
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "Variant", "register_op", "register", "ops", "variants_for", "get",
    "has", "select", "selected", "effective", "clear_selection",
    "selection_table", "resolve", "pallas_ok", "pallas_interpret",
    "warn_deprecated_knob",
]


@dataclass(frozen=True)
class Variant:
    """One candidate lowering for a tunable op.

    `apply` is the canonical callable for the op's documented signature
    (see the per-op sections below); `pallas` marks lowerings that need a
    compiled Pallas path (gated by `pallas_ok()`, interpret mode on CPU);
    `tunable=False` marks resolution-only pseudo-variants (e.g. dropout
    "auto") the autotuner must not time as candidates; `generated=True`
    marks template-materialized candidates (ops.templates) — search-
    produced points whose name encodes their config."""

    op: str
    name: str
    apply: Callable[..., Any]
    pallas: bool = False
    tunable: bool = True
    generated: bool = False
    doc: str = ""


@dataclass
class _OpSpec:
    op: str
    default: str
    fallback: str           # non-pallas stand-in when pallas is unusable
    doc: str = ""
    variants: Dict[str, Variant] = field(default_factory=dict)


_OPS: Dict[str, _OpSpec] = {}
#: global op -> variant-name selection (autotuner / tools / shims write it)
_selection: Dict[str, str] = {}
_lock = threading.Lock()
#: tests and the CPU autotune path set this so pallas variants resolve in
#: interpret mode where no TPU is attached (tier-1 testability)
_PALLAS_INTERPRET = False


def register_op(op: str, default: str, fallback: Optional[str] = None,
                doc: str = "") -> None:
    _OPS[op] = _OpSpec(op=op, default=default,
                       fallback=fallback or default, doc=doc)


def register(variant: Variant) -> Variant:
    spec = _OPS.get(variant.op)
    if spec is None:
        raise KeyError(f"unknown tunable op {variant.op!r}; register_op "
                       f"first (known: {sorted(_OPS)})")
    spec.variants[variant.name] = variant
    return variant


def ops() -> List[str]:
    return sorted(_OPS)


def variants_for(op: str) -> List[Variant]:
    return list(_spec(op).variants.values())


def _spec(op: str) -> _OpSpec:
    try:
        return _OPS[op]
    except KeyError:
        raise KeyError(f"unknown tunable op {op!r} "
                       f"(registered: {sorted(_OPS)})") from None


def _lookup(op: str, name: Any) -> Optional[Variant]:
    """Registered variant, or a template point materialized on demand —
    the path a persisted generated-winner name takes in a fresh process
    (ops.templates names are parseable back into their config)."""
    spec = _spec(op)
    v = spec.variants.get(name)
    if v is None and isinstance(name, str) and "[" in name:
        from veles_tpu.ops import templates
        v = templates.materialize(op, name)
    return v


def get(op: str, name: str) -> Variant:
    v = _lookup(op, name)
    if v is None:
        raise KeyError(
            f"unknown variant {name!r} for op {op!r} "
            f"(registered: {sorted(_spec(op).variants)})")
    return v


def has(op: str, name: Any) -> bool:
    return op in _OPS and _lookup(op, name) is not None


def select(op: str, name: str) -> None:
    """Pin op's lowering globally (validates both names)."""
    get(op, name)
    with _lock:
        _selection[op] = name


def selected(op: str) -> Optional[str]:
    return _selection.get(op)


def effective(op: str) -> str:
    """The variant name resolve() would use absent per-unit overrides."""
    return _selection.get(op, _spec(op).default)


def clear_selection(op: Optional[str] = None) -> None:
    with _lock:
        if op is None:
            _selection.clear()
        else:
            _selection.pop(op, None)


def selection_table(include_defaults: bool = False) -> Dict[str, str]:
    """{op: variant-name} snapshot — what a record should report. With
    `include_defaults`, ops without an explicit selection report their
    default, so the table always names every tunable op."""
    if not include_defaults:
        return dict(_selection)
    return {op: effective(op) for op in _OPS}


def pallas_ok() -> bool:
    """Can a pallas variant actually run here? True on a TPU backend, or
    anywhere while `pallas_interpret()` is active."""
    if _PALLAS_INTERPRET:
        return True
    try:
        from veles_tpu.ops import pallas_kernels as pk
        return pk.available()
    except Exception:  # noqa: BLE001 — no jax / broken backend: no pallas
        return False


@contextlib.contextmanager
def pallas_interpret():
    """Resolve (and run) pallas variants in interpret mode — the CPU
    autotune/tier-1-test path. pallas_kernels._interpret() already
    interprets whenever no TPU is attached; this flag only lifts the
    resolve()-time gating."""
    global _PALLAS_INTERPRET
    prev = _PALLAS_INTERPRET
    _PALLAS_INTERPRET = True
    try:
        yield
    finally:
        _PALLAS_INTERPRET = prev


def resolve(op: str, unit: Any = None) -> Variant:
    """The variant a unit must trace NOW. Precedence:
    1. the unit's explicit per-instance `variant_override` (constructor
       knobs like MaxPooling(lowering=...));
    2. the global selection (autotuner cache / tools / legacy shims);
    3. the op's registered default.
    Pallas variants additionally need `pallas_ok()` AND the unit's
    `allow_pallas` (FusedTrainStep clears it under GSPMD
    auto-partitioning — a pallas_call cannot be auto-partitioned);
    otherwise the op's non-pallas fallback is traced instead.
    """
    spec = _spec(op)
    name = getattr(unit, "variant_override", None) if unit is not None \
        else None
    if name is None:
        name = _selection.get(op, spec.default)
    v = get(op, name)
    if v.pallas and not (pallas_ok()
                         and getattr(unit, "allow_pallas", True)):
        v = get(op, spec.fallback)
    return v


def warn_deprecated_knob(old: str, new: str) -> None:
    warnings.warn(
        f"{old} is deprecated: the fused-step build path no longer reads "
        f"it; this write is shimmed onto the lowering-variant registry "
        f"({new}). See docs/AUTOTUNE.md.",
        DeprecationWarning, stacklevel=3)


# ===========================================================================
# Registered ops. apply() bodies lazy-import jax-bearing modules so this
# module stays importable from jax-free processes (resilience supervisor).
# ===========================================================================

# -- LRN forward+backward (one op: fwd and bwd ride one custom_vjp) ---------
#    apply(x, *, k, alpha, beta, n) -> y; differentiable.

def _lrn_banded(x, *, k, alpha, beta, n):
    from veles_tpu.ops import xla as ox
    return ox.lrn_forward(x, k, alpha, beta, n, cache_bwd=False)


def _lrn_cached(x, *, k, alpha, beta, n):
    from veles_tpu.ops import xla as ox
    return ox.lrn_forward(x, k, alpha, beta, n, cache_bwd=True)


def _lrn_pallas(x, *, k, alpha, beta, n):
    from veles_tpu.ops import pallas_kernels as pk
    return pk.lrn_pallas(x, k, alpha, beta, n)


register_op(
    "lrn", default="banded_matmul", fallback="banded_matmul",
    doc="AlexNet across-channel LRN, forward + custom-VJP backward "
        "(~24% of the AlexNet step after the r4 banded-matmul rewrite)")
register(Variant("lrn", "banded_matmul", _lrn_banded,
                 doc="XLA banded-matmul window sum; bwd recomputes s/d"))
register(Variant("lrn", "cached_residual", _lrn_cached,
                 doc="same lowering, forward d=s^(-beta) and s stashed as "
                     "residuals: bwd drops one window dot + the pow chain "
                     "for two activation-sized residuals"))
register(Variant("lrn", "pallas_one_pass", _lrn_pallas, pallas=True,
                 doc="one-VMEM-pass Pallas kernel pair (native-dtype HBM "
                     "I/O, sqrt/rsqrt pow)"))


# -- max pooling (fused-step lowering; the knob is the BACKWARD shape) ------
#    apply(x, ksize, stride, use_abs) -> y; differentiable.

def _maxpool_reduce_window(x, ksize, stride, use_abs):
    from veles_tpu.ops import xla as ox
    if use_abs:
        # the custom-comparator reduce_window has no reverse-mode rule;
        # the patches/argmax formulation differentiates (gather vjp)
        return ox.maxpool_forward_with_idx(x, ksize, stride,
                                           use_abs=True)[0]
    return ox.maxpool_forward(x, ksize, stride, False)


def _maxpool_slices(x, ksize, stride, use_abs):
    from veles_tpu.ops import xla as ox
    return ox.maxpool_forward_slices(x, ksize, stride, use_abs)


register_op(
    "maxpool", default="reduce_window",
    doc="max/maxabs pooling in the fused step; the variants differ in "
        "what the BACKWARD lowers to")
register(Variant("maxpool", "reduce_window", _maxpool_reduce_window,
                 doc="lax.reduce_window; backward = select_and_scatter"))
register(Variant("maxpool", "slices", _maxpool_slices,
                 doc="max-fold over ky*kx shifted strided slices; "
                     "backward = selects + zero-pads (fusion-friendly)"))


# -- conv stem: strided thin-channel entry conv -----------------------------
#    apply(x, w, b, stride, padding, activation) -> y; differentiable.
#    Units with s2d="auto" consult resolve("conv_stem") for the decision;
#    explicit s2d="on"/"off" stays a per-layer override.

def _conv_direct(x, w, b, stride, padding, activation):
    from veles_tpu.ops import xla as ox
    return ox.conv2d_forward(x, w, b, stride, padding, activation,
                             s2d=False)


def _conv_s2d(x, w, b, stride, padding, activation):
    from veles_tpu.ops import xla as ox
    return ox.conv2d_forward(x, w, b, stride, padding, activation,
                             s2d=True)


register_op(
    "conv_stem", default="s2d",
    doc="strided thin-channel (cin<8) entry conv: direct vs the exact "
        "space-to-depth rewrite (r4 on-chip winner, 8656 -> 9377)")
register(Variant("conv_stem", "direct", _conv_direct,
                 doc="plain lax.conv_general_dilated"))
register(Variant("conv_stem", "s2d", _conv_s2d,
                 doc="space-to-depth repack: stride-1 conv on full MXU "
                     "tiles, numerics identical"))


# -- gradient reduce-scatter (the ZeRO update's collective leg) -------------
#    apply(flat_partial, axis_name) -> this shard's summed slice.
#    `flat_partial` is one param leaf's per-shard partial gradient,
#    flattened and zero-padded to a multiple of the axis size
#    (parallel.mesh.zero_flatten); the variant reduce-scatters it over
#    the named data axis so each shard receives only the 1/N slice of
#    the SUMMED gradient it owns under the update-sharding plan
#    (arxiv 2004.13336). Seeded with f32 (exact) and bf16 (wire dtype
#    halved; equivalence contract at a stated tolerance) so the EQuARX
#    int8 blockwise-scaled / error-feedback variants (arxiv 2506.17615)
#    are a pure follow-on `register()` — the fused step already resolves
#    the collective through here.

def _grad_reduce_f32(flat, axis_name):
    from jax import lax
    return lax.psum_scatter(flat, axis_name, scatter_dimension=0,
                            tiled=True)


def _grad_reduce_bf16(flat, axis_name):
    import jax.numpy as jnp
    from jax import lax
    return lax.psum_scatter(
        flat.astype(jnp.bfloat16), axis_name, scatter_dimension=0,
        tiled=True).astype(flat.dtype)


register_op(
    "grad_reduce", default="f32",
    doc="ZeRO weight-update reduce-scatter of per-shard partial "
        "gradients over the data axis (cross-host this is DCN-bound: "
        "the compressed variants trade gradient bits for wire bytes)")
register(Variant("grad_reduce", "f32", _grad_reduce_f32,
                 doc="exact: psum_scatter in the gradient dtype"))
register(Variant("grad_reduce", "bf16", _grad_reduce_bf16,
                 doc="wire dtype bf16 (bytes ÷2), accumulate + store "
                     "back in the gradient dtype; equivalence contract "
                     "at the trained-loss tolerance stated in "
                     "docs/SCALING.md"))


# -- blocked flash attention (intra-chip tile loop) -------------------------
#    apply(q, k, v, scale=None, causal=False) -> (B, S, H, D);
#    differentiable (the pallas variants are custom-VJP kernel pairs).
#    MultiHeadAttention consults resolve("flash_attn") on its local path
#    when the flash gate says long-S beats the einsum; generated
#    candidates over blk_q x blk_k x kv_order come from ops.templates.

def _flash_xla_mha(q, k, v, scale=None, causal=False):
    from veles_tpu.ops import attention as oa
    return oa.mha_forward(q, k, v, scale=scale, causal=causal)


def _flash_pallas(q, k, v, scale=None, causal=False):
    from veles_tpu.ops import pallas_kernels as pk
    return pk.flash_attention_pallas(q, k, v, scale=scale, causal=causal)


register_op(
    "flash_attn", default="pallas", fallback="xla_mha",
    doc="intra-chip blocked attention for long-S local heads (2.3x the "
        "XLA einsum at S=16384 on v5e); the generated candidates search "
        "blk_q/blk_k/KV-stream order")
register(Variant("flash_attn", "xla_mha", _flash_xla_mha,
                 doc="the einsum golden model (ops.attention.mha_forward"
                     "); right for short S — O(S^2) score matrix"))
register(Variant("flash_attn", "pallas", _flash_pallas, pallas=True,
                 doc="hand-written incumbent: blk 512/1024, forward KV "
                     "order (= templates seed)"))


# -- fused SGD weight update (the step's optimizer leg) ---------------------
#    apply(params, grads, vel, cfg, lr_scale=1.0, mults=None) ->
#    (new_params, new_vel), one LAYER pytree at a time (the fused step
#    resolves this per layer in _apply_update; ZeRO keeps its own
#    slice-wise path). Generated pallas candidates block the flattened
#    (rows, 128) update grid by rows (ops.templates).

def _sgd_xla_tree(params, grads, vel, cfg, lr_scale=1.0, mults=None):
    from veles_tpu.ops import optim
    return optim.sgd_update(params, grads, vel, cfg, lr_scale=lr_scale,
                            mults=mults)


register_op(
    "sgd_update", default="xla_tree", fallback="xla_tree",
    doc="fused SGD+momentum+weight-decay update; XLA fuses the tree "
        "rule into the backward, the pallas candidates trade that for "
        "one explicit VMEM pass over 3 buffers with searched row "
        "blocking")
register(Variant("sgd_update", "xla_tree", _sgd_xla_tree,
                 doc="per-leaf jnp rule (ops.optim.sgd_update); fuses "
                     "into the compiled step"))


# -- dropout mask RNG -------------------------------------------------------
#    apply(key, shape, drop_prob, dtype) -> pre-scaled mask (0 or 1/keep).
#    Streams differ between impls (counter-based either way); equivalence
#    is structural/statistical, like the reference's xorshift-vs-numpy
#    split. "auto" (default) keeps the device-dependent legacy behavior:
#    hardware RBG on accelerators, threefry on CPU (impl-stable goldens).

def _dropout_auto(key, shape, drop_prob, dtype):
    from veles_tpu.ops import xla as ox
    return ox.make_dropout_mask(key, shape, drop_prob, dtype, impl="auto")


def _dropout_threefry(key, shape, drop_prob, dtype):
    from veles_tpu.ops import xla as ox
    return ox.make_dropout_mask(key, shape, drop_prob, dtype,
                                impl="threefry")


def _dropout_rbg(key, shape, drop_prob, dtype):
    from veles_tpu.ops import xla as ox
    return ox.make_dropout_mask(key, shape, drop_prob, dtype, impl="rbg")


register_op(
    "dropout", default="auto",
    doc="dropout mask bit source (~7% of the AlexNet step under "
        "threefry on v5e; RBG measured 4x less wall-clock per mask)")
register(Variant("dropout", "auto", _dropout_auto, tunable=False,
                 doc="backend-dependent default: rbg on accelerators, "
                     "threefry on CPU"))
register(Variant("dropout", "threefry", _dropout_threefry,
                 doc="jax.random counter-based threefry"))
register(Variant("dropout", "rbg", _dropout_rbg,
                 doc="hardware rng_bit_generator (XLA RBG)"))
