"""Lowering-variant registry: every tunable op's candidate lowerings.

The round-4 headline (+43–51% samples/s) came entirely from swapping op
lowerings — banded-matmul LRN, the s2d conv stem — yet each variant was a
hand-flipped class attribute (`LRNormalizerForward.prefer_pallas`,
`MaxPooling.lowering`, conv `s2d`) exercised only by one-off scripts when
a chip happened to be up. This module makes the choice systematic, the
same way VELES solved kernel selection with its per-backend unit registry
(SURVEY.md §4) and TorchInductor solves it with autotuned lowering choice
plus a persistent cache (Ansel et al., PAPERS.md):

- every tunable op registers its NAMED candidate lowerings here, each
  carrying an equivalence contract against `ops.reference` (enforced by
  tests/test_variants_autotune.py: fwd AND bwd, Pallas via interpret
  mode on CPU);
- units consult `resolve()` at fused-step trace time instead of reading
  scattered class attributes (those attributes survive as deprecation
  shims that write through to `select()`);
- the autotuner (`ops.autotune`, `tools/autotune.py`, `--autotune`)
  times candidates in-graph and persists the winner; `selection_table()`
  is embedded into bench records and the supervisor's exit report so a
  measured number always names the lowerings that produced it.

Adding a variant is ONE `register()` call (see docs/AUTOTUNE.md) — it is
then automatically equivalence-tested, tunable, cacheable and reported.

This module imports no jax at module scope on purpose: the resilience
supervisor (import-light by design) reads `selection_table()` for its
exit report.
"""

from __future__ import annotations

import contextlib
import threading
import warnings
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "Variant", "register_op", "register", "ops", "variants_for", "get",
    "has", "select", "selected", "effective", "clear_selection",
    "selection_table", "resolve", "pallas_ok", "pallas_interpret",
    "warn_deprecated_knob", "grad_reduce_apply", "grad_reduce_config",
    "grad_reduce_geometry", "grad_reduce_local_request",
    "grad_reduce_resid_len", "grad_reduce_bytes", "q8_encode",
    "q8_decode", "GRAD_REDUCE_LOCAL_ENV", "serve_forward_apply",
    "serve_forward_config", "serve_prepare_params", "serve_param_bytes",
]


@dataclass(frozen=True)
class Variant:
    """One candidate lowering for a tunable op.

    `apply` is the canonical callable for the op's documented signature
    (see the per-op sections below); `pallas` marks lowerings that need a
    compiled Pallas path (gated by `pallas_ok()`, interpret mode on CPU);
    `tunable=False` marks resolution-only pseudo-variants (e.g. dropout
    "auto") the autotuner must not time as candidates; `generated=True`
    marks template-materialized candidates (ops.templates) — search-
    produced points whose name encodes their config."""

    op: str
    name: str
    apply: Callable[..., Any]
    pallas: bool = False
    tunable: bool = True
    generated: bool = False
    #: stateful lowerings carry a per-shard residual through the caller's
    #: state (grad_reduce error feedback: apply(flat, axis, resid) ->
    #: (slice, new_resid)); consumers that can't host the slot must not
    #: select one
    stateful: bool = False
    doc: str = ""


@dataclass
class _OpSpec:
    op: str
    default: str
    fallback: str           # non-pallas stand-in when pallas is unusable
    doc: str = ""
    variants: Dict[str, Variant] = field(default_factory=dict)


_OPS: Dict[str, _OpSpec] = {}
#: global op -> variant-name selection (autotuner / tools / shims write it)
_selection: Dict[str, str] = {}
_lock = threading.Lock()
#: tests and the CPU autotune path set this so pallas variants resolve in
#: interpret mode where no TPU is attached (tier-1 testability)
_PALLAS_INTERPRET = False


def register_op(op: str, default: str, fallback: Optional[str] = None,
                doc: str = "") -> None:
    _OPS[op] = _OpSpec(op=op, default=default,
                       fallback=fallback or default, doc=doc)


def register(variant: Variant) -> Variant:
    spec = _OPS.get(variant.op)
    if spec is None:
        raise KeyError(f"unknown tunable op {variant.op!r}; register_op "
                       f"first (known: {sorted(_OPS)})")
    spec.variants[variant.name] = variant
    return variant


def ops() -> List[str]:
    return sorted(_OPS)


def variants_for(op: str) -> List[Variant]:
    return list(_spec(op).variants.values())


def _spec(op: str) -> _OpSpec:
    try:
        return _OPS[op]
    except KeyError:
        raise KeyError(f"unknown tunable op {op!r} "
                       f"(registered: {sorted(_OPS)})") from None


def _lookup(op: str, name: Any) -> Optional[Variant]:
    """Registered variant, or a template point materialized on demand —
    the path a persisted generated-winner name takes in a fresh process
    (ops.templates names are parseable back into their config)."""
    spec = _spec(op)
    v = spec.variants.get(name)
    if v is None and isinstance(name, str) and "[" in name:
        from veles_tpu.ops import templates
        v = templates.materialize(op, name)
    return v


def get(op: str, name: str) -> Variant:
    v = _lookup(op, name)
    if v is None:
        raise KeyError(
            f"unknown variant {name!r} for op {op!r} "
            f"(registered: {sorted(_spec(op).variants)})")
    return v


def has(op: str, name: Any) -> bool:
    return op in _OPS and _lookup(op, name) is not None


def select(op: str, name: str) -> None:
    """Pin op's lowering globally (validates both names)."""
    get(op, name)
    with _lock:
        _selection[op] = name


def selected(op: str) -> Optional[str]:
    return _selection.get(op)


def effective(op: str) -> str:
    """The variant name resolve() would use absent per-unit overrides."""
    return _selection.get(op, _spec(op).default)


def clear_selection(op: Optional[str] = None) -> None:
    with _lock:
        if op is None:
            _selection.clear()
        else:
            _selection.pop(op, None)


def selection_table(include_defaults: bool = False) -> Dict[str, str]:
    """{op: variant-name} snapshot — what a record should report. With
    `include_defaults`, ops without an explicit selection report their
    default, so the table always names every tunable op."""
    if not include_defaults:
        return dict(_selection)
    return {op: effective(op) for op in _OPS}


def pallas_ok() -> bool:
    """Can a pallas variant actually run here? True on a TPU backend, or
    anywhere while `pallas_interpret()` is active."""
    if _PALLAS_INTERPRET:
        return True
    try:
        from veles_tpu.ops import pallas_kernels as pk
        return pk.available()
    except Exception:  # noqa: BLE001 — no jax / broken backend: no pallas
        return False


@contextlib.contextmanager
def pallas_interpret():
    """Resolve (and run) pallas variants in interpret mode — the CPU
    autotune/tier-1-test path. pallas_kernels._interpret() already
    interprets whenever no TPU is attached; this flag only lifts the
    resolve()-time gating."""
    global _PALLAS_INTERPRET
    prev = _PALLAS_INTERPRET
    _PALLAS_INTERPRET = True
    try:
        yield
    finally:
        _PALLAS_INTERPRET = prev


def resolve(op: str, unit: Any = None) -> Variant:
    """The variant a unit must trace NOW. Precedence:
    1. the unit's explicit per-instance `variant_override` (constructor
       knobs like MaxPooling(lowering=...));
    2. the global selection (autotuner cache / tools / legacy shims);
    3. the op's registered default.
    Pallas variants additionally need `pallas_ok()` AND the unit's
    `allow_pallas` (FusedTrainStep clears it under GSPMD
    auto-partitioning — a pallas_call cannot be auto-partitioned);
    otherwise the op's non-pallas fallback is traced instead.
    """
    spec = _spec(op)
    name = getattr(unit, "variant_override", None) if unit is not None \
        else None
    if name is None:
        name = _selection.get(op, spec.default)
    v = get(op, name)
    if v.pallas and not (pallas_ok()
                         and getattr(unit, "allow_pallas", True)):
        v = get(op, spec.fallback)
    return v


def warn_deprecated_knob(old: str, new: str) -> None:
    warnings.warn(
        f"{old} is deprecated: the fused-step build path no longer reads "
        f"it; this write is shimmed onto the lowering-variant registry "
        f"({new}). See docs/AUTOTUNE.md.",
        DeprecationWarning, stacklevel=3)


# ===========================================================================
# Registered ops. apply() bodies lazy-import jax-bearing modules so this
# module stays importable from jax-free processes (resilience supervisor).
# ===========================================================================

# -- LRN forward+backward (one op: fwd and bwd ride one custom_vjp) ---------
#    apply(x, *, k, alpha, beta, n) -> y; differentiable.

def _lrn_banded(x, *, k, alpha, beta, n):
    from veles_tpu.ops import xla as ox
    return ox.lrn_forward(x, k, alpha, beta, n, cache_bwd=False)


def _lrn_cached(x, *, k, alpha, beta, n):
    from veles_tpu.ops import xla as ox
    return ox.lrn_forward(x, k, alpha, beta, n, cache_bwd=True)


def _lrn_pallas(x, *, k, alpha, beta, n):
    from veles_tpu.ops import pallas_kernels as pk
    return pk.lrn_pallas(x, k, alpha, beta, n)


register_op(
    "lrn", default="banded_matmul", fallback="banded_matmul",
    doc="AlexNet across-channel LRN, forward + custom-VJP backward "
        "(~24% of the AlexNet step after the r4 banded-matmul rewrite)")
register(Variant("lrn", "banded_matmul", _lrn_banded,
                 doc="XLA banded-matmul window sum; bwd recomputes s/d"))
register(Variant("lrn", "cached_residual", _lrn_cached,
                 doc="same lowering, forward d=s^(-beta) and s stashed as "
                     "residuals: bwd drops one window dot + the pow chain "
                     "for two activation-sized residuals"))
register(Variant("lrn", "pallas_one_pass", _lrn_pallas, pallas=True,
                 doc="one-VMEM-pass Pallas kernel pair (native-dtype HBM "
                     "I/O, sqrt/rsqrt pow)"))


# -- max pooling (fused-step lowering; the knob is the BACKWARD shape) ------
#    apply(x, ksize, stride, use_abs) -> y; differentiable.

def _maxpool_reduce_window(x, ksize, stride, use_abs):
    from veles_tpu.ops import xla as ox
    if use_abs:
        # the custom-comparator reduce_window has no reverse-mode rule;
        # the patches/argmax formulation differentiates (gather vjp)
        return ox.maxpool_forward_with_idx(x, ksize, stride,
                                           use_abs=True)[0]
    return ox.maxpool_forward(x, ksize, stride, False)


def _maxpool_slices(x, ksize, stride, use_abs):
    from veles_tpu.ops import xla as ox
    return ox.maxpool_forward_slices(x, ksize, stride, use_abs)


register_op(
    "maxpool", default="reduce_window",
    doc="max/maxabs pooling in the fused step; the variants differ in "
        "what the BACKWARD lowers to")
register(Variant("maxpool", "reduce_window", _maxpool_reduce_window,
                 doc="lax.reduce_window; backward = select_and_scatter"))
register(Variant("maxpool", "slices", _maxpool_slices,
                 doc="max-fold over ky*kx shifted strided slices; "
                     "backward = selects + zero-pads (fusion-friendly)"))


# -- lrn_maxpool: the searched (lrn, maxpool) CROSS-OP fusion ---------------
#    apply(x, *, k, alpha, beta, n, ksize, stride) -> pooled output;
#    differentiable. A PURE fusion op (ISSUE 13): "composed" is the
#    incumbent (identical math to the two units tracing separately);
#    the generated ``fused[rt=..,io=..,fuse=..]`` points come from
#    ops.templates, every one gated on the COMPOSED ops.reference
#    golden. When a fused winner is selected, FusedTrainStep lets the
#    normalization unit claim its pooling successor's work (the pooling
#    unit becomes a pass-through for that trace) — see
#    parallel/fused.py fusion_pairs().

def _lrn_maxpool_composed(x, *, k, alpha, beta, n, ksize, stride):
    from veles_tpu.ops import xla as ox
    y = ox.lrn_forward(x, k, alpha, beta, n)
    return ox.maxpool_forward(y, tuple(ksize), tuple(stride), False)


register_op(
    "lrn_maxpool", default="composed", fallback="composed",
    doc="searched cross-op fusion of an adjacent (lrn, maxpool) unit "
        "pair: both ops stream the same activation rows, so the fused "
        "Pallas point does LRN then pooling in ONE VMEM pass "
        "(ops/templates.py; LRN alone was ~24% of the AlexNet step "
        "pre-Pallas — ROOFLINE.md)")
register(Variant("lrn_maxpool", "composed", _lrn_maxpool_composed,
                 doc="the unfused incumbent: member lowerings traced "
                     "separately (XLA LRN + reduce_window pooling)"))


# -- conv stem: strided thin-channel entry conv -----------------------------
#    apply(x, w, b, stride, padding, activation) -> y; differentiable.
#    Units with s2d="auto" consult resolve("conv_stem") for the decision;
#    explicit s2d="on"/"off" stays a per-layer override.

def _conv_direct(x, w, b, stride, padding, activation):
    from veles_tpu.ops import xla as ox
    return ox.conv2d_forward(x, w, b, stride, padding, activation,
                             s2d=False)


def _conv_s2d(x, w, b, stride, padding, activation):
    from veles_tpu.ops import xla as ox
    return ox.conv2d_forward(x, w, b, stride, padding, activation,
                             s2d=True)


register_op(
    "conv_stem", default="s2d",
    doc="strided thin-channel (cin<8) entry conv: direct vs the exact "
        "space-to-depth rewrite (r4 on-chip winner, 8656 -> 9377)")
register(Variant("conv_stem", "direct", _conv_direct,
                 doc="plain lax.conv_general_dilated"))
register(Variant("conv_stem", "s2d", _conv_s2d,
                 doc="space-to-depth repack: stride-1 conv on full MXU "
                     "tiles, numerics identical"))


# -- gradient reduce-scatter (the ZeRO update's collective leg) -------------
#    apply(flat_partial, axis_name, resid=None) -> this shard's summed
#    slice; STATEFUL (error-feedback) variants return (slice, new_resid).
#    `flat_partial` is one param leaf's per-shard partial gradient,
#    flattened and zero-padded to a multiple of the axis size
#    (parallel.mesh.zero_flatten); the variant reduce-scatters it over
#    the named data axis so each shard receives only the 1/N slice of
#    the SUMMED gradient it owns under the update-sharding plan
#    (arxiv 2004.13336). Cross-host that exchange rides DCN, where bytes
#    — not FLOPs — bound scaling efficiency, so the family trades
#    gradient bits for wire bytes (EQuARX, arxiv 2506.17615):
#
#    - f32 / bf16: psum_scatter in the wire dtype (exact / bytes ÷2);
#    - int8_block: per-block absmax-scaled int8 codes, the f32 scales
#      riding the SAME all-to-all exchange, dequantize-accumulate in
#      f32 (bytes ÷~4 at blk=256);
#    - int8_ef:   int8_block + error feedback — the quantization
#      residual is carried per shard in the ZeRO flat-vector state (the
#      step's "ef" slot) and added back before the next quantization,
#      so the compression error telescopes instead of accumulating;
#    - hier2:     two-level decomposition over the (hosts x local)
#      factorization of the data axis: ICI-local reduce-scatter in the
#      gradient dtype, then the DCN exchange moves only the 1/n_local
#      slices (DCN bytes ÷n_local) — the CPU 8-device mesh tests it as
#      (hosts=2, local=4) via VELES_GRAD_REDUCE_LOCAL;
#    - the searched family `wire[dt=..,blk=..,ef=..,hier=..]`
#      (ops.templates) composes all four axes; every point is built by
#      the ONE `grad_reduce_apply` below and equivalence-gated against
#      the ops.reference quantization goldens before the budgeted
#      search may time it.
#
#    All collective calls live in THIS module by the velint
#    stray-collective contract. The byte model (`grad_reduce_bytes`)
#    feeds veles_collective_bytes_total; docs/SCALING.md states the
#    per-variant math and the trained-loss tolerances.

GRAD_REDUCE_LOCAL_ENV = "VELES_GRAD_REDUCE_LOCAL"

#: canonical configs of the named (hand-registered) family members —
#: shared by registration, `grad_reduce_config` and the byte model
_GR_NAMED: Dict[str, Dict[str, Any]] = {
    "f32": {"dt": "f32", "blk": 0, "ef": 0, "hier": 0},
    "bf16": {"dt": "bf16", "blk": 0, "ef": 0, "hier": 0},
    "int8_block": {"dt": "int8", "blk": 256, "ef": 0, "hier": 0},
    "int8_ef": {"dt": "int8", "blk": 256, "ef": 1, "hier": 0},
    "hier2": {"dt": "f32", "blk": 0, "ef": 0, "hier": 1},
}


def grad_reduce_local_request(n_shards: int) -> int:
    """The UNCLAMPED ICI-group-size request for the hierarchical
    variants: env VELES_GRAD_REDUCE_LOCAL (explicit geometry — CPU
    tests, odd topologies) or this process's local device count. The
    jaxpr auditor checks an explicit request divides the data axis;
    `grad_reduce_geometry` below clamps a non-dividing request to the
    LARGEST DIVISOR it does not exceed — the traced op then runs that
    different (but always-valid) decomposition, never a crash."""
    import os
    raw = os.environ.get(GRAD_REDUCE_LOCAL_ENV)
    if raw:
        try:
            return int(raw)
        except ValueError:
            return 0
    try:
        import jax
        return jax.local_device_count()
    except Exception:  # noqa: BLE001 — no backend: treat as single-host
        return n_shards


def grad_reduce_geometry(n_shards: int) -> tuple:
    """(n_hosts, n_local): the two-level factorization of the data axis
    the hierarchical variants decompose over. n_local is the request
    clamped to the largest divisor of n_shards it does not exceed, so
    the groups always tile the axis; (1, n) or (n, 1) geometries make
    the hierarchy degenerate and `grad_reduce_apply` falls back to the
    flat exchange."""
    loc = grad_reduce_local_request(n_shards)
    loc = max(1, min(int(loc), n_shards))
    while n_shards % loc:
        loc -= 1
    return n_shards // loc, loc


def grad_reduce_config(name: Any) -> Optional[Dict[str, Any]]:
    """Canonical EFFECTIVE config {dt, blk, ef, hier} for any
    grad_reduce variant name — named incumbents or template-generated
    ``wire[...]`` points; None for foreign names. Error feedback is an
    int8-only mechanism: ef (and blk) canonicalize to 0 for float wire
    dtypes, so two names that trace the same program report the same
    config (bytes, state slots and bench aliasing all read this)."""
    cfg = _GR_NAMED.get(name)
    if cfg is not None:
        cfg = dict(cfg)
    elif isinstance(name, str) and "[" in name:
        from veles_tpu.ops import templates
        for t in templates.templates_for("grad_reduce"):
            parsed = t.parse(name)
            if parsed is not None:
                cfg = dict(parsed)
                break
    if cfg is None:
        return None
    if cfg.get("dt") != "int8":
        cfg["ef"] = 0
        cfg["blk"] = 0
    return cfg


def grad_reduce_resid_len(name: str, padded: int,
                          n_shards: int) -> Optional[int]:
    """Per-shard error-feedback residual length for one (padded,) flat
    leaf under the named variant — None for stateless variants. The
    flat int8+EF exchange quantizes the whole per-shard partial
    (residual = padded elements); the hierarchical one applies EF to
    the DCN leg only, AFTER the ICI reduce-scatter, so its residual is
    the 1/n_local slice. One rule shared by the traced op, the step's
    state allocation and the checkpoint geometry — they can never
    disagree."""
    cfg = grad_reduce_config(name)
    if not cfg or not cfg["ef"]:
        return None
    if cfg["hier"]:
        h, loc = grad_reduce_geometry(n_shards)
        if h > 1 and loc > 1:
            return padded // loc
    return padded


def grad_reduce_bytes(name: str, n_elems: int,
                      n_shards: int) -> Dict[str, Any]:
    """Modeled per-device egress bytes per step of the grad_reduce
    exchange (plus the param all-gather leg for context), split by link
    leg under the (hosts x local) geometry. The model counts gradient
    payload a device must move to peers: off-host destinations are DCN,
    on-host are ICI; int8 wire adds the per-block f32 scale overhead
    (4/blk bytes per element). This is the producer behind
    veles_collective_bytes_total (docs/SCALING.md states the math) —
    modeled from the collective's algorithm and the plan sizes, since
    XLA exposes no per-collective wire counters."""
    cfg = grad_reduce_config(name) or dict(_GR_NAMED["f32"])
    h, loc = grad_reduce_geometry(n_shards)
    item = {"f32": 4.0, "bf16": 2.0, "int8": 1.0}[cfg["dt"]]
    if cfg["dt"] == "int8" and cfg["blk"]:
        item += 4.0 / cfg["blk"]      # the scales ride the same exchange
    n = n_shards
    if cfg["hier"] and h > 1 and loc > 1:
        # phase 1 (ICI): reduce-scatter within the local group, in the
        # gradient dtype; phase 2 (DCN): only the 1/local slices cross
        ici = n_elems * (loc - 1) / loc * 4.0
        dcn = (n_elems / loc) * (h - 1) / h * item
    else:
        dcn = n_elems * (n - loc) / n * item
        ici = n_elems * (loc - 1) / n * item
    return {"dcn_bytes": int(dcn), "ici_bytes": int(ici),
            "allgather_dcn_bytes": int(n_elems / n * (n - loc) * 4.0),
            "allgather_ici_bytes": int(n_elems / n * (loc - 1) * 4.0),
            "geometry": {"hosts": h, "local": loc},
            "config": cfg}


def q8_encode(x2, blk: int):
    """jax twin of ops.reference.quantize_blockwise over the last axis
    of a 2-D (rows, cols) array, zero-padding cols up to a block
    multiple. Returns (codes int8 (rows, colsp), scales f32
    (rows, colsp//blk)). BITWISE-identical to the numpy golden — the
    grad_reduce equivalence contract asserts it."""
    import jax.numpy as jnp
    rows, cols = x2.shape
    pad = (-cols) % blk
    if pad:
        x2 = jnp.pad(x2, ((0, 0), (0, pad)))
    xb = x2.reshape(rows, -1, blk)
    absmax = jnp.max(jnp.abs(xb), axis=-1)
    scale = jnp.where(absmax > 0, absmax / jnp.float32(127.0),
                      jnp.float32(1.0))
    q = jnp.clip(jnp.round(xb / scale[..., None]), -127, 127) \
        .astype(jnp.int8)
    return q.reshape(rows, -1), scale


def q8_decode(q, scale, blk: int):
    """jax twin of ops.reference.dequantize_blockwise (2-D rows form)."""
    import jax.numpy as jnp
    rows = q.shape[0]
    xb = q.reshape(rows, -1, blk).astype(jnp.float32)
    return (xb * scale[..., None]).reshape(rows, -1)


def _q8_exchange(x, axis_name, blk, resid, groups, local, want_resid):
    """Blockwise-int8 exchange-and-accumulate: quantize each destination
    row (per-block absmax scales), all_to_all the codes AND the scales
    in one pattern (the scale exchange rides the same scatter),
    dequantize and accumulate in f32. `x` is (rows, local) with row j
    bound for exchange-group member j; returns (my summed (local,)
    slice, new residual (rows*local,) or None)."""
    import jax.numpy as jnp  # noqa: F401 — q8 helpers carry the math
    from jax import lax
    if resid is not None:
        x = x + resid.reshape(x.shape)
    q, s = q8_encode(x, blk)
    new_resid = None
    if want_resid:
        new_resid = (x - q8_decode(q, s, blk)[:, :local]).reshape(-1)
    kw = {"axis_index_groups": groups} if groups is not None else {}
    q_r = lax.all_to_all(q, axis_name, 0, 0, tiled=True, **kw)
    s_r = lax.all_to_all(s, axis_name, 0, 0, tiled=True, **kw)
    out = q8_decode(q_r, s_r, blk)[:, :local].sum(axis=0)
    return out, new_resid


def grad_reduce_apply(cfg: Dict[str, Any]) -> Callable[..., Any]:
    """Build the canonical grad_reduce apply for one config point — the
    ONE implementation behind every named incumbent and every generated
    ``wire[...]`` candidate. Stateful (EF) applies ALWAYS return
    (slice, new_resid); resid=None means a zero residual. The closure
    carries its canonical config as ``apply.gr_config`` so the
    equivalence contract can pick per-dtype tolerances without a second
    naming scheme."""
    dt = cfg["dt"]
    blk = int(cfg.get("blk") or 256)
    ef = bool(cfg.get("ef")) and dt == "int8"
    hier = bool(cfg.get("hier"))

    def apply(flat, axis_name, resid=None):
        import jax.numpy as jnp
        from jax import lax

        from veles_tpu._compat import axis_size
        n = axis_size(axis_name)
        h, loc = grad_reduce_geometry(n)
        two_level = hier and h > 1 and loc > 1
        local = flat.shape[0] // n
        new_resid = None
        if two_level:
            lgroups = [[hh * loc + ll for ll in range(loc)]
                       for hh in range(h)]
            cgroups = [[hh * loc + ll for hh in range(h)]
                       for ll in range(loc)]
            # phase 1 (ICI): reduce-scatter within each host's local
            # group, in the gradient dtype — the row order below lands
            # device (host h, local l) exactly the final slices device
            # index h*loc+l owns, matching the flat scatter's layout
            x = flat.astype(jnp.float32).reshape(h, loc, local) \
                .transpose(1, 0, 2)
            x = lax.psum_scatter(x, axis_name, scatter_dimension=0,
                                 axis_index_groups=lgroups, tiled=True)
            x = x.reshape(h, local)   # per-host partials of my slices
            if dt == "int8":
                out, new_resid = _q8_exchange(
                    x, axis_name, blk, resid if ef else None, cgroups,
                    local, ef)
            else:
                w = x.astype(jnp.bfloat16) if dt == "bf16" else x
                out = lax.psum_scatter(
                    w, axis_name, scatter_dimension=0,
                    axis_index_groups=cgroups, tiled=True
                ).reshape(-1).astype(jnp.float32)
        elif dt == "int8":
            x = flat.astype(jnp.float32).reshape(n, local)
            out, new_resid = _q8_exchange(
                x, axis_name, blk, resid if ef else None, None, local,
                ef)
        elif dt == "bf16":
            out = lax.psum_scatter(
                flat.astype(jnp.bfloat16), axis_name,
                scatter_dimension=0, tiled=True).astype(jnp.float32)
        else:
            out = lax.psum_scatter(flat, axis_name,
                                   scatter_dimension=0, tiled=True)
        out = out.astype(flat.dtype)
        return (out, new_resid) if ef else out

    apply.gr_config = {"dt": dt, "blk": blk if dt == "int8" else 0,
                       "ef": int(ef), "hier": int(hier)}
    return apply


register_op(
    "grad_reduce", default="f32",
    doc="ZeRO weight-update reduce-scatter of per-shard partial "
        "gradients over the data axis (cross-host this is DCN-bound: "
        "the compressed/hierarchical variants trade gradient bits and "
        "exchange topology for DCN wire bytes — EQuARX, arxiv "
        "2506.17615)")
register(Variant("grad_reduce", "f32",
                 grad_reduce_apply(_GR_NAMED["f32"]),
                 doc="exact: psum_scatter in the gradient dtype"))
register(Variant("grad_reduce", "bf16",
                 grad_reduce_apply(_GR_NAMED["bf16"]),
                 doc="wire dtype bf16 (bytes ÷2), accumulate + store "
                     "back in the gradient dtype; equivalence contract "
                     "at the trained-loss tolerance stated in "
                     "docs/SCALING.md"))
register(Variant("grad_reduce", "int8_block",
                 grad_reduce_apply(_GR_NAMED["int8_block"]),
                 doc="EQuARX-style blockwise-scaled int8 exchange "
                     "(blk=256): codes + per-block f32 scales ride one "
                     "all_to_all, dequantize-accumulate in f32 — wire "
                     "bytes ~0.26x the f32 scatter"))
register(Variant("grad_reduce", "int8_ef",
                 grad_reduce_apply(_GR_NAMED["int8_ef"]), stateful=True,
                 doc="int8_block + error feedback: the quantization "
                     "residual carries in the ZeRO flat-vector state "
                     "(the step's 'ef' slot) and is added back before "
                     "the next quantization, telescoping the "
                     "compression error"))
register(Variant("grad_reduce", "hier2",
                 grad_reduce_apply(_GR_NAMED["hier2"]),
                 doc="two-level (hosts x local) decomposition: "
                     "ICI-local reduce-scatter, then the DCN exchange "
                     "moves only the 1/n_local slices (DCN bytes "
                     "÷n_local); exact f32 math, trajectory-equal to "
                     "the flat scatter at rtol 1e-5"))


# -- blocked flash attention (intra-chip tile loop) -------------------------
#    apply(q, k, v, scale=None, causal=False) -> (B, S, H, D);
#    differentiable (the pallas variants are custom-VJP kernel pairs).
#    MultiHeadAttention consults resolve("flash_attn") on its local path
#    when the flash gate says long-S beats the einsum; generated
#    candidates over blk_q x blk_k x kv_order come from ops.templates.

def _flash_xla_mha(q, k, v, scale=None, causal=False):
    from veles_tpu.ops import attention as oa
    return oa.mha_forward(q, k, v, scale=scale, causal=causal)


def _flash_pallas(q, k, v, scale=None, causal=False):
    from veles_tpu.ops import pallas_kernels as pk
    return pk.flash_attention_pallas(q, k, v, scale=scale, causal=causal)


register_op(
    "flash_attn", default="pallas", fallback="xla_mha",
    doc="intra-chip blocked attention for long-S local heads (2.3x the "
        "XLA einsum at S=16384 on v5e); the generated candidates search "
        "blk_q/blk_k/KV-stream order")
register(Variant("flash_attn", "xla_mha", _flash_xla_mha,
                 doc="the einsum golden model (ops.attention.mha_forward"
                     "); right for short S — O(S^2) score matrix"))
register(Variant("flash_attn", "pallas", _flash_pallas, pallas=True,
                 doc="hand-written incumbent: blk 512/1024, forward KV "
                     "order (= templates seed)"))


# -- fused SGD weight update (the step's optimizer leg) ---------------------
#    apply(params, grads, vel, cfg, lr_scale=1.0, mults=None) ->
#    (new_params, new_vel), one LAYER pytree at a time (the fused step
#    resolves this per layer in _apply_update; ZeRO keeps its own
#    slice-wise path). Generated pallas candidates block the flattened
#    (rows, 128) update grid by rows (ops.templates).

def _sgd_xla_tree(params, grads, vel, cfg, lr_scale=1.0, mults=None):
    from veles_tpu.ops import optim
    return optim.sgd_update(params, grads, vel, cfg, lr_scale=lr_scale,
                            mults=mults)


register_op(
    "sgd_update", default="xla_tree", fallback="xla_tree",
    doc="fused SGD+momentum+weight-decay update; XLA fuses the tree "
        "rule into the backward, the pallas candidates trade that for "
        "one explicit VMEM pass over 3 buffers with searched row "
        "blocking")
register(Variant("sgd_update", "xla_tree", _sgd_xla_tree,
                 doc="per-leaf jnp rule (ops.optim.sgd_update); fuses "
                     "into the compiled step"))


# -- quantized serving forward (ISSUE 15) -----------------------------------
#    apply(prepared, x, forward, shapes=None) -> f32 output.
#    `forward` is the caller's dense forward ((params, x) -> out — the
#    serving tier passes FusedTrainStep._forward's local trace);
#    `prepared` is the param pytree AFTER this variant's host-side wire
#    transform (`serve_prepare_params`), `shapes` the matching pytree of
#    original leaf shapes (static — needed to undo the int8 padding).
#    The EQuARX-era registry discipline (arxiv 2506.17615) applied to
#    serving: a low-byte serving path is only ever a ledger-gated CONFIG
#    POINT behind the ONE `serve_forward_apply` builder — never a fork
#    of the forward. Equivalence contract: templates._serve_contract
#    runs every variant against ops.reference.serve_forward_mlp with the
#    reference quantizers supplying the golden weight transform
#    (ints BITWISE, forward within per-wire tolerance); the serving tier
#    additionally refuses to SERVE a non-f32 variant without a passing
#    ledger record AND probes it against the f32 forward of the REAL
#    model at startup (veles_tpu/serving.py).
#
#    - f32:  identity wire — the reference point;
#    - bf16: params stored and computed in bfloat16 (model bytes /2),
#      activations cast at entry, output restored to f32;
#    - int8: weight-only — >=2-D float leaves with a full block of
#      columns stored as per-block absmax int8 codes + f32 scales
#      (ops.reference.serve_quantize_weight; model bytes ~/4),
#      dequantized to f32 in-trace so XLA fuses the dequant into the
#      matmul's weight read; 1-D leaves (biases) and sub-block-width
#      leaves stay f32 (negligible bytes / the pad would inflate them
#      — see _serve_quantizable).

_SERVE_NAMED: Dict[str, Dict[str, Any]] = {
    "f32": {"wire": "f32", "blk": 0},
    "bf16": {"wire": "bf16", "blk": 0},
    "int8": {"wire": "int8", "blk": 64},
}


def serve_forward_config(name: Any) -> Optional[Dict[str, Any]]:
    """Canonical config {wire, blk} for a serve_forward variant name
    (None for foreign names)."""
    cfg = _SERVE_NAMED.get(name)
    return dict(cfg) if cfg is not None else None


def _serve_quantizable(a, blk: int) -> bool:
    """int8-wire eligibility: >=2-D float leaves whose last axis holds
    at least one full block — a narrower leaf would zero-PAD up to the
    block and come out LARGER on the wire than its f32 form (measured:
    a (10, 16) weight ballooned 640 B of codes from 640 B of f32).
    Ineligible leaves stay f32; on real layer widths (>= blk) the wire
    is ~bytes/4."""
    import numpy as np
    arr = np.asarray(a)
    return (arr.ndim >= 2 and arr.shape[-1] >= blk
            and np.issubdtype(arr.dtype, np.floating))


def serve_prepare_params(name: str, params):
    """HOST-side wire transform of a (tuple-of-dicts) f32 param pytree
    into `name`'s serving format. Returns (prepared, shapes): int8
    leaves become {"q": codes, "s": scales} dicts built by the
    ops.reference quantizer (the codes ARE the golden — one
    quantization rule for collectives and serving), bf16 leaves are
    cast, f32 passes through; `shapes` records each original leaf shape
    (static metadata the traced dequantize needs to undo padding)."""
    import numpy as np
    cfg = _SERVE_NAMED[name]
    prepared, shapes = [], []
    for layer in params:
        pl: Dict[str, Any] = {}
        sl: Dict[str, tuple] = {}
        for k, a in layer.items():
            arr = np.asarray(a)
            sl[k] = tuple(int(s) for s in arr.shape)
            if cfg["wire"] == "int8" \
                    and _serve_quantizable(arr, cfg["blk"]):
                from veles_tpu.ops import reference
                q, s = reference.serve_quantize_weight(
                    arr.astype(np.float32), cfg["blk"])
                pl[k] = {"q": q, "s": s}
            elif cfg["wire"] == "bf16" \
                    and np.issubdtype(arr.dtype, np.floating):
                import ml_dtypes
                pl[k] = arr.astype(ml_dtypes.bfloat16)
            else:
                pl[k] = arr
        prepared.append(pl)
        shapes.append(sl)
    return tuple(prepared), tuple(shapes)


def serve_param_bytes(prepared) -> int:
    """Wire bytes of a prepared param pytree — the measured form of the
    quantized-serving memory claim (model_info/bench surface it next to
    the f32 model bytes)."""
    import jax
    import numpy as np
    return sum(int(np.asarray(leaf).nbytes)
               for leaf in jax.tree_util.tree_leaves(prepared))


def _serve_restore(cfg, prepared, shapes):
    """Traced inverse of serve_prepare_params: prepared tree -> the
    param tree the dense forward consumes (f32 for int8 wire — the
    dequantize fuses into the weight read; bf16 stays bf16 so the
    forward computes in the wire dtype)."""
    import jax.numpy as jnp
    out = []
    for li, layer in enumerate(prepared):
        d = {}
        for k, v in layer.items():
            if isinstance(v, dict) and "q" in v:
                shp = tuple(shapes[li][k])
                deq = q8_decode(v["q"], v["s"], cfg["blk"])
                d[k] = deq[:, :shp[-1]].reshape(shp)
            else:
                d[k] = v
        out.append(d)
    return tuple(out)


def serve_forward_apply(cfg: Dict[str, Any]) -> Callable[..., Any]:
    """Build the canonical serve_forward apply for one config point —
    the ONE implementation behind every named wire variant. The closure
    carries ``apply.sv_config`` so the equivalence contract can derive
    the matching reference transform without a second naming scheme."""
    cfg = dict(cfg)

    def apply(prepared, x, forward, shapes=None):
        import jax.numpy as jnp
        params = _serve_restore(cfg, prepared, shapes)
        if cfg["wire"] == "bf16":
            x = x.astype(jnp.bfloat16)
        out = forward(params, x)
        return out.astype(jnp.float32)

    apply.sv_config = cfg
    return apply


register_op(
    "serve_forward", default="f32", fallback="f32",
    doc="the serving tier's wire format for model params: f32 "
        "reference, bf16 (bytes /2) and weight-only blockwise int8 "
        "(bytes ~/4) — every low-byte point ledger-gated against the "
        "f32 forward before it may serve (ISSUE 15; the EQuARX "
        "registry discipline, arxiv 2506.17615)")
register(Variant("serve_forward", "f32",
                 serve_forward_apply(_SERVE_NAMED["f32"]),
                 doc="identity wire: the trained f32 params as-is"))
register(Variant("serve_forward", "bf16",
                 serve_forward_apply(_SERVE_NAMED["bf16"]),
                 doc="params stored + computed in bfloat16 (model "
                     "bytes /2), output restored to f32"))
register(Variant("serve_forward", "int8",
                 serve_forward_apply(_SERVE_NAMED["int8"]),
                 doc="weight-only per-block absmax int8 (blk=64, model "
                     "bytes ~/4): codes quantized by the ops.reference "
                     "golden on the host, dequantized in-trace so XLA "
                     "fuses the dequant into the weight read"))


# -- dropout mask RNG -------------------------------------------------------
#    apply(key, shape, drop_prob, dtype) -> pre-scaled mask (0 or 1/keep).
#    Streams differ between impls (counter-based either way); equivalence
#    is structural/statistical, like the reference's xorshift-vs-numpy
#    split. "auto" (default) keeps the device-dependent legacy behavior:
#    hardware RBG on accelerators, threefry on CPU (impl-stable goldens).

def _dropout_auto(key, shape, drop_prob, dtype):
    from veles_tpu.ops import xla as ox
    return ox.make_dropout_mask(key, shape, drop_prob, dtype, impl="auto")


def _dropout_threefry(key, shape, drop_prob, dtype):
    from veles_tpu.ops import xla as ox
    return ox.make_dropout_mask(key, shape, drop_prob, dtype,
                                impl="threefry")


def _dropout_rbg(key, shape, drop_prob, dtype):
    from veles_tpu.ops import xla as ox
    return ox.make_dropout_mask(key, shape, drop_prob, dtype, impl="rbg")


register_op(
    "dropout", default="auto",
    doc="dropout mask bit source (~7% of the AlexNet step under "
        "threefry on v5e; RBG measured 4x less wall-clock per mask)")
register(Variant("dropout", "auto", _dropout_auto, tunable=False,
                 doc="backend-dependent default: rbg on accelerators, "
                     "threefry on CPU"))
register(Variant("dropout", "threefry", _dropout_threefry,
                 doc="jax.random counter-based threefry"))
register(Variant("dropout", "rbg", _dropout_rbg,
                 doc="hardware rng_bit_generator (XLA RBG)"))
