"""Search-generated kernel candidates: parameterized Pallas templates.

The PR-2 registry (ops/variants.py) made lowering choice systematic, but
its candidate set was closed — a handful of hand-written lowerings per
op, so the autotuner could never find a point the hand-written set
doesn't contain. Following "Agentic Operator Generation for ML ASICs"
(arxiv 2512.10977, PAPERS.md), this module makes the set GENERATED:

- a `KernelTemplate` names an op's tuning axes (a typed config space —
  the frozen constants of ops/pallas_kernels.py turned parameters:
  LRN row-tile + dtype staging, flash-attention blk_q/blk_k/KV-stream
  order, fused-SGD row blocking) and builds a concrete candidate
  callable from any point in the space;
- every generated point registers through `ops.variants` under a
  parseable name (``base[axis=value,...]``), so resolve()/select()/
  selection_table() treat it exactly like a hand-written variant, and a
  persisted winner re-materializes in a fresh process from its name
  alone (`materialize`, hooked into `variants.get`);
- the EQUIVALENCE LEDGER is the structural correctness gate: a
  candidate is timeable ONLY after `check_equivalence` records a pass
  against the op's `ops.reference` contract (fwd + bwd, Pallas via
  interpret mode on CPU). The budgeted search (ops/autotune.py) refuses
  to time an ungated candidate — correctness is structural, not
  hoped-for.

No jax at module scope: variants.py (jax-free by design, the resilience
supervisor imports it) calls into `materialize` from `get()`; all
jax-bearing work lives inside template builders, contracts and benches.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from veles_tpu.ops import variants

__all__ = [
    "Axis", "KernelTemplate", "register_template", "templates_for",
    "template_ops", "materialize", "space_signature",
    "check_equivalence", "equivalence_record", "passed", "clear_ledger",
    "ledger_table", "bench_candidate", "UngatedCandidateError",
    "fusion_members", "fusion_config", "fusion_point",
]


class UngatedCandidateError(RuntimeError):
    """Raised when something tries to time a candidate that has no
    passing equivalence record — the structural gate the search rides."""


@dataclass(frozen=True)
class Axis:
    """One typed tuning axis: a name and its finite choice set."""

    name: str
    choices: Tuple[Any, ...]
    doc: str = ""

    def __post_init__(self):
        if not self.choices:
            raise ValueError(f"axis {self.name!r} has no choices")


@dataclass
class KernelTemplate:
    """A parameterized kernel: op + axes + a builder that turns one
    config point into the op's canonical `apply` callable.

    `seed` is the coordinate-descent start point — the hand-written
    incumbent's settings expressed as a config, so the search begins
    where four rounds of manual tuning ended."""

    op: str
    base: str                       # variant-name prefix, e.g. "pallas"
    axes: Tuple[Axis, ...]
    build: Callable[[Dict[str, Any]], Callable[..., Any]]
    seed: Dict[str, Any]
    pallas: bool = True
    doc: str = ""
    #: optional config -> hashable key of the kernel the MICROBENCH
    #: would actually execute (kernels that clamp their parameters to
    #: the input shape — flash fit() — make distinct configs alias at
    #: the bench shapes; the search skips aliases so the budget times
    #: distinct kernels and a cached winner names an executed config)
    bench_key: Optional[Callable[[Dict[str, Any]], Any]] = None
    #: optional config -> bool: does this point carry per-shard state
    #: through the caller (grad_reduce error feedback)? Materialized
    #: variants get Variant.stateful from it so the fused step can size
    #: its state slot from the NAME alone.
    stateful: Optional[Callable[[Dict[str, Any]], bool]] = None
    #: name of the axis that decides whether a point FUSES a neighbor's
    #: work ("fuse"/"epi"/"drop"); a point is a FUSED point when that
    #: axis's value is not in _FUSE_OFF. None = the template has no
    #: fusion structure (a pure tuning-constant space).
    fuse_axis: Optional[str] = None
    #: the member registry ops a pure-fusion op's candidates compose
    #: (lrn_maxpool -> ("lrn", "maxpool")); the budgeted search charges
    #: a fused candidate against the COMBINED profile share of these.
    #: Empty for templates whose op is itself a unit op (conv_stem,
    #: flash_attn — their fuse axis rides the op's own share).
    fuses: Tuple[str, ...] = ()
    #: declarative VMEM model (ISSUE 14, analysis/resources.py):
    #: (config, shapes, dtype) -> resident bytes of the point's Pallas
    #: blocks (double-buffered in/out block bytes + scratch, derived
    #: from the kernel's BlockSpecs in ops/pallas_kernels.py; worst
    #: direction wins). `shapes` is an op-specific dim dict — missing
    #: keys fall back to the rule's canonical bench shapes, the very
    #: kernel the microbench would run. None = no static footprint
    #: (non-Pallas ops): unknown is never pruned.
    vmem_footprint: Optional[
        Callable[[Dict[str, Any], Dict[str, Any], Any], int]] = None

    def __post_init__(self):
        self.seed = self.validate(self.seed)

    # -- config handling ------------------------------------------------------

    def axis(self, name: str) -> Axis:
        for a in self.axes:
            if a.name == name:
                return a
        raise KeyError(f"template {self.op}/{self.base}: no axis {name!r}")

    def validate(self, config: Dict[str, Any]) -> Dict[str, Any]:
        """Canonicalize a config: every axis present, every value in its
        choice set, declaration order."""
        out = {}
        for a in self.axes:
            if a.name not in config:
                raise KeyError(f"template {self.op}/{self.base}: config "
                               f"missing axis {a.name!r}")
            v = config[a.name]
            if v not in a.choices:
                raise ValueError(
                    f"template {self.op}/{self.base}: {a.name}={v!r} not "
                    f"in {a.choices}")
            out[a.name] = v
        extra = set(config) - set(out)
        if extra:
            raise KeyError(f"template {self.op}/{self.base}: unknown "
                           f"axes {sorted(extra)}")
        return out

    @property
    def size(self) -> int:
        n = 1
        for a in self.axes:
            n *= len(a.choices)
        return n

    def configs(self) -> List[Dict[str, Any]]:
        """The full cross product, declaration-ordered."""
        points: List[Dict[str, Any]] = [{}]
        for a in self.axes:
            points = [{**p, a.name: c} for p in points for c in a.choices]
        return points

    # -- naming (the cache/registry identity of a generated point) -----------

    def name(self, config: Dict[str, Any]) -> str:
        cfg = self.validate(config)
        inner = ",".join(f"{k}={cfg[k]}" for k in cfg)
        return f"{self.base}[{inner}]"

    _NAME_RE = re.compile(r"^(?P<base>[A-Za-z0-9_]+)\[(?P<cfg>[^\]]*)\]$")

    def parse(self, name: str) -> Optional[Dict[str, Any]]:
        """Config encoded in a generated-variant name; None when the
        name doesn't belong to this template (wrong base, unknown axis,
        out-of-space value — a stale cache must degrade, not crash)."""
        m = self._NAME_RE.match(name)
        if m is None or m.group("base") != self.base:
            return None
        cfg: Dict[str, Any] = {}
        for part in filter(None, m.group("cfg").split(",")):
            if "=" not in part:
                return None
            k, _, raw = part.partition("=")
            try:
                ax = self.axis(k)
            except KeyError:
                return None
            # decode by the axis's own value type (int axes vs str axes)
            val: Any = raw
            if raw.lstrip("-").isdigit():
                val = int(raw)
            if val not in ax.choices:
                return None
            cfg[k] = val
        try:
            return self.validate(cfg)
        except (KeyError, ValueError):
            return None


_TEMPLATES: Dict[str, List[KernelTemplate]] = {}


def register_template(t: KernelTemplate) -> KernelTemplate:
    _TEMPLATES.setdefault(t.op, []).append(t)
    return t


def templates_for(op: str) -> List[KernelTemplate]:
    return list(_TEMPLATES.get(op, ()))


def template_ops() -> List[str]:
    return sorted(_TEMPLATES)


def materialize(op: str, name: str) -> Optional["variants.Variant"]:
    """Register-on-demand: turn a generated-variant NAME back into a
    live registry entry (the path a persisted cache winner takes in a
    fresh process — `variants.get` falls through to here on a miss).
    None when no template of `op` owns the name."""
    for t in templates_for(op):
        cfg = t.parse(name)
        if cfg is None:
            continue
        v = variants.Variant(
            op=op, name=t.name(cfg), apply=t.build(cfg),
            pallas=t.pallas, generated=True,
            stateful=bool(t.stateful(cfg)) if t.stateful else False,
            doc=f"generated from template {t.base} at {cfg}")
        return variants.register(v)
    return None


# -- cross-op fusion structure (ISSUE 13) -----------------------------------
#: fuse-axis values that mean "do NOT fuse" — the composed point
_FUSE_OFF = (0, "none", "off", None)


def fusion_members(op: str) -> Tuple[str, ...]:
    """The member registry ops whose work a pure-fusion op's candidates
    claim (() for ordinary ops) — the search's combined-share charging
    and tools/layer_profile.py's split both read this."""
    out: List[str] = []
    for t in templates_for(op):
        for m in t.fuses:
            if m not in out:
                out.append(m)
    return tuple(out)


def fusion_config(op: str, name: Any) -> Optional[Dict[str, Any]]:
    """Parsed config of `name` IF it is a FUSED point of one of op's
    templates (its fuse axis is on); None for composed/foreign names —
    the one rule FusedTrainStep, variant_table and the jaxpr auditor
    share to decide whether a selection actually claims a neighbor."""
    for t in templates_for(op):
        if t.fuse_axis is None:
            continue
        cfg = t.parse(name) if isinstance(name, str) else None
        if cfg is not None and cfg.get(t.fuse_axis) not in _FUSE_OFF:
            return cfg
    return None


def fusion_point(op: str, unit: Any = None):
    """The variant `op` resolves to right now IF that resolution is a
    FUSED point (pallas gating included — under GSPMD or a pallas-less
    backend resolve() falls back to the composed incumbent and this
    returns None). The trace-time gate behind the pass-through-unit
    rule."""
    v = variants.resolve(op, unit=unit)
    return v if fusion_config(op, v.name) is not None else None


def space_signature(op: str) -> List[Dict[str, Any]]:
    """Cache-key payload for a template-searched op: the config space
    itself (a changed axis/choice set must invalidate old decisions the
    same way a changed layer shape does for workflow ops)."""
    return [{
        "template": t.base,
        "axes": {a.name: list(a.choices) for a in t.axes},
        "seed": dict(t.seed),
    } for t in templates_for(op)]


# ===========================================================================
# Equivalence ledger — the structural gate between generation and timing
# ===========================================================================

#: op -> contract callable(apply) -> detail dict; RAISES on mismatch.
#: Contracts compare against ops.reference (numpy goldens) forward AND
#: backward on small canonical shapes; Pallas candidates run in
#: interpret mode on CPU automatically (pallas_kernels._interpret()).
CONTRACTS: Dict[str, Callable[[Callable], Dict[str, Any]]] = {}

#: (op, variant-name) -> {"status": "pass"|"fail", ...}
_LEDGER: Dict[Tuple[str, str], Dict[str, Any]] = {}


def check_equivalence(op: str, name: str,
                      force: bool = False) -> Dict[str, Any]:
    """Run op's ops.reference contract on the named candidate and record
    the outcome. Idempotent per (op, name) unless `force`."""
    rec = _LEDGER.get((op, name))
    if rec is not None and not force:
        return rec
    contract = CONTRACTS.get(op)
    if contract is None:
        rec = {"status": "fail",
               "error": f"op {op!r} has no equivalence contract"}
    else:
        try:
            v = variants.get(op, name)
            rec = {"status": "pass", **(contract(v.apply) or {})}
        except Exception as e:  # noqa: BLE001 — a failing candidate is
            # DATA (the search skips it), never a search abort
            rec = {"status": "fail", "error": f"{e!s:.300}"}
    _LEDGER[(op, name)] = rec
    return rec


def equivalence_record(op: str, name: str) -> Optional[Dict[str, Any]]:
    rec = _LEDGER.get((op, name))
    return dict(rec) if rec else None


def passed(op: str, name: str) -> bool:
    rec = _LEDGER.get((op, name))
    return bool(rec) and rec.get("status") == "pass"


def clear_ledger() -> None:
    _LEDGER.clear()


def ledger_table() -> Dict[str, str]:
    return {f"{op}/{name}": rec.get("status", "?")
            for (op, name), rec in _LEDGER.items()}


# ===========================================================================
# Microbenches — how a candidate is timed when the op is not reachable
# through a workflow's fused step (flash_attn / sgd_update live below
# the unit graph). Workflow ops (lrn) time IN-GRAPH via the PR-2
# protocol instead; see ops.autotune.
# ===========================================================================

BENCHES: Dict[str, Callable[[Callable, int], float]] = {}


def bench_candidate(op: str, apply: Callable, repeats: int = 2) -> float:
    """Seconds per fwd(+bwd where differentiable) call of `apply` on the
    op's canonical bench shapes (tiny on CPU, real on TPU)."""
    return BENCHES[op](apply, repeats)


def _on_cpu() -> bool:
    import jax
    return jax.default_backend() == "cpu"


def _time_jitted(fn, args, repeats: int) -> float:
    import time

    import jax
    f = jax.jit(fn)
    jax.block_until_ready(f(*args))       # compile + warm
    best = float("inf")
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        jax.block_until_ready(f(*args))
        best = min(best, time.perf_counter() - t0)
    return best


# ===========================================================================
# VMEM footprint rules (ISSUE 14): the declarative cost model behind the
# search's static pruning (analysis/resources.py owns the budget table
# and verdicts). Each rule mirrors its kernel's BlockSpecs in
# ops/pallas_kernels.py: Pallas pipelines grid steps with DOUBLE-
# BUFFERED in/out blocks, so resident bytes = 2 x (in-block + out-block
# bytes) + scratch. In-kernel temporaries beyond the declared refs are
# a documented under-count (docs/ANALYSIS.md blind spots).
# ===========================================================================


def _dtype_width(dtype) -> int:
    """Byte width of a compute-dtype spec ('bfloat16', np dtype, None =
    f32) without requiring numpy to know the name."""
    if dtype is None:
        return 4
    s = str(dtype)
    return {"bfloat16": 2, "bf16": 2, "float16": 2, "f16": 2,
            "float64": 8, "f64": 8}.get(s, 4)


# ===========================================================================
# Registered templates: the tuning axes of ops/pallas_kernels.py
# ===========================================================================

# -- lrn: row tile + HBM staging dtype --------------------------------------

def _lrn_build(cfg):
    def apply(x, *, k, alpha, beta, n):
        from veles_tpu.ops import pallas_kernels as pk
        return pk.lrn_pallas(x, k, alpha, beta, n,
                             row_tile=cfg["rt"], io_dtype=cfg["io"])
    return apply


def _lrn_contract(apply):
    import jax
    import numpy as np

    from veles_tpu.ops import reference as ref
    rs = np.random.RandomState(3)
    x = rs.randn(2, 4, 4, 16).astype(np.float32)
    g = rs.randn(2, 4, 4, 16).astype(np.float32)
    k, alpha, beta, n = 2.0, 1e-4, 0.75, 5
    y, vjp = jax.vjp(
        lambda xx: apply(xx, k=k, alpha=alpha, beta=beta, n=n), x)
    (dx,) = vjp(g)
    np.testing.assert_allclose(
        np.asarray(y), ref.lrn_forward(x, k, alpha, beta, n), atol=2e-5)
    np.testing.assert_allclose(
        np.asarray(dx), ref.lrn_backward(x, g, k, alpha, beta, n),
        atol=2e-5)
    return {"checked": "lrn fwd+bwd vs ops.reference, atol 2e-5"}


def _lrn_bench(apply, repeats):
    import jax
    import jax.numpy as jnp
    shape = (8, 6, 6, 16) if _on_cpu() else (256, 27, 27, 96)
    x = jax.random.normal(jax.random.PRNGKey(0), shape, jnp.float32)

    def fwd_bwd(xx):
        y, vjp = jax.vjp(
            lambda a: apply(a, k=2.0, alpha=1e-4, beta=0.75, n=5), xx)
        return y, vjp(y)[0]

    return _time_jitted(fwd_bwd, (x,), repeats)


def _lrn_vmem(cfg, shapes, dtype):
    """Both LRN passes block (rt, C); the backward is the worst
    direction — 2 inputs (x, err) + 1 output, each double-buffered."""
    c = int(shapes.get("c") or (16 if _on_cpu() else 96))
    w = 4 if cfg["io"] == "f32" else _dtype_width(dtype)
    return 2 * 3 * cfg["rt"] * c * w


register_template(KernelTemplate(
    op="lrn", base="pallas",
    axes=(Axis("rt", (32, 64, 128, 256, 512, 1024, 2048),
               doc="rows per VMEM block (both passes)"),
          Axis("io", ("native", "f32"),
               doc="HBM staging dtype: caller's dtype (bf16 under the "
                   "fused step — half the bytes) vs f32 blocks")),
    build=_lrn_build, seed={"rt": 512, "io": "native"},
    vmem_footprint=_lrn_vmem,
    doc="one-VMEM-pass LRN pair over row-tile x staging-dtype (the "
        "hand-written pallas_one_pass uses the ~1MB heuristic tile)"))
CONTRACTS["lrn"] = _lrn_contract
BENCHES["lrn"] = _lrn_bench


# -- flash_attn: block shapes + KV streaming order --------------------------

def _flash_build(cfg):
    def apply(q, k, v, scale=None, causal=False, drop_mask=None):
        from veles_tpu.ops import pallas_kernels as pk
        return pk.flash_attention_pallas(
            q, k, v, scale=scale, causal=causal, blk_q=cfg["blk_q"],
            blk_k=cfg["blk_k"], kv_order=cfg["kv_order"],
            drop_mask=drop_mask if cfg["drop"] else None)
    #: the contract/bench read the fuse axis off the closure so a fused
    #: point is exercised (and timed) WITH its mask leg
    apply.fusion_drop = cfg["drop"]
    return apply


def _flash_contract(apply):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from veles_tpu.ops import attention as oa
    from veles_tpu.ops import reference as ref
    rs = np.random.RandomState(7)
    b, s, h, d = 1, 256, 2, 8
    q, k, v = (rs.randn(b, s, h, d).astype(np.float32) for _ in range(3))
    w = jnp.asarray(rs.randn(b, s, h, d).astype(np.float32))
    for causal in (False, True):
        got = np.asarray(apply(q, k, v, causal=causal))
        np.testing.assert_allclose(
            got, ref.mha_forward(q, k, v, causal=causal),
            rtol=2e-4, atol=2e-5)
        # backward vs jax.vjp of the einsum golden (reference.mha_forward
        # is numpy; oa.mha_forward is its pinned jax twin)
        gf = jax.grad(lambda *a: jnp.sum(apply(*a, causal=causal) * w),
                      argnums=(0, 1, 2))(q, k, v)
        gg = jax.grad(
            lambda *a: jnp.sum(oa.mha_forward(*a, causal=causal) * w),
            argnums=(0, 1, 2))(q, k, v)
        for name, a, b_ in zip("qkv", gf, gg):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=5e-4, atol=5e-5,
                                       err_msg=name)
    checked = ("flash fwd vs ops.reference.mha_forward + bwd vs "
               "einsum vjp, causal and not")
    if getattr(apply, "fusion_drop", 0):
        # FUSED point: the in-kernel dropout epilogue vs the COMPOSED
        # golden (attn_dropout_forward = mha_forward ⊙ mask; bwd vs the
        # einsum-then-dropout_backward composition through jax.grad)
        mask = (ref.make_dropout_mask(np.random.RandomState(17),
                                      (b, s, h, d), 0.4)
                .astype(np.float32))
        mj = jnp.asarray(mask)
        got = np.asarray(apply(q, k, v, causal=True, drop_mask=mask))
        np.testing.assert_allclose(
            got, ref.attn_dropout_forward(q, k, v, mask, causal=True),
            rtol=2e-4, atol=2e-5)
        gf = jax.grad(
            lambda *a: jnp.sum(apply(*a, causal=True, drop_mask=mj) * w),
            argnums=(0, 1, 2))(q, k, v)
        gg = jax.grad(
            lambda *a: jnp.sum(
                oa.mha_forward(*a, causal=True) * mj * w),
            argnums=(0, 1, 2))(q, k, v)
        for name, a, b_ in zip("qkv", gf, gg):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=5e-4, atol=5e-5,
                                       err_msg=f"drop {name}")
        checked += " + dropout epilogue vs composed attn_dropout golden"
    return {"checked": checked}


def _flash_bench_shape():
    # CPU: S must span the blk choices or every config clamps to the
    # same kernel (see _flash_bench_key); 1 head + d=4 keeps the
    # interpret-mode grid walk affordable
    return (1, 512, 1, 4) if _on_cpu() else (1, 8192, 8, 64)


def _flash_bench_key(cfg):
    """The (blk_q, blk_k, kv_order, drop) the kernel ACTUALLY runs at
    the bench shapes — flash_attention_pallas shrinks requested blocks
    to divisors of S (flash_fit_block), so e.g. blk_k=1024 at S=512 IS
    blk_k=512."""
    from veles_tpu.ops.pallas_kernels import flash_fit_block
    s = _flash_bench_shape()[1]
    return (flash_fit_block(s, cfg["blk_q"]),
            flash_fit_block(s, cfg["blk_k"]), cfg["kv_order"],
            cfg["drop"])


def _flash_vmem(cfg, shapes, dtype):
    """Worst of the three flash grids (fwd / dQ / dK-dV), each with its
    declared blocks double-buffered plus its scratch — all f32 inside
    the kernels. Blocks are clamped to divisors of S exactly like the
    traced kernel (flash_fit_block), so the pruned geometry IS the one
    that would compile."""
    from veles_tpu.ops.pallas_kernels import flash_fit_block
    _, s0, _, d0 = _flash_bench_shape()
    s = int(shapes.get("s") or s0)
    d = int(shapes.get("d") or d0)
    bq = flash_fit_block(s, cfg["blk_q"])
    bk = flash_fit_block(s, cfg["blk_k"])
    f32 = 4

    def col(rows):          # one (rows, d) block
        return rows * d * f32

    def vec(rows):          # one (rows, 1) block
        return rows * f32

    # fwd: q + k + v [+ mask] in, out + lse out; scratch m/l/acc
    fwd = 2 * (col(bq) + 2 * col(bk)
               + (col(bq) if cfg.get("drop") else 0)
               + col(bq) + vec(bq)) + 2 * vec(bq) + col(bq)
    # dQ: q/do + k/v + lse/di in, dq out; scratch dq accumulator
    dq = 2 * (2 * col(bq) + 2 * col(bk) + 2 * vec(bq)
              + col(bq)) + col(bq)
    # dK/dV (transposed grid): q/do + k/v + lse/di in, dk + dv out;
    # scratch dk/dv accumulators
    dkv = 2 * (2 * col(bq) + 2 * col(bk) + 2 * vec(bq)
               + 2 * col(bk)) + 2 * col(bk)
    return max(fwd, dq, dkv)


def _flash_bench(apply, repeats):
    import jax
    import jax.numpy as jnp
    b, s, h, d = _flash_bench_shape()
    key = jax.random.PRNGKey(1)
    q, k, v = (jax.random.normal(kk, (b, s, h, d), jnp.float32)
               for kk in jax.random.split(key, 3))
    kw = {}
    if getattr(apply, "fusion_drop", 0):
        # a FUSED point is timed with its mask leg — that is the kernel
        # a winning selection would actually trace
        kw["drop_mask"] = (
            (jax.random.uniform(jax.random.PRNGKey(6),
                                (b, s, h, d)) < 0.5)
            .astype(jnp.float32) * 2.0)

    def fwd_bwd(q, k, v):
        y, vjp = jax.vjp(lambda *a: apply(*a, causal=True, **kw),
                         q, k, v)
        return y, vjp(y)

    return _time_jitted(fwd_bwd, (q, k, v), repeats)


register_template(KernelTemplate(
    op="flash_attn", base="pallas",
    axes=(Axis("blk_q", (128, 256, 512), doc="query rows per tile"),
          Axis("blk_k", (128, 256, 512, 1024), doc="KV rows per tile"),
          Axis("kv_order", ("fwd", "rev"),
               doc="forward-pass KV tile visit order (online softmax is "
                   "order-invariant; probes prefetch locality)"),
          Axis("drop", (0, 1),
               doc="FUSE axis: apply a pre-scaled dropout mask inside "
                   "the kernel's output-block write (drops the composed "
                   "path's extra HBM round trip over the attention "
                   "output); gated by the composed attn_dropout "
                   "golden")),
    build=_flash_build,
    seed={"blk_q": 512, "blk_k": 1024, "kv_order": "fwd", "drop": 0},
    bench_key=_flash_bench_key, fuse_axis="drop",
    vmem_footprint=_flash_vmem,
    doc="blocked flash attention over blk_q x blk_k x streaming order "
        "x dropout-epilogue fusion (hand incumbent: 512/1024/fwd, "
        "unfused, tuned v5e 2026-07-29)"))
CONTRACTS["flash_attn"] = _flash_contract
BENCHES["flash_attn"] = _flash_bench


# -- sgd_update: row blocking of the fused update ---------------------------

def _sgd_pallas_build(cfg):
    rt = cfg["rt"]

    def apply(params, grads, vel, sgd_cfg, lr_scale=1.0, mults=None):
        import jax

        from veles_tpu.ops import optim
        from veles_tpu.ops import pallas_kernels as pk
        if getattr(sgd_cfg, "l1_decay", 0.0):
            # the fused kernel has no L1 term — exact math wins over
            # the lowering, fall back to the tree update
            return optim.sgd_update(params, grads, vel, sgd_cfg,
                                    lr_scale=lr_scale, mults=mults)

        def upd(path, p, g, v):
            key = path[0].key if path and hasattr(path[0], "key") \
                else None
            lr = optim.sgd_leaf_lr(sgd_cfg, p.ndim, lr_scale=lr_scale,
                                   key=key, mults=mults)
            return pk.sgd_update_pallas(p, g, v, lr, sgd_cfg.momentum,
                                        sgd_cfg.weight_decay,
                                        row_tile=rt)

        flat = jax.tree_util.tree_map_with_path(upd, params, grads, vel)
        is_pair = lambda t: isinstance(t, tuple)  # noqa: E731
        new_p = jax.tree_util.tree_map(lambda t: t[0], flat,
                                       is_leaf=is_pair)
        new_v = jax.tree_util.tree_map(lambda t: t[1], flat,
                                       is_leaf=is_pair)
        return new_p, new_v
    return apply


def _sgd_contract(apply):
    import numpy as np

    from veles_tpu.ops import optim
    from veles_tpu.ops import reference as ref
    rs = np.random.RandomState(11)
    cfg = optim.SGDConfig(lr=0.05, momentum=0.9, weight_decay=1e-3,
                          lr_bias_mult=2.0)
    params = {"weights": rs.randn(33, 17).astype(np.float32),
              "bias": rs.randn(5).astype(np.float32)}
    grads = {k: rs.randn(*v.shape).astype(np.float32)
             for k, v in params.items()}
    vel = {k: rs.randn(*v.shape).astype(np.float32)
           for k, v in params.items()}
    new_p, new_v = apply(params, grads, vel, cfg, lr_scale=0.5)
    for k in params:
        # the bias-lr convention rides ndim, exactly like the tree path
        lr = cfg.lr * 0.5 * (cfg.lr_bias_mult if params[k].ndim == 1
                             else 1.0)
        pg, vg = ref.sgd_momentum_update(
            params[k], grads[k], vel[k], lr, cfg.momentum,
            cfg.weight_decay)
        np.testing.assert_allclose(np.asarray(new_p[k]), pg, rtol=1e-5,
                                   atol=1e-6, err_msg=k)
        np.testing.assert_allclose(np.asarray(new_v[k]), vg, rtol=1e-5,
                                   atol=1e-6, err_msg=k)
    return {"checked": "sgd+momentum+wd vs ops.reference, incl. the "
                       "1-D bias lr multiplier, rtol 1e-5"}


def _sgd_bench(apply, repeats):
    import jax
    import jax.numpy as jnp

    from veles_tpu.ops import optim
    shape = (256, 65) if _on_cpu() else (4096, 4097)
    cfg = optim.SGDConfig(lr=0.01, momentum=0.9, weight_decay=1e-4)
    key = jax.random.PRNGKey(2)
    p, g, v = (jax.random.normal(kk, shape, jnp.float32)
               for kk in jax.random.split(key, 3))
    tree = {"weights": p, "bias": p[0]}

    def step(params):
        return apply(params, {"weights": g, "bias": g[0]},
                     {"weights": v, "bias": v[0]}, cfg)

    return _time_jitted(step, (tree,), repeats)


def _sgd_vmem(cfg, shapes, dtype):
    """One (rt, 128) f32 block per buffer: 3 inputs (p, g, v) + 2
    outputs, double-buffered (the SMEM scalar vector is negligible)."""
    from veles_tpu.ops import pallas_kernels as pk
    rt = max(pk._MIN_ROW_TILE, cfg["rt"])
    return 2 * 5 * rt * pk._LANE * 4


register_template(KernelTemplate(
    op="sgd_update", base="pallas_rows",
    axes=(Axis("rt", (8, 16, 32, 64, 128, 256, 512, 1024),
               doc="rows per program of the flattened (rows, 128) "
                   "update grid"),),
    build=_sgd_pallas_build, seed={"rt": 8}, vmem_footprint=_sgd_vmem,
    doc="fused SGD+momentum+weight-decay update (one VMEM pass over 3 "
        "buffers) over its row blocking; the hand-written kernel froze "
        "rt=8"))
CONTRACTS["sgd_update"] = _sgd_contract
BENCHES["sgd_update"] = _sgd_bench


# -- grad_reduce: wire dtype x scale block x error feedback x hierarchy -----
#    (the EQuARX family, arxiv 2506.17615 — ISSUE 12). All points build
#    through variants.grad_reduce_apply, the ONE collective
#    implementation; the contract gates each point on the BITWISE
#    quantize/dequantize roundtrip vs ops.reference plus the shard_map
#    exchange vs the psum golden at the wire dtype's tolerance.

def _gr_build(cfg):
    return variants.grad_reduce_apply(dict(cfg))


def _gr_mesh():
    import jax

    from veles_tpu.parallel.mesh import make_mesh
    devs = jax.devices()[:8]
    return make_mesh(devs), len(devs)


def _gr_contract(apply):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from veles_tpu._compat import shard_map
    from veles_tpu.ops import reference as ref
    from veles_tpu.parallel.mesh import DATA_AXIS
    cfg = getattr(apply, "gr_config", None) or variants.grad_reduce_config(
        "f32")
    blk = int(cfg.get("blk") or 256)
    # 1. BITWISE quantize/dequantize roundtrip vs the numpy goldens —
    # codes, scales and dequantized values must match exactly (the
    # "bitwise roundtrip" half of the equivalence ledger)
    rs = np.random.RandomState(5)
    xq = rs.randn(3, 2 * blk).astype(np.float32)
    qj, sj = variants.q8_encode(jnp.asarray(xq), blk)
    qg, sg = ref.quantize_blockwise(xq, blk)
    np.testing.assert_array_equal(np.asarray(qj), qg)
    np.testing.assert_array_equal(np.asarray(sj), sg)
    np.testing.assert_array_equal(
        np.asarray(variants.q8_decode(qj, sj, blk)),
        ref.dequantize_blockwise(qg, sg, blk))
    # 2. the exchange itself under shard_map vs the psum-then-slice
    # golden (the registry's admission bar for collectives)
    mesh, n = _gr_mesh()
    local = 48
    flat = rs.randn(n, n * local).astype(np.float32)
    stateful = bool(cfg.get("ef"))

    def body(g):
        r = apply(g.reshape(-1), DATA_AXIS)
        return r[0] if stateful else r

    got = np.asarray(jax.jit(shard_map(
        body, mesh=mesh, in_specs=P(DATA_AXIS),
        out_specs=P(DATA_AXIS)))(flat))
    want = flat.reshape(n, n, local).sum(axis=0).reshape(-1)
    if cfg["dt"] == "f32":
        tol = dict(rtol=1e-5, atol=1e-5)
    elif cfg["dt"] == "bf16":
        tol = dict(rtol=0.05, atol=0.05)
    else:
        # int8 absolute error is bounded by n_shards x scale/2 with
        # scale = block-absmax/127 (~0.03 for unit-normal grads)
        tol = dict(rtol=0.1, atol=0.03 * n)
    np.testing.assert_allclose(got, want, **tol)
    if cfg["dt"] == "int8" and not cfg["hier"]:
        # flat int8 is EXACTLY the reference-quantized exchange: the sum
        # of per-shard dequantized partials, to f32 summation rounding
        deq = np.zeros_like(flat)
        pad = (-local) % blk
        for i in range(n):
            x2 = np.pad(flat[i].reshape(n, local), ((0, 0), (0, pad)))
            q, s = ref.quantize_blockwise(x2, blk)
            deq[i] = ref.dequantize_blockwise(q, s, blk)[:, :local] \
                .reshape(-1)
        want_q = deq.reshape(n, n, local).sum(axis=0).reshape(-1)
        np.testing.assert_allclose(got, want_q, rtol=1e-6, atol=1e-5)
    return {"checked": f"q8 roundtrip bitwise vs ops.reference + "
                       f"shard_map exchange vs psum golden on {n} "
                       f"devices ({cfg['dt']} tolerance)"}


def _gr_bench_key(cfg):
    """Configs that trace the same program at the bench geometry alias:
    blk/ef only matter for int8 wire, and hier degrades to flat when
    the geometry is single-level (grad_reduce_geometry)."""
    _, n = _gr_mesh()
    h, loc = variants.grad_reduce_geometry(n)
    int8 = cfg["dt"] == "int8"
    hier = bool(cfg["hier"]) and h > 1 and loc > 1
    return (cfg["dt"], cfg["blk"] if int8 else 0,
            cfg["ef"] if int8 else 0, int(hier))


def _gr_bench(apply, repeats):
    import time

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from veles_tpu._compat import shard_map
    from veles_tpu.parallel.mesh import DATA_AXIS
    mesh, n = _gr_mesh()
    per_shard = n * (4096 if _on_cpu() else (1 << 19))
    flat = jax.random.normal(jax.random.PRNGKey(3), (n, per_shard),
                             jnp.float32)

    def body(g):
        r = apply(g.reshape(-1), DATA_AXIS)
        out = r[0] if isinstance(r, tuple) else r
        return out.reshape(1, -1)

    f = jax.jit(shard_map(body, mesh=mesh, in_specs=P(DATA_AXIS),
                          out_specs=P(DATA_AXIS)))
    jax.block_until_ready(f(flat))          # compile + warm
    best = float("inf")
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        jax.block_until_ready(f(flat))
        best = min(best, time.perf_counter() - t0)
    return best


register_template(KernelTemplate(
    op="grad_reduce", base="wire",
    axes=(Axis("dt", ("f32", "bf16", "int8"),
               doc="wire dtype of the DCN exchange"),
          Axis("blk", (64, 128, 256, 512),
               doc="int8 absmax-scale block (scale overhead 4/blk "
                   "bytes/elem); inert for float wire"),
          Axis("ef", (0, 1),
               doc="error feedback: carry the quantization residual in "
                   "the ZeRO state (int8 only — canonicalized off "
                   "otherwise)"),
          Axis("hier", (0, 1),
               doc="two-level (hosts x local) decomposition: ICI-local "
                   "reduce-scatter, DCN exchange of 1/n_local slices")),
    build=_gr_build, seed={"dt": "f32", "blk": 256, "ef": 0, "hier": 0},
    pallas=False, bench_key=_gr_bench_key,
    stateful=lambda cfg: cfg["dt"] == "int8" and bool(cfg["ef"]),
    doc="quantized + hierarchical ZeRO reduce-scatter family (EQuARX, "
        "arxiv 2506.17615) — the search picks the winner per link "
        "geometry, cache-keyed by (device_kind, hosts x local)"))
CONTRACTS["grad_reduce"] = _gr_contract
BENCHES["grad_reduce"] = _gr_bench


# -- maxpool: forward algorithm x backward combine-DAG shape ----------------
#    (carried ROADMAP item: the last registry ops with no template; the
#    axes reify the hand-written reduce_window/slices split and add the
#    slices fold's combine-tree shape — the backward's select-DAG depth)

def _maxpool_build(cfg):
    algo, fold = cfg["algo"], cfg["fold"]

    def apply(x, ksize, stride, use_abs):
        from veles_tpu.ops import variants as va
        from veles_tpu.ops import xla as ox
        if algo == "reduce_window":
            return va.get("maxpool", "reduce_window").apply(
                x, ksize, stride, use_abs)
        return ox.maxpool_forward_slices(x, ksize, stride, use_abs,
                                         fold=fold)
    return apply


def _maxpool_contract(apply):
    import jax
    import numpy as np

    from veles_tpu.ops import reference as ref
    rs = np.random.RandomState(9)
    x = rs.randn(2, 7, 7, 6).astype(np.float32)
    for use_abs in (False, True):
        y, vjp = jax.vjp(lambda a: apply(a, (3, 3), (2, 2), use_abs), x)
        yg, idx = ref.maxpool_forward(x, (3, 3), (2, 2), use_abs)
        np.testing.assert_allclose(np.asarray(y), yg, atol=1e-6,
                                   err_msg=f"use_abs={use_abs}")
        g = rs.randn(*yg.shape).astype(np.float32)
        (dx,) = vjp(g)
        np.testing.assert_allclose(
            np.asarray(dx), ref.maxpool_backward(g, idx, x.shape),
            atol=1e-6, err_msg=f"use_abs={use_abs} bwd")
    return {"checked": "maxpool fwd+bwd (max + maxabs) vs "
                       "ops.reference, atol 1e-6"}


def _maxpool_bench(apply, repeats):
    import jax
    import jax.numpy as jnp
    shape = (8, 13, 13, 8) if _on_cpu() else (256, 27, 27, 96)
    x = jax.random.normal(jax.random.PRNGKey(4), shape, jnp.float32)

    def fwd_bwd(xx):
        y, vjp = jax.vjp(lambda a: apply(a, (3, 3), (2, 2), False), xx)
        return y, vjp(y)[0]

    return _time_jitted(fwd_bwd, (x,), repeats)


def _maxpool_bench_key(cfg):
    # fold only shapes the slices combine-DAG; reduce_window ignores it
    return (cfg["algo"],
            cfg["fold"] if cfg["algo"] == "slices" else "-")


register_template(KernelTemplate(
    op="maxpool", base="gen",
    axes=(Axis("algo", ("reduce_window", "slices"),
               doc="forward lowering (the knob is what the BACKWARD "
                   "lowers to: select_and_scatter vs selects+pads)"),
          Axis("fold", ("linear", "tree"),
               doc="slices combine-DAG: left fold (deep select chain) "
                   "vs pairwise tree (log depth); inert for "
                   "reduce_window")),
    build=_maxpool_build,
    seed={"algo": "reduce_window", "fold": "linear"},
    pallas=False, bench_key=_maxpool_bench_key,
    doc="max/maxabs pooling over algorithm x backward combine shape"))
CONTRACTS["maxpool"] = _maxpool_contract
BENCHES["maxpool"] = _maxpool_bench


# -- conv_stem: input packing x accumulator dtype ---------------------------

def _conv_stem_build(cfg):
    pack, acc, epi = cfg["pack"], cfg["acc"], cfg["epi"]

    def apply(x, w, b, stride, padding, activation, epilogue=None):
        from veles_tpu.ops import xla as ox
        y = ox.conv2d_forward(x, w, b, stride, padding, activation,
                              s2d=(pack == "s2d"), acc=acc)
        if epi == "lrn" and epilogue is not None:
            # the claimed successor's LRN folded into the epilogue: the
            # step passes the NORM unit's hyperparameters when a fused
            # winner claims an adjacent (conv_stem, lrn) pair
            y = ox.lrn_forward(y, epilogue["k"], epilogue["alpha"],
                               epilogue["beta"], epilogue["n"])
        return y
    apply.fusion_epi = epi
    return apply


def _conv_stem_contract(apply):
    import jax
    import numpy as np

    from veles_tpu.ops import reference as ref
    rs = np.random.RandomState(13)
    x = rs.randn(2, 19, 19, 3).astype(np.float32)
    w = (rs.randn(5, 5, 3, 8) * 0.1).astype(np.float32)
    b = rs.randn(8).astype(np.float32)
    stride, padding, act = (4, 4), (0, 0), "strictrelu"
    y, vjp = jax.vjp(
        lambda xx, ww, bb: apply(xx, ww, bb, stride, padding, act),
        x, w, b)
    yg = ref.conv2d_forward(x, w, b, stride, padding, act)
    np.testing.assert_allclose(np.asarray(y), yg, rtol=1e-4, atol=1e-4)
    g = rs.randn(*yg.shape).astype(np.float32)
    dx, dw, db = vjp(g)
    gx, gw, gb = ref.conv2d_backward(x, w, yg, g, stride, padding, act)
    np.testing.assert_allclose(np.asarray(dx), gx, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(dw), gw, rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(np.asarray(db), gb, rtol=1e-4, atol=1e-4)
    checked = ("stem conv fwd+bwd (stride-4 thin-channel) vs "
               "ops.reference, rtol 1e-4")
    if getattr(apply, "fusion_epi", "none") == "lrn":
        # FUSED point: bias+act+LRN epilogue vs the COMPOSED golden
        epi = {"k": 2.0, "alpha": 1e-3, "beta": 0.75, "n": 5}
        y2, vjp2 = jax.vjp(
            lambda xx, ww, bb: apply(xx, ww, bb, stride, padding, act,
                                     epilogue=epi), x, w, b)
        y2g = ref.conv_lrn_forward(x, w, b, stride, padding, act, **epi)
        np.testing.assert_allclose(np.asarray(y2), y2g, rtol=1e-4,
                                   atol=1e-4)
        g2 = rs.randn(*y2g.shape).astype(np.float32)
        dx2, dw2, db2 = vjp2(g2)
        gx2, gw2, gb2 = ref.conv_lrn_backward(
            x, w, b, g2, stride, padding, act, **epi)
        np.testing.assert_allclose(np.asarray(dx2), gx2, rtol=1e-4,
                                   atol=1e-4, err_msg="epi dx")
        np.testing.assert_allclose(np.asarray(dw2), gw2, rtol=1e-4,
                                   atol=1e-3, err_msg="epi dw")
        np.testing.assert_allclose(np.asarray(db2), gb2, rtol=1e-4,
                                   atol=1e-4, err_msg="epi db")
        checked += " + LRN epilogue vs composed conv_lrn golden"
    return {"checked": checked}


def _conv_stem_bench(apply, repeats):
    import jax
    import jax.numpy as jnp
    n, hw, co = (4, 35, 16) if _on_cpu() else (256, 227, 96)
    key = jax.random.PRNGKey(5)
    k1, k2, k3 = jax.random.split(key, 3)
    x = jax.random.normal(k1, (n, hw, hw, 3), jnp.float32)
    w = jax.random.normal(k2, (11, 11, 3, co), jnp.float32) * 0.05
    b = jax.random.normal(k3, (co,), jnp.float32)
    kw = {}
    if getattr(apply, "fusion_epi", "none") == "lrn":
        # a FUSED point is timed with its folded epilogue — that is the
        # program a winning selection would actually trace
        kw["epilogue"] = {"k": 2.0, "alpha": 1e-4, "beta": 0.75, "n": 5}

    def fwd_bwd(xx, ww, bb):
        y, vjp = jax.vjp(
            lambda a, c, d: apply(a, c, d, (4, 4), (0, 0),
                                  "strictrelu", **kw), xx, ww, bb)
        return y, vjp(y)

    return _time_jitted(fwd_bwd, (x, w, b), repeats)


def _conv_stem_bench_key(cfg):
    # the microbench runs f32 inputs, where the accumulator axis traces
    # the same program — packing and the epilogue fusion distinguish
    # kernels there (epi=lrn points are timed WITH the folded LRN)
    return (cfg["pack"], cfg["epi"])


register_template(KernelTemplate(
    op="conv_stem", base="gen",
    axes=(Axis("pack", ("direct", "s2d"),
               doc="input packing: plain strided conv vs the exact "
                   "space-to-depth rewrite (full MXU tiles)"),
          Axis("acc", ("native", "f32"),
               doc="conv accumulator dtype under sub-f32 compute: "
                   "XLA's dtype-following default vs pinned f32 "
                   "(preferred_element_type)"),
          Axis("epi", ("none", "lrn"),
               doc="FUSE axis: fold the successor LRN unit into the "
                   "bias+activation epilogue (the normalization unit's "
                   "work claimed at the matmul boundary); gated by the "
                   "composed conv_lrn golden")),
    build=_conv_stem_build,
    seed={"pack": "s2d", "acc": "native", "epi": "none"},
    pallas=False, bench_key=_conv_stem_bench_key, fuse_axis="epi",
    doc="strided thin-channel entry conv over packing x accumulator x "
        "LRN-epilogue fusion"))
CONTRACTS["conv_stem"] = _conv_stem_contract
BENCHES["conv_stem"] = _conv_stem_bench


# -- lrn_maxpool: the searched CROSS-OP fusion (ISSUE 13) -------------------
#    LRN and the pooling behind it both stream the same activation; the
#    fused point does both in one VMEM pass (ops/pallas_kernels.py
#    lrn_maxpool_pallas). The op is a PURE fusion op: its candidates
#    compose the (lrn, maxpool) member ops, the search charges a fused
#    candidate against their COMBINED profile share, and FusedTrainStep
#    lets the normalization unit claim its pooling successor's work
#    when a fused winner is selected (the pooling unit passes through
#    for that trace). Every point — composed or fused — is gated by the
#    COMPOSED ops.reference golden (lrn_maxpool_forward/backward).

def _lrn_pool_build(cfg):
    if not cfg["fuse"]:
        # the composed point: exactly the two member lowerings the
        # UNFUSED step would trace (XLA LRN + reduce_window pooling) —
        # the incumbent the fused candidates must beat
        def apply(x, *, k, alpha, beta, n, ksize, stride):
            from veles_tpu.ops import xla as ox
            y = ox.lrn_forward(x, k, alpha, beta, n)
            return ox.maxpool_forward(y, tuple(ksize), tuple(stride),
                                      False)
        apply.fused = False
        return apply

    def apply(x, *, k, alpha, beta, n, ksize, stride):
        from veles_tpu.ops import pallas_kernels as pk
        return pk.lrn_maxpool_pallas(x, k, alpha, beta, n,
                                     tuple(ksize), tuple(stride),
                                     row_tile=cfg["rt"],
                                     io_dtype=cfg["io"])
    apply.fused = True
    return apply


def _lrn_pool_contract(apply):
    import jax
    import numpy as np

    from veles_tpu.ops import reference as ref
    rs = np.random.RandomState(21)
    k, alpha, beta, n = 2.0, 1e-4, 0.75, 5
    ksize, stride = (3, 3), (2, 2)
    # 8x8 exercises the ceil-mode edge window (Hp=9 > 8); 9x9 is exact
    for hw in (8, 9):
        x = rs.randn(2, hw, hw, 16).astype(np.float32)
        y, vjp = jax.vjp(
            lambda xx: apply(xx, k=k, alpha=alpha, beta=beta, n=n,
                             ksize=ksize, stride=stride), x)
        yg = ref.lrn_maxpool_forward(x, k, alpha, beta, n, ksize,
                                     stride)
        np.testing.assert_allclose(np.asarray(y), yg, atol=2e-5,
                                   err_msg=f"hw={hw}")
        g = rs.randn(*yg.shape).astype(np.float32)
        (dx,) = vjp(g)
        np.testing.assert_allclose(
            np.asarray(dx),
            ref.lrn_maxpool_backward(x, g, k, alpha, beta, n, ksize,
                                     stride),
            atol=2e-5, err_msg=f"hw={hw} bwd")
    return {"checked": "fused LRN+maxpool fwd+bwd vs the COMPOSED "
                       "ops.reference golden (ceil-mode edge windows "
                       "included), atol 2e-5"}


def _lrn_pool_bench(apply, repeats):
    import jax
    import jax.numpy as jnp
    shape = (8, 13, 13, 16) if _on_cpu() else (256, 55, 55, 96)
    x = jax.random.normal(jax.random.PRNGKey(8), shape, jnp.float32)

    def fwd_bwd(xx):
        y, vjp = jax.vjp(
            lambda a: apply(a, k=2.0, alpha=1e-4, beta=0.75, n=5,
                            ksize=(3, 3), stride=(2, 2)), xx)
        return y, vjp(y)[0]

    return _time_jitted(fwd_bwd, (x,), repeats)


def _lrn_pool_bench_key(cfg):
    # every fuse=0 point IS the composed incumbent (rt/io are fused-
    # kernel axes): one timing covers them all
    return ("composed",) if not cfg["fuse"] else (cfg["rt"], cfg["io"])


def _lrn_pool_vmem(cfg, shapes, dtype):
    """Fused points block whole (rt, H, W, C) sample bands; the
    backward is the worst direction (x + g in, dx out) and the kernel
    additionally materializes the padded recomputed LRN output plus the
    first-max routing mask in f32 — modeled as temporaries on top of
    the double-buffered refs. Composed points trace XLA: zero Pallas
    footprint."""
    if not cfg["fuse"]:
        return 0
    h, w, c = shapes.get("h"), shapes.get("w"), shapes.get("c")
    if h is None or w is None or c is None:
        # canonical bench-shape fallback needs the backend; callers
        # passing full shapes (the planner's static gate) must not
        # initialize one
        _, h0, w0, c0 = ((8, 13, 13, 16) if _on_cpu()
                         else (256, 55, 55, 96))
        h, w, c = h or h0, w or w0, c or c0
    h, w, c = int(h), int(w), int(c)
    ky, kx = shapes.get("ksize") or (3, 3)
    sy, sx = shapes.get("stride") or (2, 2)
    from veles_tpu.ops.pallas_kernels import _pool_out_hw
    oh, ow = _pool_out_hw(h, w, ky, kx, sy, sx)
    wd = 4 if cfg["io"] == "f32" else _dtype_width(dtype)
    rt = cfg["rt"]
    in_b = rt * h * w * c * wd
    out_b = rt * oh * ow * c * wd
    # padded recompute canvas (hp, wp) + the int32 routing mask
    hp, wp = (oh - 1) * sy + ky, (ow - 1) * sx + kx
    tmp = rt * hp * wp * c * 4 + rt * oh * ow * c * 4
    return 2 * (2 * in_b + out_b) + tmp


register_template(KernelTemplate(
    op="lrn_maxpool", base="fused",
    axes=(Axis("rt", (1, 2, 4, 8),
               doc="SAMPLES per VMEM block (each holds a whole "
                   "(H, W, C) band, so channel and pooling windows "
                   "never cross blocks)"),
          Axis("io", ("native", "f32"),
               doc="HBM staging dtype (the LRN template's axis: "
                   "caller's dtype vs f32 blocks)"),
          Axis("fuse", (0, 1),
               doc="FUSE axis: 0 = the composed member lowerings (the "
                   "incumbent), 1 = one row-streaming Pallas pass "
                   "doing LRN then maxpool over the same tile")),
    build=_lrn_pool_build,
    seed={"rt": 2, "io": "native", "fuse": 0},
    bench_key=_lrn_pool_bench_key, fuse_axis="fuse",
    fuses=("lrn", "maxpool"), vmem_footprint=_lrn_pool_vmem,
    doc="searched cross-op fusion of the (lrn, maxpool) unit pair — "
        "sample tile x staging dtype x fuse on/off, every point gated "
        "on the composed golden"))
CONTRACTS["lrn_maxpool"] = _lrn_pool_contract
BENCHES["lrn_maxpool"] = _lrn_pool_bench


# -- serve_forward: quantized serving wire (ISSUE 15) -----------------------
#    No template (the wire formats are a closed named family, not a
#    searched space) — but the variants ride the SAME equivalence ledger
#    as every generated kernel: the serving tier refuses to serve a
#    non-f32 wire without a passing record here (veles_tpu/serving.py),
#    exactly as the search refuses to time an ungated candidate.

def _serve_contract(apply):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from veles_tpu.ops import reference as ref
    from veles_tpu.ops import variants as va
    cfg = apply.sv_config
    rs = np.random.RandomState(7)
    # hidden width >= the int8 block (64) so the quantized wire's
    # eligibility rule actually quantizes w1 (w2's 4 columns stay f32
    # by the same rule — both branches exercised)
    w1 = (rs.randn(24, 96) * 0.2).astype(np.float32)
    b1 = (rs.randn(96) * 0.1).astype(np.float32)
    w2 = (rs.randn(96, 4) * 0.2).astype(np.float32)
    b2 = (rs.randn(4) * 0.1).astype(np.float32)
    params = ({"weights": w1, "bias": b1}, {"weights": w2, "bias": b2})
    x = rs.randn(8, 24).astype(np.float32)

    def forward(p, xb):
        h = jnp.tanh(xb @ p[0]["weights"] + p[0]["bias"])
        return h @ p[1]["weights"] + p[1]["bias"]

    name = {v["wire"]: k for k, v in va._SERVE_NAMED.items()}[
        cfg["wire"]]
    prepared, shapes = va.serve_prepare_params(name, params)
    if cfg["wire"] == "int8":
        # the host transform must BE the reference quantizer, bitwise —
        # one quantization rule for collectives and serving; a leaf
        # below the block width must pass through UNtouched
        for w, layer in ((w1, prepared[0]), (w2, prepared[1])):
            if w.shape[-1] >= cfg["blk"]:
                qg, sg = ref.serve_quantize_weight(w, cfg["blk"])
                np.testing.assert_array_equal(layer["weights"]["q"], qg)
                np.testing.assert_array_equal(layer["weights"]["s"], sg)
            else:
                np.testing.assert_array_equal(layer["weights"], w)
    out = np.asarray(jax.jit(
        lambda pr, xb: apply(pr, xb, forward, shapes))(prepared, x))
    # golden 1: the SAME wire transform applied through the reference
    # quantizers, forward in numpy — isolates the traced dequant+matmul
    if cfg["wire"] == "int8":
        deq = []
        for (w, b) in ((w1, b1), (w2, b2)):
            if w.shape[-1] >= cfg["blk"]:
                q, s = ref.serve_quantize_weight(w, cfg["blk"])
                w = ref.dequantize_blockwise(q, s, cfg["blk"])[
                    :, :w.shape[-1]].reshape(w.shape)
            deq.append((w, b))
        golden = ref.serve_forward_mlp(x, deq)
        np.testing.assert_allclose(out, golden, rtol=2e-5, atol=2e-5)
    elif cfg["wire"] == "f32":
        golden = ref.serve_forward_mlp(x, ((w1, b1), (w2, b2)))
        np.testing.assert_allclose(out, golden, rtol=2e-5, atol=2e-5)
    # golden 2 (every wire): stay within the serving tolerance of the
    # UNQUANTIZED f32 forward — the bound the serving tier re-probes on
    # the real model before a low-byte variant may serve
    f32 = ref.serve_forward_mlp(x, ((w1, b1), (w2, b2)))
    tol = {"f32": 1e-5, "bf16": 5e-2, "int8": 5e-2}[cfg["wire"]]
    err = float(np.max(np.abs(out - f32)))
    if err > tol:
        raise AssertionError(
            f"serve_forward/{name}: max |out - f32| = {err:.2e} "
            f"exceeds the {tol} serving tolerance")
    return {"checked": f"wire transform bitwise vs ops.reference + "
                       f"forward vs serve_forward_mlp golden; "
                       f"|out - f32| max {err:.2e} <= {tol}"}


CONTRACTS["serve_forward"] = _serve_contract
