"""Mixture-of-experts ops: dense golden routing + expert-parallel form.

Absent in the reference (2015-era framework); added because the TPU
build's distributed layer treats expert parallelism as a first-class mesh
axis alongside data/model/sequence. Design follows the standard TPU
recipe: top-1 (switch) routing, capacity-bounded dispatch expressed as
dense einsums with a one-hot dispatch mask (MXU-friendly, no gather
loops), and `lax.all_to_all` to exchange tokens when experts are sharded
over a mesh axis.

`moe_forward` (all experts local) is the golden model; `moe_forward_ep`
(inside shard_map, experts sharded over `axis_name`) must match it —
tested on the virtual 8-device mesh.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from veles_tpu._compat import axis_size as _axis_size


def router_probs(x, wr):
    """x: (N, D), wr: (D, E) -> (N, E) softmax router probabilities."""
    return jax.nn.softmax(x @ wr, axis=-1)


def top1_dispatch(probs, capacity: int):
    """Switch-style top-1 routing with per-expert capacity.

    Returns (dispatch, combine):
    - dispatch: (N, E, C) one-hot — token n occupies slot c of expert e;
    - combine:  (N, E, C) = dispatch · router gate (for the weighted sum).
    Tokens beyond an expert's capacity are DROPPED (standard switch
    behavior; the residual path keeps them alive in the layer below).
    """
    n, e = probs.shape
    expert = probs.argmax(axis=-1)                      # (N,)
    onehot = jax.nn.one_hot(expert, e, dtype=probs.dtype)  # (N, E)
    # position of each token within its expert's queue (prefix count)
    pos = (jnp.cumsum(onehot, axis=0) - onehot) * onehot   # (N, E)
    pos = pos.sum(axis=-1).astype(jnp.int32)               # (N,)
    keep = pos < capacity
    slot = jax.nn.one_hot(pos, capacity, dtype=probs.dtype)  # (N, C)
    dispatch = onehot[:, :, None] * slot[:, None, :] \
        * keep[:, None, None].astype(probs.dtype)
    gate = jnp.take_along_axis(probs, expert[:, None], 1)[:, 0]
    combine = dispatch * gate[:, None, None]
    return dispatch, combine


def expert_ffn(xe, w1, b1, w2, b2):
    """Per-expert 2-layer FFN. xe: (E, C, D), w1: (E, D, H), w2: (E, H, D)."""
    h = jnp.maximum(jnp.einsum("ecd,edh->ech", xe, w1) + b1[:, None, :],
                    0.0)
    return jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]


def moe_forward(x, wr, w1, b1, w2, b2, capacity: Optional[int] = None):
    """Golden dense MoE: all experts resident. x: (N, D) -> (N, D)."""
    n, d = x.shape
    e = wr.shape[1]
    if capacity is None:
        capacity = max(1, (2 * n) // e)
    probs = router_probs(x, wr)
    dispatch, combine = top1_dispatch(probs, capacity)
    xe = jnp.einsum("nd,nec->ecd", x, dispatch)       # gather to slots
    ye = expert_ffn(xe, w1, b1, w2, b2)               # (E, C, D)
    return jnp.einsum("ecd,nec->nd", ye, combine)     # weighted scatter


def moe_forward_ep(x, wr, w1, b1, w2, b2, axis_name: str,
                   capacity: Optional[int] = None):
    """Expert-parallel MoE inside shard_map: each device holds N/n_dev
    tokens and E/n_dev experts (w1/b1/w2/b2 sharded on the expert dim;
    x and wr sharded on tokens / replicated).

    Routing is computed locally over ALL E experts, then a token
    `all_to_all` ships each device's per-expert slot buffers to the
    device owning those experts; the expert FFN runs on local experts;
    a second `all_to_all` returns the results. This is the standard
    expert-parallel exchange, riding ICI.
    """
    n_dev = _axis_size(axis_name)
    n_loc, d = x.shape
    e_total = wr.shape[1]
    e_loc = w1.shape[0]
    assert e_loc * n_dev == e_total, (e_loc, n_dev, e_total)
    if capacity is None:
        capacity = max(1, (2 * n_loc) // e_total)
    probs = router_probs(x, wr)                        # (Nloc, E)
    dispatch, combine = top1_dispatch(probs, capacity)  # (Nloc, E, C)
    xe = jnp.einsum("nd,nec->ecd", x, dispatch)        # (E, C, D) local
    # exchange: split the expert dim across devices; after all_to_all each
    # device holds its OWN experts' slots from every source device:
    # (E, C, D) -> (n_dev·Eloc, C, D) -> a2a -> (n_dev, Eloc, C, D)
    xe = xe.reshape(n_dev, e_loc, capacity, d)
    xe = lax.all_to_all(xe, axis_name, split_axis=0, concat_axis=0,
                        tiled=False)                   # (n_dev, Eloc, C, D)
    xe = xe.transpose(1, 0, 2, 3).reshape(e_loc, n_dev * capacity, d)
    ye = expert_ffn(xe, w1, b1, w2, b2)                # local experts
    ye = ye.reshape(e_loc, n_dev, capacity, d).transpose(1, 0, 2, 3)
    ye = lax.all_to_all(ye, axis_name, split_axis=0, concat_axis=0,
                        tiled=False)                   # back to sources
    ye = ye.reshape(e_total, capacity, d)
    return jnp.einsum("ecd,nec->nd", ye, combine)
