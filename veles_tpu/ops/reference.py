"""Golden NumPy implementations of every znicz op (forward and backward).

Parity: the reference's NumPy backend (`numpy_run` methods across
`veles/znicz/*.py`) — the bit-authoritative model its OpenCL/CUDA kernels
were tested against. Here it plays the same role against `ops.xla`.

Activation semantics follow the reference:
- "tanh" is the scaled LeCun tanh  y = 1.7159·tanh(0.6666·x)
  (reference `All2AllTanh`/`ConvTanh`);
- "relu" is the reference's smooth RELU  y = ln(1+eˣ) (softplus)
  (reference `All2AllRELU`);
- "strictrelu" is max(x, 0) (reference `All2AllStrictRELU`/`ConvStrictRELU`).
Backward derivatives are expressed in terms of the *output* y where the
reference did so (tanh/sigmoid/relu), keeping its memory model (no need to
retain pre-activations).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------

TANH_A = 1.7159
TANH_B = 0.6666


def act_forward(name: str, x: np.ndarray) -> np.ndarray:
    if name == "linear":
        return x
    if name == "tanh":
        return TANH_A * np.tanh(TANH_B * x)
    if name == "relu":  # reference RELU = softplus
        return np.logaddexp(x, 0.0)
    if name == "strictrelu":
        return np.maximum(x, 0.0)
    if name == "sigmoid":
        return 1.0 / (1.0 + np.exp(-x))
    if name == "log":  # reference Log activation: asinh
        return np.arcsinh(x)
    raise ValueError(f"unknown activation {name!r}")


def act_backward(name: str, y: np.ndarray, err: np.ndarray,
                 x: Optional[np.ndarray] = None) -> np.ndarray:
    """dL/dx given dL/dy (=err) and the forward output y (input x only for
    activations whose derivative needs it)."""
    if name == "linear":
        return err
    if name == "tanh":
        return err * (TANH_B * (TANH_A - y * y / TANH_A))
    if name == "relu":
        return err * (1.0 - np.exp(-y))
    if name == "strictrelu":
        return err * (y > 0)
    if name == "sigmoid":
        return err * y * (1.0 - y)
    if name == "log":
        assert x is not None
        return err / np.sqrt(x * x + 1.0)
    raise ValueError(f"unknown activation {name!r}")


# ---------------------------------------------------------------------------
# fully connected (parity: veles/znicz/all2all.py + gd.py)
# ---------------------------------------------------------------------------

def all2all_forward(x: np.ndarray, w: np.ndarray, b: np.ndarray,
                    activation: str = "linear") -> np.ndarray:
    """y = act(x @ W + b); x: (N, in), W: (in, out), b: (out,)."""
    x2 = x.reshape(x.shape[0], -1)
    return act_forward(activation, x2 @ w + b)


def all2all_backward(x: np.ndarray, w: np.ndarray, y: np.ndarray,
                     err_y: np.ndarray, activation: str = "linear"
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Returns (err_x, dW, db) — parity: GradientDescent.numpy_run."""
    x2 = x.reshape(x.shape[0], -1)
    pre_err = act_backward(activation, y, err_y)
    dw = x2.T @ pre_err
    db = pre_err.sum(axis=0)
    err_x = (pre_err @ w.T).reshape(x.shape)
    return err_x, dw, db


def softmax(x: np.ndarray) -> np.ndarray:
    """Max-subtracted softmax (parity: All2AllSoftmax fused max-subtract)."""
    e = np.exp(x - x.max(axis=-1, keepdims=True))
    return e / e.sum(axis=-1, keepdims=True)


# ---------------------------------------------------------------------------
# convolution (parity: veles/znicz/conv.py + gd_conv.py) — NHWC / HWIO
# ---------------------------------------------------------------------------

def _im2col(x: np.ndarray, kh: int, kw: int, sy: int, sx: int,
            ph: int, pw: int) -> Tuple[np.ndarray, int, int]:
    n, h, w, c = x.shape
    xp = np.pad(x, ((0, 0), (ph, ph), (pw, pw), (0, 0)))
    oh = (h + 2 * ph - kh) // sy + 1
    ow = (w + 2 * pw - kw) // sx + 1
    cols = np.zeros((n, oh, ow, kh, kw, c), x.dtype)
    for i in range(kh):
        for j in range(kw):
            cols[:, :, :, i, j, :] = xp[:, i:i + oh * sy:sy, j:j + ow * sx:sx, :]
    return cols, oh, ow


def conv2d_forward(x: np.ndarray, w: np.ndarray, b: np.ndarray,
                   stride: Tuple[int, int] = (1, 1),
                   padding: Tuple[int, int] = (0, 0),
                   activation: str = "linear") -> np.ndarray:
    """x: (N,H,W,C), w: (kh,kw,C,OC), b: (OC,) -> (N,OH,OW,OC)."""
    kh, kw, _, oc = w.shape
    cols, oh, ow = _im2col(x, kh, kw, *stride, *padding)
    y = np.tensordot(cols, w, axes=([3, 4, 5], [0, 1, 2])) + b
    return act_forward(activation, y)


def conv2d_backward(x: np.ndarray, w: np.ndarray, y: np.ndarray,
                    err_y: np.ndarray,
                    stride: Tuple[int, int] = (1, 1),
                    padding: Tuple[int, int] = (0, 0),
                    activation: str = "linear"
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Returns (err_x, dW, db) — parity: GradientDescentConv."""
    n, h, wid, c = x.shape
    kh, kw, _, oc = w.shape
    sy, sx = stride
    ph, pw = padding
    pre_err = act_backward(activation, y, err_y)  # (N,OH,OW,OC)
    cols, oh, ow = _im2col(x, kh, kw, sy, sx, ph, pw)
    dw = np.tensordot(cols, pre_err, axes=([0, 1, 2], [0, 1, 2]))
    db = pre_err.sum(axis=(0, 1, 2))
    # scatter err back through im2col (col2im)
    dcols = np.tensordot(pre_err, w, axes=([3], [3]))  # (N,OH,OW,kh,kw,C)
    err_xp = np.zeros((n, h + 2 * ph, wid + 2 * pw, c), x.dtype)
    for i in range(kh):
        for j in range(kw):
            err_xp[:, i:i + oh * sy:sy, j:j + ow * sx:sx, :] += \
                dcols[:, :, :, i, j, :]
    err_x = err_xp[:, ph:ph + h, pw:pw + wid, :]
    return err_x, dw, db


def deconv2d_forward(x: np.ndarray, w: np.ndarray,
                     stride: Tuple[int, int] = (1, 1),
                     padding: Tuple[int, int] = (0, 0),
                     out_hw: Optional[Tuple[int, int]] = None) -> np.ndarray:
    """Transposed conv (parity: veles/znicz/deconv.py `Deconv`): the adjoint
    of conv2d_forward wrt its input. x: (N,OH,OW,OC), w: (kh,kw,C,OC)."""
    n, oh, ow, oc = x.shape
    kh, kw, c, _ = w.shape
    sy, sx = stride
    ph, pw = padding
    if out_hw is None:
        out_hw = ((oh - 1) * sy + kh - 2 * ph, (ow - 1) * sx + kw - 2 * pw)
    h, wid = out_hw
    dcols = np.tensordot(x, w, axes=([3], [3]))  # (N,OH,OW,kh,kw,C)
    yp = np.zeros((n, h + 2 * ph, wid + 2 * pw, c), x.dtype)
    for i in range(kh):
        for j in range(kw):
            yp[:, i:i + oh * sy:sy, j:j + ow * sx:sx, :] += \
                dcols[:, :, :, i, j, :]
    return yp[:, ph:ph + h, pw:pw + wid, :]


def deconv2d_backward(x: np.ndarray, w: np.ndarray, err_y: np.ndarray,
                      stride: Tuple[int, int] = (1, 1),
                      padding: Tuple[int, int] = (0, 0)
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Gradient of deconv2d_forward (parity: veles/znicz/gd_deconv.py
    `GDDeconv`). Since deconv is the adjoint of conv wrt its input, its
    input-gradient is the plain forward conv of err_y, and its weight
    gradient is conv's dW with the roles of input and output error swapped.
    x: (N,OH,OW,OC), w: (kh,kw,C,OC), err_y: (N,H,W,C).
    Returns (err_x, dW)."""
    kh, kw, c, oc = w.shape
    zero_b = np.zeros((oc,), x.dtype)
    err_x = conv2d_forward(err_y, w, zero_b, stride, padding)
    cols, _, _ = _im2col(err_y, kh, kw, *stride, *padding)
    dw = np.tensordot(cols, x, axes=([0, 1, 2], [0, 1, 2]))
    return err_x, dw


def depool_forward(x: np.ndarray, idx: np.ndarray,
                   out_shape: Tuple[int, ...]) -> np.ndarray:
    """Depooling (parity: veles/znicz/depooling.py): scatter each pooled
    value back to its recorded winner offset — the exact adjoint of max
    pooling, used by autoencoder decoders. Sentinel offsets (== out size)
    mark dead windows and are dropped."""
    out = np.zeros(int(np.prod(out_shape)) + 1, x.dtype)
    np.add.at(out, idx.ravel(), x.ravel())
    return out[:-1].reshape(out_shape)


def depool_backward(err_y: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """Gather: dL/dx of the scatter is err at each winner offset."""
    flat = np.append(err_y.ravel(), 0.0).astype(err_y.dtype)
    return flat[idx.ravel()].reshape(idx.shape)


def cut_forward(x: np.ndarray, crop: Tuple[int, int]) -> np.ndarray:
    """Cutter (parity: veles/znicz/cutter.py): crop `crop` = (cy, cx)
    border pixels off each spatial edge."""
    cy, cx = crop
    n, h, w, c = x.shape
    return x[:, cy:h - cy, cx:w - cx, :].copy()


def cut_backward(err_y: np.ndarray, x_shape: Tuple[int, ...],
                 crop: Tuple[int, int]) -> np.ndarray:
    cy, cx = crop
    err_x = np.zeros(x_shape, err_y.dtype)
    err_x[:, cy:x_shape[1] - cy, cx:x_shape[2] - cx, :] = err_y
    return err_x


# ---------------------------------------------------------------------------
# pooling (parity: veles/znicz/pooling.py + gd_pooling.py)
# ---------------------------------------------------------------------------

def _pool_windows(x, ky, kx, sy, sx):
    n, h, w, c = x.shape
    oh = int(np.ceil((h - ky) / sy)) + 1 if h > ky else 1
    ow = int(np.ceil((w - kx) / sx)) + 1 if w > kx else 1
    return oh, ow


def maxpool_forward(x: np.ndarray, ksize: Tuple[int, int],
                    stride: Tuple[int, int], use_abs: bool = False
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Max (or max-|·|, sign kept — reference MaxAbsPooling) pooling.
    Returns (y, flat offsets of the winners into x) — the reference kernels
    record argmax offsets for the backward scatter."""
    n, h, w, c = x.shape
    ky, kx = ksize
    sy, sx = stride
    oh, ow = _pool_windows(x, ky, kx, sy, sx)
    y = np.zeros((n, oh, ow, c), x.dtype)
    idx = np.zeros((n, oh, ow, c), np.int64)
    for i in range(oh):
        for j in range(ow):
            y0, x0 = i * sy, j * sx
            win = x[:, y0:y0 + ky, x0:x0 + kx, :]
            key = np.abs(win) if use_abs else win
            flat = key.reshape(n, -1, c)
            am = flat.argmax(axis=1)  # (n, c)
            wh = win.shape[1] * win.shape[2]
            picked = np.take_along_axis(win.reshape(n, wh, c), am[:, None, :],
                                        1)[:, 0, :]
            y[:, i, j, :] = picked
            dy, dx = np.unravel_index(am, (win.shape[1], win.shape[2]))
            nn = np.arange(n)[:, None]
            cc = np.arange(c)[None, :]
            idx[:, i, j, :] = ((nn * h + (y0 + dy)) * w + (x0 + dx)) * c + cc
    return y, idx


def maxpool_backward(err_y: np.ndarray, idx: np.ndarray,
                     x_shape: Tuple[int, ...]) -> np.ndarray:
    err_x = np.zeros(int(np.prod(x_shape)), err_y.dtype)
    np.add.at(err_x, idx.ravel(), err_y.ravel())
    return err_x.reshape(x_shape)


def stochastic_pool_forward(x: np.ndarray, rng: np.random.RandomState,
                            ksize: Tuple[int, int], stride: Tuple[int, int]
                            ) -> Tuple[np.ndarray, np.ndarray]:
    """Stochastic pooling (Zeiler & Fergus; reference StochasticPooling):
    sample a window element with probability ∝ its positive magnitude;
    all-nonpositive windows yield 0. Returns (y, flat winner offsets into x;
    `x.size` marks dead windows — the backward scatter skips those).

    Sampling is host-RNG-driven so it cannot match the XLA path
    sample-for-sample; tests assert distributional/structural properties
    instead (the reference had the same OpenCL-vs-numpy RNG split)."""
    n, h, w, c = x.shape
    ky, kx = ksize
    sy, sx = stride
    oh, ow = _pool_windows(x, ky, kx, sy, sx)
    y = np.zeros((n, oh, ow, c), x.dtype)
    idx = np.full((n, oh, ow, c), x.size, np.int64)
    for i in range(oh):
        for j in range(ow):
            y0, x0 = i * sy, j * sx
            win = x[:, y0:y0 + ky, x0:x0 + kx, :]
            wh = win.shape[1] * win.shape[2]
            flat = win.reshape(n, wh, c)
            pos = np.maximum(flat, 0.0)
            tot = pos.sum(axis=1)                       # (n, c)
            cum = np.cumsum(pos, axis=1)
            u = rng.random_sample((n, 1, c)) * tot[:, None, :]
            am = (cum > u).argmax(axis=1)               # first bin past u
            picked = np.take_along_axis(flat, am[:, None, :], 1)[:, 0, :]
            alive = tot > 0
            y[:, i, j, :] = np.where(alive, picked, 0.0)
            dy, dx = np.unravel_index(am, (win.shape[1], win.shape[2]))
            nn = np.arange(n)[:, None]
            cc = np.arange(c)[None, :]
            off = ((nn * h + (y0 + dy)) * w + (x0 + dx)) * c + cc
            idx[:, i, j, :] = np.where(alive, off, x.size)
    return y, idx


def stochastic_pool_backward(err_y: np.ndarray, idx: np.ndarray,
                             x_shape: Tuple[int, ...]) -> np.ndarray:
    """Scatter err to the sampled winners; `x.size` offsets (dead windows)
    land in a scratch slot that is dropped."""
    err_x = np.zeros(int(np.prod(x_shape)) + 1, err_y.dtype)
    np.add.at(err_x, idx.ravel(), err_y.ravel())
    return err_x[:-1].reshape(x_shape)


def avgpool_forward(x: np.ndarray, ksize: Tuple[int, int],
                    stride: Tuple[int, int]) -> np.ndarray:
    n, h, w, c = x.shape
    ky, kx = ksize
    sy, sx = stride
    oh, ow = _pool_windows(x, ky, kx, sy, sx)
    y = np.zeros((n, oh, ow, c), x.dtype)
    for i in range(oh):
        for j in range(ow):
            win = x[:, i * sy:i * sy + ky, j * sx:j * sx + kx, :]
            y[:, i, j, :] = win.mean(axis=(1, 2))
    return y


def avgpool_backward(err_y: np.ndarray, x_shape: Tuple[int, ...],
                     ksize: Tuple[int, int], stride: Tuple[int, int]
                     ) -> np.ndarray:
    n, h, w, c = x_shape
    ky, kx = ksize
    sy, sx = stride
    oh, ow = err_y.shape[1], err_y.shape[2]
    err_x = np.zeros(x_shape, err_y.dtype)
    for i in range(oh):
        for j in range(ow):
            win = err_x[:, i * sy:i * sy + ky, j * sx:j * sx + kx, :]
            cnt = win.shape[1] * win.shape[2]
            win += (err_y[:, i:i + 1, j:j + 1, :] / cnt)
    return err_x


# ---------------------------------------------------------------------------
# local response normalization (parity: veles/znicz/normalization.py)
# ---------------------------------------------------------------------------

def lrn_forward(x: np.ndarray, k: float = 2.0, alpha: float = 1e-4,
                beta: float = 0.75, n: int = 5) -> np.ndarray:
    """AlexNet-style across-channel LRN: y = x / (k + α·Σ x²)^β over a
    window of n channels centered at each channel."""
    sq = x * x
    c = x.shape[-1]
    half = n // 2
    ssum = np.zeros_like(x)
    for d in range(-half, half + 1):
        lo, hi = max(0, -d), min(c, c - d)
        ssum[..., lo:hi] += sq[..., lo + d:hi + d]
    return x * (k + alpha * ssum) ** (-beta)


def lrn_backward(x: np.ndarray, err_y: np.ndarray, k: float = 2.0,
                 alpha: float = 1e-4, beta: float = 0.75, n: int = 5
                 ) -> np.ndarray:
    """Hand-derived LRN gradient (the reference shipped a dedicated kernel;
    SURVEY.md §7 lists LRN backward as a Pallas candidate on TPU)."""
    sq = x * x
    c = x.shape[-1]
    half = n // 2
    ssum = np.zeros_like(x)
    for d in range(-half, half + 1):
        lo, hi = max(0, -d), min(c, c - d)
        ssum[..., lo:hi] += sq[..., lo + d:hi + d]
    scale = k + alpha * ssum
    # dy_i/dx_j = δ_ij·scale_i^-β − 2αβ·x_i·x_j·scale_i^-(β+1) for |i−j|≤half
    t = err_y * x * scale ** (-beta - 1.0)  # (…, c)
    tsum = np.zeros_like(x)
    for d in range(-half, half + 1):
        lo, hi = max(0, -d), min(c, c - d)
        tsum[..., lo:hi] += t[..., lo + d:hi + d]
    return err_y * scale ** (-beta) - 2.0 * alpha * beta * x * tsum


# ---------------------------------------------------------------------------
# composed goldens (NO 2015 parity — the gates for the searched CROSS-OP
# fusion templates, ops/templates.py). Each is built by COMPOSING the
# existing per-op goldens above, nothing else: tests assert these helpers
# are BITWISE equal to applying the member goldens sequentially, so a
# fused Pallas kernel gated against a composed golden is transitively
# gated against every member op's golden.
# ---------------------------------------------------------------------------

def lrn_maxpool_forward(x: np.ndarray, k: float = 2.0, alpha: float = 1e-4,
                        beta: float = 0.75, n: int = 5,
                        ksize: Tuple[int, int] = (3, 3),
                        stride: Tuple[int, int] = (2, 2)) -> np.ndarray:
    """LRN then max pooling over the same activation — the composed
    golden the fused `lrn_maxpool` template points are gated against."""
    y = lrn_forward(x, k, alpha, beta, n)
    return maxpool_forward(y, ksize, stride, False)[0]


def lrn_maxpool_backward(x: np.ndarray, err_y: np.ndarray, k: float = 2.0,
                         alpha: float = 1e-4, beta: float = 0.75,
                         n: int = 5, ksize: Tuple[int, int] = (3, 3),
                         stride: Tuple[int, int] = (2, 2)) -> np.ndarray:
    """Backward of the composed pair: scatter the pooled error to the
    recorded winners (first max in window scan order — the argmax
    convention every maxpool golden and lowering shares), then the LRN
    backward."""
    y = lrn_forward(x, k, alpha, beta, n)
    _, idx = maxpool_forward(y, ksize, stride, False)
    g_lrn = maxpool_backward(err_y, idx, y.shape)
    return lrn_backward(x, g_lrn, k, alpha, beta, n)


def conv_lrn_forward(x: np.ndarray, w: np.ndarray, b: np.ndarray,
                     stride: Tuple[int, int] = (1, 1),
                     padding: Tuple[int, int] = (0, 0),
                     activation: str = "linear", k: float = 2.0,
                     alpha: float = 1e-4, beta: float = 0.75,
                     n: int = 5) -> np.ndarray:
    """conv+bias+activation with the LRN folded into the epilogue — the
    composed golden for the conv_stem template's `epi=lrn` points."""
    return lrn_forward(conv2d_forward(x, w, b, stride, padding,
                                      activation), k, alpha, beta, n)


def conv_lrn_backward(x: np.ndarray, w: np.ndarray, b: np.ndarray,
                      err_y: np.ndarray,
                      stride: Tuple[int, int] = (1, 1),
                      padding: Tuple[int, int] = (0, 0),
                      activation: str = "linear", k: float = 2.0,
                      alpha: float = 1e-4, beta: float = 0.75, n: int = 5
                      ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(err_x, dW, db) of the composed conv+LRN epilogue."""
    y_conv = conv2d_forward(x, w, b, stride, padding, activation)
    g_conv = lrn_backward(y_conv, err_y, k, alpha, beta, n)
    return conv2d_backward(x, w, y_conv, g_conv, stride, padding,
                           activation)


def attn_dropout_forward(q: np.ndarray, k: np.ndarray, v: np.ndarray,
                         mask: np.ndarray, scale: float = None,
                         causal: bool = False) -> np.ndarray:
    """Attention with the pre-scaled dropout mask applied to the output
    block — the composed golden for the flash_attn template's `drop=1`
    points (mask (B, S, H, D), values 0 or 1/keep; the backward leg is
    `dropout_backward` on the incoming error, composed in tests)."""
    return dropout_forward(mha_forward(q, k, v, scale=scale,
                                       causal=causal), mask)


# ---------------------------------------------------------------------------
# fused SGD+momentum update (parity: veles/znicz/nn_units.py weight-update
# kernels; the golden for the `sgd_update` lowering variants)
# ---------------------------------------------------------------------------

def sgd_momentum_update(p: np.ndarray, g: np.ndarray, v: np.ndarray,
                        lr: float, momentum: float = 0.0,
                        weight_decay: float = 0.0,
                        l1_decay: float = 0.0):
    """One leaf of the reference update rule:
    v ← μ·v − lr·(g + λ2·w + λ1·sign(w));  w ← w + v."""
    reg = g + weight_decay * p + l1_decay * np.sign(p)
    v_new = momentum * v - lr * reg
    return p + v_new, v_new


# ---------------------------------------------------------------------------
# blockwise int8 quantization (NO 2015 parity — the golden for the EQuARX
# `grad_reduce` wire compression, arxiv 2506.17615: per-block absmax
# scales, round-to-nearest-even codes. The jax twins in ops.variants
# (`q8_encode`/`q8_decode`) must reproduce these BITWISE — codes, scales
# and the dequantized values — which the grad_reduce equivalence contract
# asserts before any quantized collective may be timed or trained with.)
# ---------------------------------------------------------------------------

def quantize_blockwise(x: np.ndarray, block: int
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Per-block absmax int8 quantization over the LAST axis (its length
    must divide `block` — callers zero-pad first; a zero pad block gets
    scale 1 and all-zero codes, contributing nothing on dequantize).
    Returns (codes int8, scales f32); codes = clip(rint(x/scale), ±127)
    with scale = absmax/127 (1.0 for an all-zero block)."""
    assert x.shape[-1] % block == 0, (x.shape, block)
    xb = x.reshape(x.shape[:-1] + (x.shape[-1] // block, block)) \
        .astype(np.float32)
    absmax = np.max(np.abs(xb), axis=-1)
    scale = np.where(absmax > 0, absmax / np.float32(127.0),
                     np.float32(1.0)).astype(np.float32)
    q = np.clip(np.rint(xb / scale[..., None]), -127, 127).astype(np.int8)
    return q.reshape(x.shape), scale


def dequantize_blockwise(q: np.ndarray, scale: np.ndarray,
                         block: int) -> np.ndarray:
    """Inverse of `quantize_blockwise`: codes x scales -> f32 values."""
    assert q.shape[-1] % block == 0, (q.shape, block)
    qb = q.reshape(q.shape[:-1] + (q.shape[-1] // block, block)) \
        .astype(np.float32)
    return (qb * scale[..., None].astype(np.float32)).reshape(q.shape)


# ---------------------------------------------------------------------------
# quantized serving forward (NO 2015 parity — the golden the
# `serve_forward` registry variants are equivalence-gated against,
# ISSUE 15: the low-byte serving path is only ever a ledger-gated point.
# Weight-only quantization reuses the blockwise int8 golden above — one
# quantization rule for collectives and serving, never two.)
# ---------------------------------------------------------------------------

def serve_forward_mlp(x: np.ndarray, layers) -> np.ndarray:
    """Canonical tanh-MLP serving forward in numpy: `layers` is a list
    of (w, b) pairs, tanh between layers, linear head. The serve_forward
    equivalence contract runs every wire variant against THIS model with
    the variant's own weight transform applied through the reference
    quantizers, so the contract isolates the forward math from the
    (separately bitwise-asserted) quantization."""
    h = x.astype(np.float64)
    for i, (w, b) in enumerate(layers):
        h = h @ w.astype(np.float64) + b.astype(np.float64)
        if i < len(layers) - 1:
            h = np.tanh(h)
    return h.astype(np.float32)


def serve_quantize_weight(w: np.ndarray, block: int
                          ) -> Tuple[np.ndarray, np.ndarray]:
    """Weight-only int8 serving transform of one >=2-D param leaf:
    reshape to (rows, cols) = (prod(leading), last), zero-pad cols to a
    block multiple, per-block absmax int8 via `quantize_blockwise`.
    Returns (codes int8 (rows, colsp), scales f32 (rows, colsp//block)).
    The jax dequantize in ops.variants must reproduce
    `dequantize_blockwise` of exactly these codes/scales — the
    serve_forward contract asserts it."""
    rows = int(np.prod(w.shape[:-1], dtype=np.int64))
    cols = w.shape[-1]
    pad = (-cols) % block
    w2 = w.reshape(rows, cols).astype(np.float32)
    if pad:
        w2 = np.concatenate(
            [w2, np.zeros((rows, pad), np.float32)], axis=1)
    return quantize_blockwise(w2, block)


# ---------------------------------------------------------------------------
# multi-head attention (NO 2015 parity — the reference framework has no
# attention anywhere, SURVEY.md §5.7; this numpy model is the golden the
# `flash_attn` lowering variants are equivalence-gated against)
# ---------------------------------------------------------------------------

def mha_forward(q: np.ndarray, k: np.ndarray, v: np.ndarray,
                scale: float = None, causal: bool = False) -> np.ndarray:
    """Plain softmax attention in numpy. q/k/v: (B, S, H, D) ->
    (B, S, H, D)."""
    b, s, h, d = q.shape
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    sc = np.einsum("bqhd,bkhd->bhqk", q, k).astype(np.float64) * scale
    if causal:
        mask = np.tril(np.ones((s, s), bool))
        sc = np.where(mask[None, None], sc, -np.inf)
    sc -= sc.max(axis=-1, keepdims=True)
    p = np.exp(sc)
    p /= p.sum(axis=-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v.astype(np.float64)) \
        .astype(q.dtype)


# ---------------------------------------------------------------------------
# dropout (parity: veles/znicz/dropout.py)
# ---------------------------------------------------------------------------

def dropout_forward(x: np.ndarray, mask: np.ndarray) -> np.ndarray:
    """mask is pre-scaled (0 or 1/keep_prob), generated by the caller's PRNG;
    the reference likewise generated the mask with its device RNG kernel."""
    return x * mask


def dropout_backward(err_y: np.ndarray, mask: np.ndarray) -> np.ndarray:
    return err_y * mask


def make_dropout_mask(rng: np.random.RandomState, shape, drop_prob: float,
                      dtype=np.float32) -> np.ndarray:
    keep = 1.0 - drop_prob
    return (rng.random_sample(shape) < keep).astype(dtype) / dtype(keep)


# ---------------------------------------------------------------------------
# evaluators (parity: veles/znicz/evaluator.py)
# ---------------------------------------------------------------------------

def softmax_ce(probs: np.ndarray, labels: np.ndarray, n_classes: int,
               weights: np.ndarray = None
               ) -> Tuple[float, np.ndarray, int, np.ndarray]:
    """EvaluatorSoftmax: input is the softmax OUTPUT (All2AllSoftmax yields
    probabilities). Returns (mean CE loss, err wrt pre-softmax logits,
    n_err, confusion matrix). `weights` (N,) sample weights (the Loader's
    pad mask) — zero rows drop out of every metric; None == all-ones.

    Deviation from reference (documented): err is divided by batch size so
    learning rates are batch-size-invariant; the reference folded this into
    its lr convention.
    """
    n = probs.shape[0]
    onehot = np.zeros((n, n_classes), probs.dtype)
    onehot[np.arange(n), labels] = 1.0
    eps = np.finfo(probs.dtype).tiny
    logs = -np.log(np.maximum(probs[np.arange(n), labels], eps))
    pred = probs.argmax(axis=1)
    wrong = pred != labels
    confusion = np.zeros((n_classes, n_classes), np.int64)
    if weights is None:
        loss = float(logs.mean())
        err = (probs - onehot) / np.asarray(n, probs.dtype)
        n_err = int(wrong.sum())
        np.add.at(confusion, (labels, pred), 1)
    else:
        w = weights.astype(probs.dtype)
        wsum = max(float(w.sum()), float(eps))
        loss = float((logs * w).sum() / wsum)
        err = (probs - onehot) * w[:, None] / wsum
        n_err = int((wrong & (w > 0)).sum())
        np.add.at(confusion, (labels, pred), (w > 0).astype(np.int64))
    return loss, err, n_err, confusion


def mse(y: np.ndarray, target: np.ndarray, weights: np.ndarray = None
        ) -> Tuple[float, np.ndarray]:
    """EvaluatorMSE: returns (mean-over-batch MSE, err wrt y); `weights`
    (N,) sample weights as in softmax_ce."""
    n = y.shape[0]
    diff = y - target
    if weights is None:
        loss = float((diff * diff).sum() / n)
        return loss, 2.0 * diff / np.asarray(n, y.dtype)
    wb = weights.astype(y.dtype).reshape((n,) + (1,) * (y.ndim - 1))
    wsum = max(float(weights.sum()), 1e-9)
    loss = float((wb * diff * diff).sum() / wsum)
    return loss, 2.0 * diff * wb / np.asarray(wsum, y.dtype)


# ---------------------------------------------------------------------------
# Kohonen SOM (parity: veles/znicz/kohonen.py — NOT gradient descent)
# ---------------------------------------------------------------------------

def kohonen_forward(x: np.ndarray, w: np.ndarray) -> np.ndarray:
    """Winner indices: argmin over squared L2 distance to each neuron.
    x: (N, D), w: (K, D) -> (N,) int winners."""
    d2 = (x * x).sum(1)[:, None] - 2.0 * x @ w.T + (w * w).sum(1)[None, :]
    return d2.argmin(axis=1)


def kohonen_update(x: np.ndarray, w: np.ndarray, grid: np.ndarray,
                   lr: float, sigma: float) -> np.ndarray:
    """One batch of neighborhood-decay updates: for each sample, every
    neuron moves toward it weighted by a Gaussian over grid distance to the
    winner. grid: (K, 2) neuron coordinates. Returns the new weights."""
    w = w.copy()
    for xi in x:
        win = int(kohonen_forward(xi[None, :], w)[0])
        gd2 = ((grid - grid[win]) ** 2).sum(axis=1)
        h = np.exp(-gd2 / (2.0 * sigma * sigma)).astype(w.dtype)
        w += lr * h[:, None] * (xi[None, :] - w)
    return w


# ---------------------------------------------------------------------------
# RBM (parity: veles/znicz/rbm_units.py — CD-1)
# ---------------------------------------------------------------------------

def rbm_cd1(v0: np.ndarray, w: np.ndarray, bv: np.ndarray, bh: np.ndarray,
            rng: np.random.RandomState
            ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """One contrastive-divergence step. v0: (N, V), w: (V, H).
    Returns (dW, dbv, dbh) — gradients to ADD (ascent on log-likelihood)."""
    sig = lambda a: 1.0 / (1.0 + np.exp(-a))  # noqa: E731
    h0p = sig(v0 @ w + bh)
    h0 = (rng.random_sample(h0p.shape) < h0p).astype(v0.dtype)
    v1p = sig(h0 @ w.T + bv)
    h1p = sig(v1p @ w + bh)
    n = v0.shape[0]
    dw = (v0.T @ h0p - v1p.T @ h1p) / n
    dbv = (v0 - v1p).mean(axis=0)
    dbh = (h0p - h1p).mean(axis=0)
    return dw, dbv, dbh


# ---------------------------------------------------------------------------
# LSTM cell (parity: the reference's char-LSTM built from all2all+activation
# units with explicit unrolling; here a fused cell, scanned on device)
# ---------------------------------------------------------------------------

def lstm_step(x: np.ndarray, h: np.ndarray, c: np.ndarray, wx: np.ndarray,
              wh: np.ndarray, b: np.ndarray
              ) -> Tuple[np.ndarray, np.ndarray]:
    """Standard LSTM cell; gate order [i, f, g, o]. wx: (D, 4H), wh: (H, 4H)."""
    sig = lambda a: 1.0 / (1.0 + np.exp(-a))  # noqa: E731
    z = x @ wx + h @ wh + b
    hsz = h.shape[1]
    i = sig(z[:, 0 * hsz:1 * hsz])
    f = sig(z[:, 1 * hsz:2 * hsz])
    g = np.tanh(z[:, 2 * hsz:3 * hsz])
    o = sig(z[:, 3 * hsz:4 * hsz])
    c_new = f * c + i * g
    h_new = o * np.tanh(c_new)
    return h_new, c_new


def lstm_forward(xs: np.ndarray, h0: np.ndarray, c0: np.ndarray,
                 wx: np.ndarray, wh: np.ndarray, b: np.ndarray
                 ) -> Tuple[np.ndarray, dict]:
    """Unrolled forward over time. xs: (T, N, D) -> hs: (T, N, H), plus the
    per-step cache (gates, cell states) that lstm_backward consumes."""
    sig = lambda a: 1.0 / (1.0 + np.exp(-a))  # noqa: E731
    T, n, _ = xs.shape
    hsz = h0.shape[1]
    hs = np.zeros((T, n, hsz), xs.dtype)
    cache = {k: np.zeros((T, n, hsz), xs.dtype)
             for k in ("i", "f", "g", "o", "c", "hprev", "cprev")}
    h, c = h0, c0
    for t in range(T):
        z = xs[t] @ wx + h @ wh + b
        i = sig(z[:, 0 * hsz:1 * hsz])
        f = sig(z[:, 1 * hsz:2 * hsz])
        g = np.tanh(z[:, 2 * hsz:3 * hsz])
        o = sig(z[:, 3 * hsz:4 * hsz])
        cache["hprev"][t], cache["cprev"][t] = h, c
        c = f * c + i * g
        h = o * np.tanh(c)
        for k, v in (("i", i), ("f", f), ("g", g), ("o", o), ("c", c)):
            cache[k][t] = v
        hs[t] = h
    return hs, cache


def lstm_backward(xs: np.ndarray, wx: np.ndarray, wh: np.ndarray,
                  dhs: np.ndarray, cache: dict
                  ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """BPTT through lstm_forward (parity: the reference's char-LSTM
    backward, which its unit graph unrolled step-by-step on host).
    dhs: (T, N, H) = dL/dh_t for every step. Returns (dxs, dwx, dwh, db)."""
    T, n, d = xs.shape
    hsz = dhs.shape[2]
    dxs = np.zeros_like(xs)
    dwx = np.zeros_like(wx)
    dwh = np.zeros_like(wh)
    db = np.zeros((4 * hsz,), xs.dtype)
    dh_next = np.zeros((n, hsz), xs.dtype)
    dc_next = np.zeros((n, hsz), xs.dtype)
    for t in range(T - 1, -1, -1):
        i, f, g, o = (cache[k][t] for k in ("i", "f", "g", "o"))
        c, cprev, hprev = cache["c"][t], cache["cprev"][t], cache["hprev"][t]
        tanh_c = np.tanh(c)
        dh = dhs[t] + dh_next
        dc = dc_next + dh * o * (1.0 - tanh_c * tanh_c)
        do = dh * tanh_c
        df = dc * cprev
        di = dc * g
        dg = dc * i
        dz = np.concatenate([di * i * (1 - i), df * f * (1 - f),
                             dg * (1 - g * g), do * o * (1 - o)], axis=1)
        dxs[t] = dz @ wx.T
        dh_next = dz @ wh.T
        dc_next = dc * f
        dwx += xs[t].T @ dz
        dwh += hprev.T @ dz
        db += dz.sum(axis=0)
    return dxs, dwx, dwh, db
