"""Numeric core: every znicz op as a pure function, twice.

- `ops.reference` — independent NumPy implementations (forward AND backward)
  that serve as the golden model, exactly the role the reference's NumPy
  backend played against its OpenCL/CUDA kernels (SURVEY.md §4: "the NumPy
  backend is the golden model").
- `ops.xla` — jnp/lax implementations used on TPU; backward passes come from
  `jax.vjp` over these forwards, and the equivalence tests check vjp-grads
  against the hand-derived NumPy backwards. One XLA lowering replaces both
  of the reference's hand-written kernel families (`veles/znicz/ocl/*.cl`,
  `veles/znicz/cuda/*.cu`).

Conventions (TPU-first, deliberately NOT the reference's layouts):
- images are NHWC, conv weights HWIO (XLA/MXU native);
- fully-connected weights are (in_features, out_features): y = x @ W + b.
"""

from veles_tpu.ops import reference, xla  # noqa: F401
