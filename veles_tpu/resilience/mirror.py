"""Snapshot durability backend: mirror snapshots to a second store.

PR-1 left snapshot durability to the filesystem (ROADMAP "Still
manual"); this module closes it. After every atomic local write the
Snapshotter pushes the snapshot AND its sha256 sidecar to a configurable
mirror — a second directory (NFS/attached volume) or an HTTP blob store
(`upload_url`-style PUT endpoint) — verifies the uploaded bytes against
the sidecar digest, and skips the upload entirely when the mirror
already holds a verified copy (idempotent: re-running a job over the
same snapshot stream never grows the mirror). On the restore side,
`Snapshotter.latest(mirror=...)` and the cluster member's snapshot
resolution fetch from the mirror when the local directory is missing,
truncated or corrupt — a re-placed host rejoins from durable state
instead of failing the attempt.

TRUST MODEL: mirrored snapshots are the SAME pickles the local
directory holds — code on unpickle — so a mirror must live inside the
same trust boundary as the local snapshot dir (your volume, your
loopback/token-authenticated store). `MirrorServer` below enforces the
usual loopback-testable hardening (shared token, bounded bodies,
sanitized names) but it does not make foreign pickles safe; never point
a restore at a mirror you do not own.

Import-light on purpose (stdlib only): the supervisor/cluster member
processes use this and must never initialize jax.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import threading
import urllib.error
import urllib.request
from typing import Dict, List, Optional

_log = logging.getLogger("veles.Mirror")


def _tmp_name(path: str) -> str:
    """A per-writer temp name next to `path` (still `.tmp`-suffixed so
    listings skip it). Concurrent pushes/fetches of the SAME entry —
    a respawned child re-exporting while the old push is still in
    flight, two handler threads serving the same upload — must each
    write their own temp file: a shared `path + ".tmp"` let one
    writer's atomic replace steal (or tear) another's bytes."""
    return f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"

#: mirrored snapshot bodies above this are refused by MirrorServer
#: (a snapshot is a compressed workflow pickle: even flagship runs sit
#: far below this; anything bigger is a bug or an attack)
MAX_SNAPSHOT_BODY = 1 << 30


def _sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                return h.hexdigest()
            h.update(block)


def _read_sidecar(path: str) -> Optional[str]:
    """Digest recorded in `path`'s .sha256 sidecar (None when absent or
    unreadable)."""
    try:
        with open(path + ".sha256") as f:
            return f.read().split()[0]
    except (OSError, IndexError):
        return None


def _safe_name(name: str) -> str:
    """Mirror entries are FLAT: reject anything that is not a plain
    basename (path traversal through a snapshot name must be impossible
    on both client and server side)."""
    base = os.path.basename(name)
    if not base or base != name or base.startswith(".") or "/" in name \
            or "\\" in name:
        raise ValueError(f"illegal mirror entry name {name!r}")
    return base


class Mirror:
    """One mirrored snapshot store. Entries are (name, digest, mtime)
    triples; `push` is idempotent on (name, digest)."""

    #: for logs/reports
    spec = ""

    def entries(self) -> List[Dict[str, object]]:
        """[{"name", "digest", "mtime"}] for every mirrored snapshot
        (digest from the mirrored sidecar; empty on an unreachable
        mirror — visibility is best-effort, restores re-verify)."""
        raise NotImplementedError

    def has(self, name: str, digest: str) -> bool:
        raise NotImplementedError

    def push(self, path: str) -> bool:
        """Mirror `path` + its sidecar; verify the mirrored bytes
        against the sidecar digest. Returns True when the mirror holds a
        verified copy afterwards (including the no-op case where it
        already did)."""
        raise NotImplementedError

    def fetch(self, name: str, dest_dir: str) -> Optional[str]:
        """Restore one snapshot (+ sidecar) into `dest_dir`, verifying
        the digest; returns the local path or None (missing/corrupt)."""
        raise NotImplementedError

    def delete(self, name: str) -> None:
        """Best-effort removal (keep_last pruning follows the local
        retention policy so the mirror cannot grow without bound)."""
        raise NotImplementedError

    # -- control-plane meta records -------------------------------------------
    # Tiny mutable JSON records living NEXT TO the snapshot blobs: the
    # cluster's shared rendezvous state (coordinator announcement +
    # per-host presence beacons for re-election). Last-writer-wins by
    # design — the election's claim/settle protocol builds on exactly
    # that. Meta names never contain ".pickle", so they are invisible
    # to `entries()`/quorum votes and exempt from keep_last pruning.

    def put_meta(self, name: str, record: Dict[str, object]) -> bool:
        """Atomically publish `record` under `name` (overwrites)."""
        raise NotImplementedError

    def get_meta(self, name: str) -> Optional[Dict[str, object]]:
        """The record under `name`, or None (absent/unreadable/not a
        JSON object)."""
        raise NotImplementedError

    def meta_names(self, prefix: str = "") -> List[str]:
        """Names of the meta records currently published, filtered by
        `prefix`, sorted. Empty on an unreachable mirror (discovery is
        best-effort — readers treat a missing listing like an empty
        one and re-poll). This is what makes OPEN-membership presence
        beacons possible: the cluster plane knows its host ids up
        front, but a serving-fleet router must discover replicas it was
        never told about (join-mid-run) purely from the bus."""
        raise NotImplementedError

    def _corrupt(self, name: str) -> None:
        """Deterministic bit-rot injection hook (mirror_corrupt fault):
        tear the MIRRORED copy while the local one stays intact."""
        raise NotImplementedError

    def _maybe_inject_corruption(self, name: str) -> None:
        from veles_tpu.resilience.faults import active_plan
        plan = active_plan()
        if plan is not None and plan.mirror_corrupt_at_push():
            self._corrupt(name)
            _log.warning("FAULT INJECTION: tore mirrored copy of %s",
                         name)


class DirMirror(Mirror):
    """Second-directory mirror (attached volume, NFS mount)."""

    def __init__(self, root: str, clock=None) -> None:
        from veles_tpu.resilience.clock import SYSTEM_CLOCK
        self.root = root
        self.spec = root
        self._clock = clock or SYSTEM_CLOCK

    def _path(self, name: str) -> str:
        return os.path.join(self.root, _safe_name(name))

    def entries(self) -> List[Dict[str, object]]:
        try:
            names = [n for n in os.listdir(self.root)
                     if ".pickle" in n and not n.endswith(".sha256")
                     and not n.endswith(".tmp")]
        except OSError:
            return []
        out = []
        for n in names:
            digest = _read_sidecar(self._path(n))
            if digest is None:
                continue     # sidecar-less mirror entry: not trustable
            try:
                mtime = os.path.getmtime(self._path(n))
            except OSError:
                continue
            out.append({"name": n, "digest": digest, "mtime": mtime})
        return out

    def has(self, name: str, digest: str) -> bool:
        return _read_sidecar(self._path(name)) == digest

    def push(self, path: str) -> bool:
        name = os.path.basename(path)
        digest = _read_sidecar(path) or _sha256_file(path)
        os.makedirs(self.root, exist_ok=True)
        if self.has(name, digest):
            _log.debug("mirror already holds %s (digest match): no-op",
                       name)
            return True
        dst = self._path(name)
        tmp = _tmp_name(dst)
        shutil.copyfile(path, tmp)
        if _sha256_file(tmp) != digest:      # torn read of a live file
            os.remove(tmp)
            _log.warning("mirror push of %s read back a different "
                         "digest: not published", name)
            return False
        os.replace(tmp, dst)
        side_tmp = _tmp_name(dst + ".sha256")
        with open(side_tmp, "w") as f:
            f.write(f"{digest}  {name}\n")
        os.replace(side_tmp, dst + ".sha256")
        self._maybe_inject_corruption(name)
        return True

    def fetch(self, name: str, dest_dir: str) -> Optional[str]:
        src = self._path(name)
        digest = _read_sidecar(src)
        if digest is None or not os.path.exists(src):
            return None
        if _sha256_file(src) != digest:
            _log.warning("mirror copy of %s is corrupt (digest "
                         "mismatch) — not restoring it", name)
            return None
        os.makedirs(dest_dir, exist_ok=True)
        dst = os.path.join(dest_dir, name)
        tmp = _tmp_name(dst)
        shutil.copyfile(src, tmp)
        if _sha256_file(tmp) != digest:
            os.remove(tmp)
            return None
        os.replace(tmp, dst)
        side_tmp = _tmp_name(dst + ".sha256")
        with open(side_tmp, "w") as f:
            f.write(f"{digest}  {name}\n")
        os.replace(side_tmp, dst + ".sha256")
        return dst

    def delete(self, name: str) -> None:
        for victim in (self._path(name), self._path(name) + ".sha256"):
            try:
                os.remove(victim)
            except OSError:
                pass

    def put_meta(self, name: str, record: Dict[str, object]) -> bool:
        dst = self._path(name)
        os.makedirs(self.root, exist_ok=True)
        # per-process tmp name: two hosts publishing the same record
        # concurrently must each tear nothing (last replace wins)
        tmp = _tmp_name(dst)
        try:
            with open(tmp, "w") as f:
                json.dump(record, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, dst)
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False
        return True

    #: torn-read retries in get_meta: put_meta's tmp+fsync+replace makes
    #: a mid-replace read impossible on POSIX-local stores, but the
    #: DirMirror contract includes NFS/network mounts where a reader can
    #: still observe partial bytes — retry briefly, then degrade to None
    META_READ_RETRIES = 2
    META_READ_RETRY_S = 0.02

    def get_meta(self, name: str) -> Optional[Dict[str, object]]:
        for attempt in range(self.META_READ_RETRIES + 1):
            try:
                with open(self._path(name)) as f:
                    data = json.load(f)
            except OSError:
                # absent (or unreadable) record: nothing a retry fixes
                return None
            except ValueError:
                # torn/partial JSON mid-replace: the complete record
                # lands with the writer's atomic rename — give it a
                # beat, then degrade to None (callers already treat
                # None as "no record yet" and re-poll)
                if attempt < self.META_READ_RETRIES:
                    self._clock.sleep(self.META_READ_RETRY_S)
                    continue
                _log.warning("meta record %s unparseable after %d "
                             "re-reads (torn write?) — treating as "
                             "absent", name, attempt + 1)
                return None
            return data if isinstance(data, dict) else None
        return None

    def meta_names(self, prefix: str = "") -> List[str]:
        # meta records are exactly the non-snapshot files: no ".pickle"
        # in the name (the entries() invisibility rule), no sidecars,
        # no in-flight per-writer tmp files
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        return sorted(
            n for n in names
            if ".pickle" not in n and not n.endswith((".sha256", ".tmp"))
            and n.startswith(prefix))

    def _corrupt(self, name: str) -> None:
        from veles_tpu.resilience.faults import corrupt_file
        corrupt_file(self._path(name))


class HttpMirror(Mirror):
    """HTTP blob-store mirror: PUT `{base}/{name}` (the PR-1
    `upload_url` contract) plus the sidecar, GET to verify/restore,
    `GET {base}/?index=1` for the entry listing (MirrorServer speaks
    all of these; a dumb PUT-only store still receives verified-size
    uploads, it just cannot serve restores)."""

    def __init__(self, base_url: str, token: Optional[str] = None,
                 timeout: float = 60.0, retries: int = 3,
                 retry_base: float = 0.2, retry_cap: float = 2.0,
                 retry_total: float = 8.0) -> None:
        self.base_url = base_url.rstrip("/")
        self.token = token if token is not None \
            else os.environ.get("VELES_WEB_TOKEN") or None
        self.timeout = timeout
        # bounded jittered-exponential retries on TRANSIENT failures
        # (connection refused/reset, 5xx, torn response) — a mirror that
        # blips for a second must not fail a push or a watcher poll. The
        # `retry_total` wall-clock budget is deliberately BELOW the
        # default WeightWatcher poll interval (10 s): a down mirror
        # costs at most one bounded stall per poll, never a pile-up.
        self.retries = max(int(retries), 1)
        self.retry_base = float(retry_base)
        self.retry_cap = float(retry_cap)
        self.retry_total = float(retry_total)
        self.spec = self.base_url

    # -- plumbing -------------------------------------------------------------

    def _request(self, method: str, name_or_query: str,
                 data: Optional[bytes] = None):
        req = urllib.request.Request(
            f"{self.base_url}/{name_or_query}", data=data, method=method)
        if self.token:
            req.add_header("X-Veles-Token", self.token)
        if data is not None:
            req.add_header("Content-Type", "application/octet-stream")
        return urllib.request.urlopen(req, timeout=self.timeout)

    def _retry(self, fn):
        """Run `fn` under the shared bounded-backoff policy
        (resilience/backoff.py). Transient = connection-level errors +
        torn responses + HTTP 5xx; a 4xx is PERMANENT (retrying a 404
        three times would stall every `has()` probe of a not-yet-pushed
        name) and must be handled inside `fn`. Exhaustion re-raises the
        last transient error — soft-fail callers catch it."""
        import http.client
        from veles_tpu.resilience.backoff import call_with_backoff
        return call_with_backoff(
            fn, attempts=self.retries, base=self.retry_base,
            cap=self.retry_cap, total=self.retry_total,
            retry_on=(urllib.error.URLError, OSError, ValueError,
                      http.client.HTTPException))

    def _get_bytes(self, name_or_query: str) -> Optional[bytes]:
        import http.client

        def attempt() -> Optional[bytes]:
            try:
                with self._request("GET", name_or_query) as resp:
                    return resp.read()
            except urllib.error.HTTPError as e:
                if e.code < 500:
                    return None   # permanent (404 et al.): no retry
                raise
        try:
            return self._retry(attempt)
        except (urllib.error.URLError, OSError, ValueError,
                http.client.HTTPException):
            # HTTPException covers a TORN response (IncompleteRead from
            # a blob replaced mid-stream): best-effort visibility, the
            # caller retries or degrades exactly like "unreachable"
            return None

    def _get_to_file(self, name: str, dst: str) -> Optional[str]:
        """Stream a GET into `dst`, returning the sha256 hex digest."""
        import http.client

        def attempt() -> Optional[str]:
            h = hashlib.sha256()
            try:
                # "wb" truncates: a retried attempt restarts the stream
                # from byte 0, never appends to a torn prior try
                with self._request("GET", name) as resp, \
                        open(dst, "wb") as f:
                    while True:
                        block = resp.read(1 << 20)
                        if not block:
                            break
                        h.update(block)
                        f.write(block)
            except urllib.error.HTTPError as e:
                if e.code < 500:
                    return None
                raise
            return h.hexdigest()
        try:
            got = self._retry(attempt)
        except (urllib.error.URLError, OSError, ValueError,
                http.client.HTTPException):
            got = None
        if got is None:
            try:
                os.remove(dst)
            except OSError:
                pass
        return got

    # -- Mirror API -----------------------------------------------------------

    def entries(self) -> List[Dict[str, object]]:
        raw = self._get_bytes("?index=1")
        if raw is None:
            return []
        try:
            items = json.loads(raw)
            return [{"name": _safe_name(str(i["name"])),
                     "digest": str(i["digest"]),
                     "mtime": float(i.get("mtime", 0.0))}
                    for i in items]
        except (ValueError, KeyError, TypeError):
            return []

    def has(self, name: str, digest: str) -> bool:
        raw = self._get_bytes(_safe_name(name) + ".sha256")
        if raw is None:
            return False
        try:
            return raw.decode().split()[0] == digest
        except (UnicodeDecodeError, IndexError):
            return False

    def push(self, path: str) -> bool:
        from veles_tpu.http_util import http_put_file
        name = _safe_name(os.path.basename(path))
        digest = _read_sidecar(path) or _sha256_file(path)
        if self.has(name, digest):
            _log.debug("mirror already holds %s (digest match): no-op",
                       name)
            return True
        headers = {"X-Veles-Token": self.token} if self.token else None
        self._retry(lambda: http_put_file(
            f"{self.base_url}/{name}", path,
            timeout=self.timeout, headers=headers))
        # verify-on-upload BEFORE publishing the sidecar: the sidecar
        # is what `has()`/`entries()` trust, so it must only ever sit
        # next to bytes that verified — publishing it first would turn
        # a corrupted-in-transit upload into a permanently "already
        # mirrored" poisoned entry. A PUT-only store (no GET) is
        # tolerated with a warning — that upload happened, it just
        # cannot be independently verified (nor serve restores).
        tmp = _tmp_name(path + ".mirror_verify")
        got = self._get_to_file(name, tmp)
        try:
            os.remove(tmp)
        except OSError:
            pass
        if got is not None and got != digest:
            _log.warning("mirror copy of %s failed verify-on-upload "
                         "(digest mismatch): unpublishing it", name)
            self.delete(name)
            return False
        sidecar = path + ".sha256"
        if os.path.exists(sidecar):
            self._retry(lambda: http_put_file(
                f"{self.base_url}/{name}.sha256", sidecar,
                timeout=self.timeout, headers=headers))
        else:
            def _put_sidecar() -> None:
                with self._request(
                        "PUT", name + ".sha256",
                        data=f"{digest}  {name}\n".encode()) as resp:
                    resp.read()
            self._retry(_put_sidecar)
        if got is None:
            _log.warning("mirror %s does not serve GET: upload of %s "
                         "is unverified", self.base_url, name)
        self._maybe_inject_corruption(name)
        return True

    def fetch(self, name: str, dest_dir: str) -> Optional[str]:
        name = _safe_name(name)
        raw = self._get_bytes(name + ".sha256")
        if raw is None:
            return None
        try:
            digest = raw.decode().split()[0]
        except (UnicodeDecodeError, IndexError):
            return None
        os.makedirs(dest_dir, exist_ok=True)
        dst = os.path.join(dest_dir, name)
        tmp = _tmp_name(dst)
        got = self._get_to_file(name, tmp)
        if got != digest:
            _log.warning("mirror copy of %s is corrupt (digest "
                         "mismatch) — not restoring it", name)
            try:
                os.remove(tmp)
            except OSError:
                pass
            return None
        os.replace(tmp, dst)
        side_tmp = _tmp_name(dst + ".sha256")
        with open(side_tmp, "w") as f:
            f.write(f"{digest}  {name}\n")
        os.replace(side_tmp, dst + ".sha256")
        return dst

    def delete(self, name: str) -> None:
        for victim in (_safe_name(name), _safe_name(name) + ".sha256"):
            try:
                with self._request("DELETE", victim) as resp:
                    resp.read()
            except (urllib.error.URLError, OSError, ValueError):
                pass

    def put_meta(self, name: str, record: Dict[str, object]) -> bool:
        try:
            with self._request("PUT", _safe_name(name),
                               data=json.dumps(record).encode()) as resp:
                resp.read()
                return resp.status == 200
        except (urllib.error.URLError, OSError, ValueError):
            return False

    def get_meta(self, name: str) -> Optional[Dict[str, object]]:
        raw = self._get_bytes(_safe_name(name))
        if raw is None:
            return None
        try:
            data = json.loads(raw)
        except (ValueError, UnicodeDecodeError):
            return None
        return data if isinstance(data, dict) else None

    def meta_names(self, prefix: str = "") -> List[str]:
        raw = self._get_bytes("?metas=1")
        if raw is None:
            return []
        try:
            names = json.loads(raw)
        except (ValueError, UnicodeDecodeError):
            return []
        if not isinstance(names, list):
            return []
        out = []
        for n in names:
            try:
                n = _safe_name(str(n))
            except ValueError:
                continue        # a hostile listing cannot smuggle paths
            if n.startswith(prefix):
                out.append(n)
        return sorted(out)

    def _corrupt(self, name: str) -> None:
        """Re-PUT a torn copy over the mirrored file (the server keeps
        whatever bytes the last PUT sent — exactly how real bit rot
        looks to a digest check). Local file and sidecar stay intact."""
        import tempfile

        from veles_tpu.http_util import http_put_file
        from veles_tpu.resilience.faults import corrupt_file
        fd, tmp = tempfile.mkstemp(prefix="mirror_corrupt_")
        os.close(fd)
        try:
            if self._get_to_file(name, tmp) is None:
                return
            corrupt_file(tmp)
            headers = {"X-Veles-Token": self.token} if self.token \
                else None
            http_put_file(f"{self.base_url}/{name}", tmp,
                          timeout=self.timeout, headers=headers)
        finally:
            try:
                os.remove(tmp)
            except OSError:
                pass


def get_mirror(spec: str, token: Optional[str] = None) -> Mirror:
    """`http(s)://...` -> HttpMirror; anything else -> DirMirror."""
    if spec.startswith(("http://", "https://")):
        return HttpMirror(spec, token=token)
    return DirMirror(spec)


def restore_missing(mirror: "Mirror | str", directory: str,
                    prefix: str = "") -> List[str]:
    """Fetch every verified mirror entry matching `prefix` that the
    local `directory` is missing (or holds corrupt) — the re-placed
    host's rejoin path. Returns the restored local paths, newest
    first."""
    if isinstance(mirror, str):
        mirror = get_mirror(mirror)
    restored: List[str] = []
    entries = sorted(mirror.entries(),
                     key=lambda e: float(e["mtime"]), reverse=True)
    for e in entries:
        name = str(e["name"])
        if prefix and not name.startswith(prefix):
            continue
        local = os.path.join(directory, name)
        if os.path.exists(local) \
                and _read_sidecar(local) == e["digest"] \
                and _sha256_file(local) == e["digest"]:
            continue        # local copy already valid
        got = mirror.fetch(name, directory)
        if got is not None:
            # preserve the mirror's ordering hint: latest() sorts by
            # mtime, and a fetched batch would otherwise all carry "now"
            try:
                os.utime(got, (float(e["mtime"]), float(e["mtime"])))
            except OSError:
                pass
            _log.warning("restored %s from mirror %s", name,
                         mirror.spec)
            restored.append(got)
    return restored


# -- loopback-testable HTTP mirror store --------------------------------------

class MirrorServer:
    """Tiny blob store speaking the HttpMirror protocol: PUT/GET/DELETE
    `/{name}` plus `GET /?index=1` (snapshot listing) and
    `GET /?metas=1` (meta-record listing). Hardened like the other control
    planes (task_queue/web_status): optional shared token via
    `X-Veles-Token` (constant-time compare), bounded bodies (413),
    sanitized flat names (400). Runs on a thread; `port=0` auto-picks —
    the loopback chaos/CI store, and a real single-box durable store
    when pointed at a separate volume."""

    def __init__(self, root: str, host: str = "127.0.0.1",
                 port: int = 0, token: Optional[str] = None,
                 max_body: int = MAX_SNAPSHOT_BODY) -> None:
        self.root = root
        self.host = host
        self.port = port
        self.token = token
        self.max_body = max_body
        self._httpd = None
        self._thread = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "MirrorServer":
        import threading
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)

        from veles_tpu.http_util import check_shared_token
        os.makedirs(self.root, exist_ok=True)
        outer = self
        token = self.token

        class Handler(BaseHTTPRequestHandler):
            def _name(self):
                name = self.path.lstrip("/").split("?")[0]
                try:
                    return _safe_name(name) if name else None
                except ValueError:
                    return None

            def _deny(self, code: int) -> None:
                self.send_response(code)
                self.end_headers()

            def do_PUT(self):  # noqa: N802 (http.server API)
                if not check_shared_token(self, token):
                    return
                name = self._name()
                if name is None:
                    return self._deny(400)
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                except ValueError:
                    return self._deny(400)
                if length > outer.max_body:
                    return self._deny(413)
                dst = os.path.join(outer.root, name)
                tmp = _tmp_name(dst)
                remaining = length
                with open(tmp, "wb") as f:
                    while remaining > 0:
                        block = self.rfile.read(min(1 << 20, remaining))
                        if not block:
                            break
                        f.write(block)
                        remaining -= len(block)
                if remaining:
                    os.remove(tmp)      # short body: do not publish
                    return self._deny(400)
                os.replace(tmp, dst)
                self._deny(200)

            def do_GET(self):  # noqa: N802
                if not check_shared_token(self, token):
                    return
                if "metas=1" in self.path:
                    # meta-record listing (the serving-fleet beacon
                    # discovery path): every non-snapshot file, the
                    # same rule DirMirror.meta_names applies locally
                    out = sorted(
                        n for n in os.listdir(outer.root)
                        if ".pickle" not in n
                        and not n.endswith((".sha256", ".tmp")))
                    body = json.dumps(out).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if "index=1" in self.path:
                    out = []
                    for n in sorted(os.listdir(outer.root)):
                        if n.endswith((".sha256", ".tmp")):
                            continue
                        digest = _read_sidecar(
                            os.path.join(outer.root, n))
                        if digest is None:
                            continue
                        out.append({
                            "name": n, "digest": digest,
                            "mtime": os.path.getmtime(
                                os.path.join(outer.root, n))})
                    body = json.dumps(out).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                name = self._name()
                if name is None:
                    return self._deny(400)
                src = os.path.join(outer.root, name)
                if not os.path.isfile(src):
                    return self._deny(404)
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/octet-stream")
                self.send_header("Content-Length",
                                 str(os.path.getsize(src)))
                self.end_headers()
                with open(src, "rb") as f:
                    shutil.copyfileobj(f, self.wfile)

            def do_DELETE(self):  # noqa: N802
                if not check_shared_token(self, token):
                    return
                name = self._name()
                if name is None:
                    return self._deny(400)
                try:
                    os.remove(os.path.join(outer.root, name))
                except OSError:
                    return self._deny(404)
                self._deny(200)

            def log_message(self, *args):
                pass

        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=lambda: self._httpd.serve_forever(poll_interval=0.05),
            daemon=True, name="mirror-server")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


def _main(argv=None) -> int:
    """`python -m veles_tpu.resilience.mirror --root DIR [--host H]
    [--port P]` — run the reference blob store standalone (the deploy/
    manifests' mirror pod; token from VELES_WEB_TOKEN)."""
    import argparse
    import signal
    import threading as _threading
    ap = argparse.ArgumentParser(
        description="veles snapshot mirror store (PUT/GET/DELETE "
                    "/{name}, GET /?index=1)")
    ap.add_argument("--root", required=True,
                    help="directory holding the mirrored blobs")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8080)
    args = ap.parse_args(argv)
    token = os.environ.get("VELES_WEB_TOKEN") or None
    if not token and args.host not in ("127.0.0.1", "localhost", "::1"):
        ap.error("a non-loopback mirror store needs a shared secret: "
                 "set VELES_WEB_TOKEN (mirrored snapshots are pickles "
                 "— see the trust model in this module's docstring)")
    srv = MirrorServer(args.root, host=args.host, port=args.port,
                       token=token).start()
    print(f"mirror store on {srv.url} (root {args.root})", flush=True)
    done = _threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: done.set())
    done.wait()
    srv.stop()
    return 0


if __name__ == "__main__":          # pragma: no cover — thin wrapper
    raise SystemExit(_main())
